"""Backend-reset helper for environments that pin a TPU platform at startup.

The surrounding environment pins ``JAX_PLATFORMS=axon`` (single-chip TPU
tunnel) and registers the backend at interpreter startup via sitecustomize,
so env vars set inside Python are too late — the only way to get a CPU (or
virtual multi-device CPU) backend is to rewrite the jax config and clear the
already-initialized backends. Shared by ``tests/conftest.py``, ``bench.py``'s
fallback path, and ``__graft_entry__.dryrun_multichip``.
"""
from typing import Optional


def force_cpu_backend(n_devices: Optional[int] = None) -> None:
    """Re-point jax at the host CPU platform, optionally with virtual devices."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    if n_devices is not None:
        try:
            jax.config.update("jax_num_cpu_devices", n_devices)
        except AttributeError:
            # jax < 0.5 predates the config option; fall back to the XLA flag.
            # CAVEAT: XLA parses XLA_FLAGS once per process, so this only
            # works if no backend has been initialized yet — verified below.
            import os
            import re

            flags = os.environ.get("XLA_FLAGS", "")
            # replace any existing count (a stale value would win at backend
            # re-init and silently hand back the wrong device count)
            flags = re.sub(r"--xla_force_host_platform_device_count=\S+", "", flags)
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={n_devices}".strip()
            )
    from jax.extend import backend as _jeb

    _jeb.clear_backends()
    if n_devices is not None and jax.device_count() < n_devices:
        raise RuntimeError(
            f"force_cpu_backend({n_devices}) took no effect: jax reports "
            f"{jax.device_count()} device(s). On jax < 0.5 the virtual-device "
            "count rides on XLA_FLAGS, which XLA reads once per process — call "
            "force_cpu_backend before anything initializes a jax backend."
        )

"""Version compatibility for the pinned container jax.

The framework (and its tests) target the current jax surface —
``jax.shard_map`` at top level, ``jax.lax.pcast`` for replicated→varying
conversion, and the ``jax_num_cpu_devices`` config option. The container
pins jax 0.4.37, which predates all three. This module back-fills them so
one code path serves both:

- ``jax.shard_map``: aliased from ``jax.experimental.shard_map`` (same
  call signature for the mesh/in_specs/out_specs keywords used here), with
  ``check_rep=False`` — 0.4.37's replication checker miscounts scan
  carries (its own error message says to disable it), and it is a static
  lint, not part of execution semantics.
- ``jax.lax.pcast``: identity. 0.4.37's shard_map does not track varying
  manual axes, so the replicated→varying cast new jax requires is a no-op
  there; the rep-checker treats replicated values as usable wherever a
  varying one is expected.
- ``jax_num_cpu_devices``: handled in ``backend.force_cpu_backend`` via
  the ``--xla_force_host_platform_device_count`` XLA flag, which the CPU
  client reads at (re)initialization — equivalent as long as it is set
  before the backend comes up (``clear_backends`` forces that).

Idempotent; imported for its side effect by ``metrics_tpu/__init__``. The
back-fill is a process-wide mutation of the ``jax`` namespace by design:
~45 call sites (library, tests, examples, bench) target the current
``jax.shard_map`` surface, and on old jax the attribute does not exist, so
nothing that feature-detects it loses a working code path — but be aware
that other libraries in the same process will also see the shim.
"""


def ensure_jax_compat() -> None:
    import jax

    if not hasattr(jax, "shard_map"):
        import functools

        from jax.experimental.shard_map import shard_map as _shard_map

        def _shard_map_compat(f=None, *args, **kwargs):
            # new jax renamed check_rep -> check_vma; accept both spellings
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            kwargs.setdefault("check_rep", False)
            if f is None:
                return functools.partial(_shard_map_compat, **kwargs)
            return _shard_map(f, *args, **kwargs)

        jax.shard_map = _shard_map_compat
    if not hasattr(jax.lax, "pcast"):
        jax.lax.pcast = lambda x, axes, to=None: x


ensure_jax_compat()

"""Core ``Metric`` runtime — TPU-first redesign of reference
``src/torchmetrics/metric.py`` (953 LoC).

Design stance (SURVEY.md §7): a metric is a **pytree of arrays + pure
functions**. The stateful ``Metric`` object is a thin host-side shell over a
pure ``update(state, *batch) -> state`` and ``compute(state) -> value``; both
are jit-compiled XLA graphs (the reference runs eager torch ops with no
compilation anywhere, reference ``metric.py:220-346``). Key differences from
the reference, by subsystem:

- **State registry** (`add_state`, reference ``metric.py:150-217``): states
  are immutable ``jax.Array`` leaves (or python lists of arrays for ``cat``
  states). "Reset" rebuilds defaults; no in-place mutation exists, so the
  reference's detach/clone defensive copies are unnecessary.
- **Compilation**: the subclass's ``update``/``compute`` bodies are traced
  once into XLA graphs via a state-swap closure and cached per input
  shape/dtype. Metrics with list (``cat``) states or host-side work (text)
  opt out with ``jittable_update/compute = False`` and still run every array
  op through XLA eagerly.
- **Forward protocol** (reference ``metric.py:220-346``): same dual
  semantics — accumulate globally AND return the batch-local value — with the
  same two strategies (``full_state_update`` True/False) selected by class
  attribute.
- **Distributed sync** (reference ``metric.py:348-498``): under ``pjit`` with
  sharded inputs, state is already globally correct (GSPMD inserts the
  collectives), so sync is the identity. Across *processes* (multi-host), the
  sync/unsync/sync_context lifecycle exists with identical semantics, but
  rides ``multihost_utils`` instead of NCCL (see
  ``metrics_tpu/parallel/sync.py``). Inside ``shard_map``, use the pure
  functional API with ``axis_name`` (``metrics_tpu.pure``).
- **Serialization** (reference ``metric.py:654-692``): state is a pytree —
  ``state_dict`` returns numpy copies; orbax/flax checkpointing works on the
  same pytree for free.
"""
import contextlib
import functools
import inspect
import threading
import time
from copy import deepcopy
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.analysis.lockwitness import named_lock
from metrics_tpu.obs import trace as _obs_trace
from metrics_tpu.obs.runtime_metrics import note_jit_retrace as _note_jit_retrace
from metrics_tpu.parallel.sync import distributed_available, gather_all_arrays, sync_state
from metrics_tpu.utilities.data import _flatten, _squeeze_if_scalar, dim_zero_cat
from metrics_tpu.utilities.exceptions import MetricsTPUUserError
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array
Reduction = Union[str, Callable, None]

# Errors meaning "this update body needs concrete values → run it eagerly".
_TRACE_ERRORS = tuple(
    getattr(jax.errors, name)
    for name in (
        "ConcretizationTypeError",
        "TracerArrayConversionError",
        "TracerBoolConversionError",
        "TracerIntegerConversionError",
        # boolean-indexing with a traced mask (e.g. the negative-ignore_index
        # row drop) is the same "needs concrete values" family, but subclasses
        # JAXIndexError, not ConcretizationTypeError
        "NonConcreteBooleanIndexError",
    )
    if hasattr(jax.errors, name)
)


def jit_distributed_available() -> bool:
    """Reference ``metric.py:40-41``."""
    return distributed_available()


# sentinel: "the overlapped scheduler has no completed cycle yet" — distinct
# from any legal metric value (None is a legal compute result)
_NO_SYNC_VIEW = object()


def _migrate_fault_vectors(state: Dict[str, Any]) -> Dict[str, Any]:
    """Zero-pad fault-class vectors from builds with fewer fault classes up
    to the current ``NUM_FAULT_CLASSES`` (the appends-only contract —
    ``utilities/guard.py::FAULT_CLASSES``): ``FaultCounters`` leaves, and
    the streaming wrappers' RAW per-bucket/decayed fault rings (state keys
    ``win___faults``/``dec___faults``, plain class-trailing arrays). The
    checkpoint loaders migrate through ``_validated_state_value``; this
    covers the pickle path, where the pytrees are rebuilt leaf-for-leaf."""
    from metrics_tpu.utilities.guard import NUM_FAULT_CLASSES, FaultCounters

    def fix(k: str, v: Any) -> Any:
        if isinstance(v, FaultCounters) and v.counts.shape[0] < NUM_FAULT_CLASSES:
            pad = jnp.zeros((NUM_FAULT_CLASSES - v.counts.shape[0],), v.counts.dtype)
            return FaultCounters(counts=jnp.concatenate([v.counts, pad]))
        if (
            k.endswith("___faults")
            and getattr(v, "ndim", 0) >= 1
            and v.shape[-1] < NUM_FAULT_CLASSES
        ):
            pad = jnp.zeros(v.shape[:-1] + (NUM_FAULT_CLASSES - v.shape[-1],), v.dtype)
            return jnp.concatenate([v, pad], axis=-1)
        return v

    return {k: fix(k, v) for k, v in state.items()}


class Metric:
    """Base class for all metrics (reference ``metric.py:44``).

    Not an ``nn.Module``: JAX has no module system to inherit device/dtype
    handling from, and none is needed — state lives wherever XLA put it and
    moves with shardings, not ``.to()`` calls.
    """

    __jit_unwrapped__ = True

    # class-constant behavior flags (reference ``metric.py:75-77``)
    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    # compilation opt-outs (no reference analogue; the reference never compiles)
    jittable_update: bool = True
    jittable_compute: bool = True

    # data-inferred python attributes (e.g. an input-mode enum resolved at
    # the first update) that a crash-recovery snapshot must carry so a
    # fresh instance can compute() right after restore — subclasses that
    # infer config from data declare the attribute names here
    # (resilience/snapshot.py; values must pickle and be cheap to repr)
    _snapshot_attrs: Sequence[str] = ()

    # how this metric's CatBuffer ring states overflow together: False =
    # paired rings filled in lockstep (preds/target — dropped rows are the
    # SAME samples, count once via max); True = rings filled independently
    # (FID/KID real vs fake — drops add up)
    _independent_ring_drops: bool = False

    def __init__(
        self,
        compute_on_cpu: bool = False,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        sync_on_compute: bool = True,
        on_overflow: str = "warn",
        on_invalid: str = "ignore",
        debug_checks: bool = False,
        pad_batches: bool = False,
        sync_mode: str = "blocking",
        sync_every_n: Optional[int] = None,
        sync_every_s: Optional[float] = None,
        sync_transport: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        from metrics_tpu.utilities.guard import VALID_POLICIES, FaultCounters

        # kwargs popped like reference ``metric.py:91-109``
        object.__setattr__(self, "_state", {})
        object.__setattr__(self, "_defaults", {})
        object.__setattr__(self, "_reductions", {})
        object.__setattr__(self, "_persistent", {})
        self.compute_on_cpu = compute_on_cpu
        self.dist_sync_on_step = dist_sync_on_step
        self.process_group = process_group
        self.dist_sync_fn = dist_sync_fn
        self.sync_on_compute = sync_on_compute
        if on_overflow not in ("warn", "error", "ignore"):
            raise ValueError(f"`on_overflow` must be 'warn', 'error' or 'ignore', got {on_overflow!r}")
        self.on_overflow = on_overflow
        if on_invalid not in VALID_POLICIES:
            raise ValueError(f"`on_invalid` must be one of {VALID_POLICIES}, got {on_invalid!r}")
        self.on_invalid = on_invalid
        self.debug_checks = debug_checks
        # serving hardening (ops/padding.py): pad every update batch up to a
        # ladder tier so ragged traffic compiles at most len(ladder) graphs;
        # pad rows are masked through the `valid` machinery and counted in
        # the fault channel's informational `padded_rows` class
        self.pad_batches = bool(pad_batches)
        # overlapped async sync (parallel/async_sync.py): double-buffer the
        # reduced state — collectives issue eagerly at update time against a
        # snapshot while the live accumulator keeps absorbing updates, so
        # compute() reads an already-reduced, at-most-one-cycle-stale view
        # with ZERO collective latency; compute(fresh=True) escapes back to
        # the blocking fused sync
        if sync_mode not in ("blocking", "overlapped"):
            raise ValueError(
                f"`sync_mode` must be 'blocking' or 'overlapped', got {sync_mode!r}"
            )
        self.sync_mode = sync_mode
        if sync_mode == "overlapped":
            from metrics_tpu.parallel.async_sync import resolve_sync_cadence

            self.sync_every_n, self.sync_every_s = resolve_sync_cadence(
                sync_every_n, sync_every_s
            )
            # one lock guards every _state swap window (update commit,
            # blocking-sync swap, overlapped-view read, snapshot_state) so
            # the scheduler's background snapshot can never capture a torn
            # mid-swap state — and crash snapshots stay consistent
            # hot=False for the witness: device work under this lock IS the
            # designed swap-window contract
            object.__setattr__(
                self, "_overlap_lock", named_lock("metric._overlap_lock", threading.RLock())
            )
        else:
            if sync_every_n is not None or sync_every_s is not None:
                raise ValueError(
                    "`sync_every_n`/`sync_every_s` need sync_mode='overlapped'"
                )
            self.sync_every_n = None
            self.sync_every_s = None
        # quantized sync transport (ops/quantize.py): the wire codec the
        # OVERLAPPED cycle ships float state through — readers consume an
        # at-most-one-cycle-stale view anyway, so compressed cycles trade
        # precision nobody reads at full width for DCN bandwidth, within
        # the codec's documented per-block error envelope. Blocking syncs
        # (and compute(fresh=True)) are ALWAYS exact; None resolves
        # METRICS_TPU_SYNC_TRANSPORT > 'exact' per cycle.
        from metrics_tpu.ops.quantize import validate_transport

        validate_transport(sync_transport)
        if sync_transport not in (None, "exact") and sync_mode != "overlapped":
            raise ValueError(
                "`sync_transport` needs sync_mode='overlapped' (the blocking "
                "sync path is always exact)"
            )
        self.sync_transport = sync_transport
        object.__setattr__(self, "_sync_scheduler", None)
        # set by MetricCollection._ensure_overlap_scheduler: which head's
        # entry of a collection-shared view this metric reads
        object.__setattr__(self, "_sync_view_key", None)
        self._faults_reported = 0
        if on_invalid != "ignore" or self.pad_batches:
            # the in-graph fault channel: per-class uint32 counters carried
            # as ordinary sum-reduced metric state (see utilities/guard.py);
            # padding rides it too so padded_rows merge/sync/snapshot for free
            self.add_state("_faults", default=FaultCounters.zeros(), dist_reduce_fx="sum")
        if kwargs:
            raise ValueError(f"Unexpected keyword arguments: {list(kwargs)}")

        self._update_count = 0
        self._update_called = False
        # staleness channel (resilience/health.py): wall-clock + step of the
        # most recent update, so a stalled stream is visible in health_report
        self._last_update_unix: Optional[float] = None
        self._computed: Any = None
        self._forward_cache: Any = None
        self._is_synced = False
        self._cache: Optional[Dict[str, Any]] = None
        self._to_sync = True
        self._should_unsync = True
        self._enable_grad = False

        # wrap the subclass's update/compute (reference ``metric.py:113-114``)
        self._original_update = self._maybe_guard(self.update)
        self._original_compute = self.compute
        object.__setattr__(self, "update", self._wrap_update(self._original_update))
        object.__setattr__(self, "compute", self._wrap_compute(self._original_compute))
        self._update_jit: Optional[Callable] = None
        self._compute_jit: Optional[Callable] = None
        self._update_signature = inspect.signature(self._original_update)

    # ------------------------------------------------------------------
    # state registry
    # ------------------------------------------------------------------

    def add_state(
        self,
        name: str,
        default: Union[Array, list],
        dist_reduce_fx: Reduction = None,
        persistent: bool = False,
        template: Optional[Array] = None,
    ) -> None:
        """Register a named state leaf (reference ``metric.py:150-217``).

        ``default`` is either an array (fixed-shape accumulator) or an empty
        list (a ``cat`` state — batches appended, concatenated lazily).

        ``template`` (list states only) is an empty ``(0, *row)`` array
        declaring the entries' dtype/trailing shape, so a sync of an
        *empty* list state can gather with the declared dtype instead of
        collapsing to float32 ``(0,)`` (see ``parallel/sync.py``). Passing
        an explicit ``template=None`` declares the rows RAGGED (data-
        dependent trailing shape — e.g. whole image batches): no static
        template exists, and the graft-lint state-discipline rule (GL302,
        ``metrics_tpu/analysis``) treats the explicit ``None`` as that
        declaration while flagging list states that omit the kwarg.
        """
        from metrics_tpu.utilities.guard import FaultCounters
        from metrics_tpu.utilities.ringbuffer import CatBuffer

        if isinstance(default, (CatBuffer, FaultCounters)) or getattr(
            type(default), "is_sketch_state", False
        ):
            pass  # static-shape pytree states (jittable cat / fault counters /
            #       mergeable sketches — see metrics_tpu/streaming/sketches.py)
        elif not isinstance(default, list) or default:
            if not isinstance(default, (jax.Array, np.ndarray, int, float)):
                raise ValueError("state variable must be an array, a CatBuffer, or an empty list (any value)")
            default = jnp.asarray(default)
        if dist_reduce_fx not in ("sum", "mean", "cat", "max", "min", None) and not callable(dist_reduce_fx):
            raise ValueError("`dist_reduce_fx` must be callable or one of ['mean', 'sum', 'cat', 'min', 'max', None]")
        if template is not None:
            if not isinstance(default, list):
                raise ValueError("`template` is only meaningful for list ('cat') states")
            self.__dict__.setdefault("_list_templates", {})[name] = jnp.asarray(template)
        self._defaults[name] = deepcopy(default) if isinstance(default, list) else default
        self._reductions[name] = dist_reduce_fx
        self._persistent[name] = persistent
        self._state[name] = [] if isinstance(default, list) else default

    def _sync_defaults(self) -> Dict[str, Any]:
        """Defaults for the sync layer: list-state defaults are replaced by
        their registered dtype/shape ``template`` (when one exists), so
        ``sync_state``/``fused_sync`` can gather empty list states with the
        declared dtype instead of the legacy float32 ``(0,)``."""
        out = dict(self._defaults)
        for name, tpl in self.__dict__.get("_list_templates", {}).items():
            out[name] = tpl
        return out

    # attribute routing so subclass code can write ``self.tp += x``
    def __setattr__(self, name: str, value: Any) -> None:
        defaults = self.__dict__.get("_defaults")
        if defaults is not None and name in defaults:
            self.__dict__["_state"][name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str) -> Any:
        # only called when normal lookup fails
        defaults = self.__dict__.get("_defaults")
        if defaults is not None and name in defaults:
            return self.__dict__["_state"][name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    @property
    def metric_state(self) -> Dict[str, Any]:
        """Current state pytree (read-only view)."""
        return dict(self._state)

    @property
    def update_called(self) -> bool:
        return self._update_called

    @property
    def update_count(self) -> int:
        return self._update_count

    # ------------------------------------------------------------------
    # update / compute wrapping (reference ``metric.py:376-399,500-528``)
    # ------------------------------------------------------------------

    def _can_jit_update(self) -> bool:
        if not self.jittable_update:
            return False
        return not any(isinstance(d, list) for d in self._defaults.values())

    def _can_jit_compute(self) -> bool:
        if not self.jittable_compute:
            return False
        return not any(isinstance(d, list) for d in self._defaults.values())

    def _maybe_guard(self, update: Callable) -> Callable:
        """Wrap the raw update body with the in-graph fault channel.

        Counting/masking happens *inside* whatever traces this body —
        the module runtime's own jit, ``functionalize``, or a user's
        ``shard_map`` — so faults are detected on the compiled path the
        concrete-only checks in ``utilities/checks.py`` cannot see.
        Attribute reads are lazy: subclass ``__init__`` sets ``num_classes``
        / ``capacity`` / ``threshold`` after this wrapper is built.
        """

        if self.on_invalid == "ignore":
            return update  # guard compiled out entirely — zero overhead

        @functools.wraps(update)
        def guarded(*args: Any, **kwargs: Any) -> None:
            from metrics_tpu.utilities.guard import guard_update_args

            args, kwargs, counters = guard_update_args(self, args, kwargs)
            self._faults = self._faults + counters
            return update(*args, **kwargs)

        return guarded

    def _make_update_jit(self) -> Callable:
        def pure_update(state: Dict[str, Any], args: tuple, kwargs: dict) -> Dict[str, Any]:
            # trace-TIME counter + instant, not a graph op: this body runs
            # once per (re)trace, so the count IS the retrace count
            # (audit_recompilation's idiom as live telemetry — the
            # metric_jit_retrace_total counter increments tracing on or off,
            # the timeline instant rides when the tracer records); the
            # instrumented_update_step registry entry proves the compiled
            # graph stays free of host callbacks
            _note_jit_retrace(metric=type(self).__name__, fn="update")
            prev = self.__dict__["_state"]
            object.__setattr__(self, "_state", dict(state))
            try:
                self._original_update(*args, **kwargs)
                return dict(self.__dict__["_state"])
            finally:
                object.__setattr__(self, "_state", prev)

        if not self.debug_checks:
            return jax.jit(pure_update)

        # strict mode: trap in-graph NaN/inf *production* and bad gathers,
        # not just faulty inputs — the errors surface at this (eager) call
        # site instead of silently poisoning the accumulators
        from jax.experimental import checkify

        checked = jax.jit(checkify.checkify(pure_update, errors=checkify.float_checks))

        def run_checked(state: Dict[str, Any], args: tuple, kwargs: dict) -> Dict[str, Any]:
            err, out = checked(state, args, kwargs)
            checkify.check_error(err)
            return out

        return run_checked

    def _make_compute_jit(self) -> Callable:
        def pure_compute(state: Dict[str, Any]) -> Any:
            # trace-time retrace counter + instant (see _make_update_jit)
            _note_jit_retrace(metric=type(self).__name__, fn="compute")
            prev = self.__dict__["_state"]
            object.__setattr__(self, "_state", dict(state))
            try:
                return self._original_compute()
            finally:
                object.__setattr__(self, "_state", prev)

        return jax.jit(pure_compute)

    def _state_swap_guard(self):
        """The overlapped-sync swap lock (a no-op context for blocking
        metrics): held around every window where ``_state`` is mutated or
        temporarily swapped, so the async scheduler's background snapshot —
        and a crash snapshot — can never observe a torn mid-swap state."""
        lock = self.__dict__.get("_overlap_lock")
        return lock if lock is not None else contextlib.nullcontext()

    def _wrap_update(self, update: Callable) -> Callable:
        @functools.wraps(update)
        def wrapped_func(*args: Any, **kwargs: Any) -> None:
            with _obs_trace.span("metric.update", metric=type(self).__name__):
                with self._state_swap_guard():
                    self._run_update(update, args, kwargs)
            if self.sync_mode == "overlapped":
                # eager issue: the scheduler snapshots the just-committed
                # state and runs the collective on its worker thread while
                # this thread moves on to the next batch (the T3 overlap)
                self._ensure_sync_scheduler().notify(steps=self._update_count)

        return wrapped_func

    def _run_update(self, update: Callable, args: tuple, kwargs: dict) -> None:
        self._computed = None
        self._update_count += 1
        self._update_called = True
        self._last_update_unix = time.time()
        if self._is_synced:
            raise MetricsTPUUserError(
                "The Metric shouldn't be synced when performing ``update``. "
                "HINT: Did you forget to call ``unsync``?"
            )
        n_padded = 0
        if self.pad_batches:
            # pad OUTSIDE the jit boundary: the compiled update only ever
            # sees ladder-tier shapes, so ragged traffic reuses graphs
            from metrics_tpu.ops.padding import pad_update_args

            args, kwargs, n_padded = pad_update_args(self, args, kwargs)
        if self._can_jit_update() and not self.compute_on_cpu:
            if self._update_jit is None:
                self._update_jit = self._make_update_jit()
            # the profiler's live join (obs/profile.py): per-tier dispatch
            # wall of the jitted update — priced only while tracing is on,
            # so the default request path gains one amortized env read
            tap_t0 = time.perf_counter() if _obs_trace.tracing_enabled() else None
            try:
                new_state = self._update_jit(dict(self._state), args, kwargs)
            except (_TRACE_ERRORS + (TypeError,)):
                # update body needs concrete values, or takes non-array
                # args jit can't stage → fall back to eager (a genuine
                # bug will re-raise from the eager call below)
                object.__setattr__(self, "jittable_update", False)
                update(*args, **kwargs)
            else:
                object.__setattr__(self, "_state", new_state)
                if tap_t0 is not None and getattr(self._update_jit, "_tap_kind", None) is None:
                    # an AOTDispatcher slot carries its own (serve_aot_update) tap
                    from metrics_tpu.obs.runtime_metrics import observe_jit_wall
                    from metrics_tpu.ops.padding import leading_rows

                    # per-tier attribution only when the row count IS a
                    # ladder tier (pad_batches) — unpadded ragged traffic
                    # would mint one never-evicted histogram per distinct
                    # batch size, bloating every scrape without bound
                    rows = leading_rows(args) if self.pad_batches else None
                    observe_jit_wall(
                        "metric_update_jit", rows, (time.perf_counter() - tap_t0) * 1e3
                    )
        else:
            update(*args, **kwargs)
        if n_padded:
            # the pad count is static (a shape delta), so it accumulates
            # with one tiny eager add instead of riding the jitted graph
            from metrics_tpu.utilities.guard import FaultCounters

            self._state["_faults"] = self._state["_faults"] + FaultCounters.single(
                padded_rows=n_padded
            )
        if self.compute_on_cpu:
            self._move_list_states_to_host()

    def _move_list_states_to_host(self) -> None:
        """Offload accumulated list ("cat") states to host memory.

        The reference's ``compute_on_cpu`` (``metric.py:91,396-406``) moves
        list states to CPU after each update so unbounded concat states don't
        exhaust accelerator memory. Entries become host numpy arrays here,
        and the final compute runs on the CPU backend too
        (:meth:`_compute_on_cpu_device`).
        """
        for name, value in self._state.items():
            if isinstance(value, list):
                self._state[name] = [np.asarray(v) if isinstance(v, jax.Array) else v for v in value]

    def _wrap_compute(self, compute: Callable) -> Callable:
        @functools.wraps(compute)
        def wrapped_func(*args: Any, **kwargs: Any) -> Any:
            # `fresh=True` is the overlapped-sync escape hatch: skip the
            # double-buffered view and pay today's blocking fused sync for a
            # value covering every local update (a no-op for blocking-mode
            # metrics, which are always "fresh")
            fresh = bool(kwargs.pop("fresh", False))
            if not self._update_called:
                rank_zero_warn(
                    f"The ``compute`` method of metric {type(self).__name__} was called before the ``update`` "
                    "method which may lead to errors, as metric states have not yet been updated.",
                    UserWarning,
                )
            if (
                self.sync_mode == "overlapped"
                and not fresh
                and self._to_sync
                and self.sync_on_compute
                and not self._is_synced
                # forward-protocol internal computes (batch-local values on a
                # freshly-reset state) must never serve the accumulated view
                and self._should_unsync
            ):
                value = self._overlapped_read(*args, **kwargs)
                if value is not _NO_SYNC_VIEW:
                    return value
                # no completed cycle yet: kick one so later reads are
                # covered, and fall through to the blocking path below
                self._ensure_sync_scheduler().request()
            if self._computed is not None:
                return self._computed  # cache (reference ``metric.py:512``)
            with self._state_swap_guard():
                with self.sync_context(
                    dist_sync_fn=self.dist_sync_fn,
                    should_sync=self._to_sync and self.sync_on_compute,
                    should_unsync=self._should_unsync,
                ):
                    value = self._compute_unsynced(*args, **kwargs)
                    # checked while synced: `dropped`/fault counters are then
                    # the global (summed) counts, so every rank takes the
                    # same warn/error branch
                    self._check_cat_overflow()
                    self._check_faults()
                self._computed = _squeeze_if_scalar(value)
            return self._computed

        @functools.wraps(compute)
        def traced_compute(*args: Any, **kwargs: Any) -> Any:
            # one span over the whole read path — cache hit, overlapped view
            # swap, or blocking sync+compute alike (the sync leg additionally
            # carries its own metric.sync_dist span)
            with _obs_trace.span("metric.compute", metric=type(self).__name__):
                return wrapped_func(*args, **kwargs)

        return traced_compute

    # ------------------------------------------------------------------
    # overlapped async sync (parallel/async_sync.py)
    # ------------------------------------------------------------------

    def _ensure_sync_scheduler(self):
        """Lazily build this metric's :class:`AsyncSyncScheduler` (threads
        must not outlive clones: deepcopy/pickle drop the scheduler and the
        copy rebuilds its own on first use)."""
        sched = self.__dict__.get("_sync_scheduler")
        if sched is None:
            from metrics_tpu.parallel.async_sync import AsyncSyncScheduler
            from metrics_tpu.resilience.health import record_degradation

            name = type(self).__name__

            def on_error(err: BaseException) -> None:
                # a failed cycle keeps the previous view: loudly stale (the
                # event is the loudness), never a hang; cadence retries
                record_degradation(
                    "async_sync_error",
                    f"overlapped sync cycle for {name} raised "
                    f"{type(err).__name__}: {err}",
                    metric=name,
                )

            sched = AsyncSyncScheduler(
                snapshot_fn=self._overlap_snapshot,
                reduce_fn=self._overlap_reduce,
                sync_every_n=self.sync_every_n,
                sync_every_s=self.sync_every_s,
                on_error=on_error,
                name=name,
            )
            object.__setattr__(self, "_sync_scheduler", sched)
        return sched

    def _overlap_snapshot(self):
        """Worker-side capture of the live state (the cycle's snapshot
        buffer). The swap guard makes it impossible to catch a blocking
        sync's temporary global state or a half-committed eager update."""
        with self._state_swap_guard():
            return self._copy_state(), self._update_count

    def _overlap_reduce(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """The cycle's collective: the SAME gather+reduce the blocking path
        runs (so an overlapped read is bit-identical to a blocking read over
        the batches its cycle covers), applied to the snapshot buffer on the
        scheduler thread. Single-process worlds reduce to the identity —
        the view is then just a consistent copy of the live state.

        With a non-``exact`` ``sync_transport`` (ctor arg >
        ``METRICS_TPU_SYNC_TRANSPORT`` > exact, resolved per cycle) the
        per-leaf gathers ship blockwise-quantized wire instead of raw f32
        — integer/counter leaves and small scalars always bypass
        (``ops/quantize.py::wrap_gather_transport``), and the overlapped
        read then bit-equals the blocking read only up to the codec's
        documented error envelope (``compute(fresh=True)`` stays exact)."""
        if not distributed_available():
            return state
        gather = self.dist_sync_fn or gather_all_arrays
        from metrics_tpu.ops.quantize import resolve_codec, wrap_gather_transport

        gather = wrap_gather_transport(gather, resolve_codec(self.sync_transport))
        return self._gathered_state(state, gather, self.process_group)

    def _overlapped_read(self, *args: Any, **kwargs: Any) -> Any:
        """Zero-collective read path: compute on the scheduler's front
        buffer (already reduced, at most one cycle stale). Returns the
        ``_NO_SYNC_VIEW`` sentinel before the first completed cycle."""
        sched = self.__dict__.get("_sync_scheduler")
        view = sched.view() if sched is not None else None
        if view is None:
            return _NO_SYNC_VIEW
        payload = view.payload
        key = self.__dict__.get("_sync_view_key")
        if key is not None:
            # collection-shared scheduler: the payload maps each compute-
            # group head's name to its (synced state, covered steps) entry
            entry = payload.get(key)
            if entry is None:
                return _NO_SYNC_VIEW
            payload = entry[0]
        with self._state_swap_guard():
            prev_state = self.__dict__["_state"]
            prev_synced = self._is_synced
            object.__setattr__(self, "_state", dict(payload))
            self._is_synced = True  # the view IS the globally-reduced state
            try:
                value = self._compute_unsynced(*args, **kwargs)
                # policy checks run against the view's (global) counters —
                # same stance as the blocking path's checked-while-synced
                self._check_cat_overflow()
                self._check_faults()
            finally:
                object.__setattr__(self, "_state", prev_state)
                self._is_synced = prev_synced
        return _squeeze_if_scalar(value)

    def request_sync(self, wait: bool = False, deadline_s: float = 30.0) -> bool:
        """Ask the overlapped scheduler for a cycle now. ``wait=True``
        blocks (bounded) until the front view covers every update made so
        far; returns whether it does. Blocking-mode metrics return True
        (every read is already fresh)."""
        if self.sync_mode != "overlapped":
            return True
        sched = self._ensure_sync_scheduler()
        target = sched.seq()
        if not wait:
            sched.request()
            return sched.covered(target)
        return sched.wait_covered(target, deadline_s)

    @property
    def sync_lag(self) -> Optional[Dict[str, Any]]:
        """Staleness of the overlapped view vs the live accumulator
        (``sync_lag_steps``/``sync_lag_s`` — surfaced per metric by
        ``health_report()``). None for blocking-mode metrics."""
        if self.sync_mode != "overlapped":
            return None
        sched = self.__dict__.get("_sync_scheduler")
        if sched is None:
            return {
                "sync_lag_steps": self._update_count,
                "sync_lag_s": None,
                "synced_once": False,
                "in_flight": False,
            }
        key = self.__dict__.get("_sync_view_key")
        if key is not None:
            # collection-shared scheduler: lag in THIS metric's update steps
            # comes from its group head's entry, not the collection-wide
            # notify watermark (whose unit is head-updates across groups)
            base = sched.lag(live_steps=self._update_count)
            view = sched.view()
            entry = view.payload.get(key) if view is not None else None
            if entry is None:
                return {**base, "sync_lag_steps": self._update_count,
                        "sync_lag_s": None, "synced_once": False}
            return {**base, "sync_lag_steps": max(0, self._update_count - entry[1])}
        return sched.lag(live_steps=self._update_count)

    @property
    def dropped_count(self) -> Optional[int]:
        """Rows dropped by capacity-bounded (``CatBuffer``) states.

        The max over this metric's ring states when they fill in lockstep
        (preds/target rings drop the same samples — max = samples lost), the
        SUM when the class declares ``_independent_ring_drops`` (FID/KID
        real vs fake rings overflow separately). ``0`` when nothing
        overflowed or no ring states exist; ``None`` when states are traced
        (inside jit) and the count cannot be concretized — use
        ``MetricDef.dropped`` from :func:`metrics_tpu.functionalize` for the
        in-graph signal.
        """
        from metrics_tpu.utilities.ringbuffer import CatBuffer

        counts = []
        for v in self._state.values():
            if isinstance(v, CatBuffer) and v.dropped is not None:
                try:
                    counts.append(int(v.dropped))
                except _TRACE_ERRORS:
                    return None
        if not counts:
            return 0
        return sum(counts) if self._independent_ring_drops else max(counts)

    def _check_cat_overflow(self) -> None:
        """Overflow is never silent: warn (default) or raise at compute when
        a capacity-mode state dropped rows (``on_overflow='ignore'`` opts out)."""
        if self.on_overflow == "ignore":
            return
        n = self.dropped_count
        if not n:  # 0 = no overflow; None = traced (checked by the eager caller)
            return
        msg = (
            f"{type(self).__name__}: {n} sample rows exceeded the configured `capacity` and were "
            "dropped; the computed value ignores them. Increase `capacity`, use the binned variant, "
            "or pass `on_overflow='ignore'` to silence this."
        )
        if self.on_overflow == "error":
            raise MetricsTPUUserError(msg)
        rank_zero_warn(msg, UserWarning)

    @property
    def fault_counts(self) -> Optional[Dict[str, int]]:
        """Per-class fault counts from the in-graph channel, as a dict keyed
        by ``guard.FAULT_CLASSES`` name. ``None`` when the guard is off
        (``on_invalid='ignore'``) or the state is traced — inside compiled
        code consume ``MetricDef.faults`` from :func:`metrics_tpu.functionalize`
        instead (the traced, psum'd form of this signal)."""
        fc = self._state.get("_faults")
        if fc is None:
            return None
        try:
            return fc.as_dict()
        except _TRACE_ERRORS:
            return None

    def _check_faults(self) -> None:
        """The eager boundary of the fault channel: ``on_invalid='warn'`` /
        ``'error'`` fire here from the (post-sync, globally summed) in-graph
        counters; a NaN state-leaf scan rounds out the ``nonfinite_state``
        class. ``drop`` already degraded in-graph and stays silent —
        inspect :attr:`fault_counts` to observe what was masked."""
        if self.on_invalid in ("ignore", "drop"):
            return
        from metrics_tpu.utilities.guard import _IDX, nan_state_leaves

        fc = self._state.get("_faults")
        if fc is None:
            return
        try:
            counts = np.asarray(fc.counts).astype(np.int64)
        except _TRACE_ERRORS:
            return  # traced compute: the caller consumes MetricDef.faults
        counts[_IDX["nonfinite_state"]] += nan_state_leaves(
            {k: v for k, v in self._state.items() if k != "_faults"}
        )
        # informational classes (padded_rows) record normal operation and
        # never trip the warn/error policies
        from metrics_tpu.utilities.guard import actionable_fault_total, format_fault_report

        total = actionable_fault_total(counts)

        if self.on_invalid == "error":
            # no warn-once watermark for errors: poisoned accumulators must
            # keep raising until the state is actually reset
            if total > 0:
                raise MetricsTPUUserError(format_fault_report(counts, type(self).__name__))
            return
        if total <= self._faults_reported:
            return
        self._faults_reported = total
        rank_zero_warn(format_fault_report(counts, type(self).__name__), UserWarning)

    def report_faults(self) -> None:
        """Public eager boundary for ``sync()``-without-``compute()`` users:
        apply the ``on_invalid`` policy to the current (ideally synced)
        counters immediately."""
        self._check_faults()

    def _compute_unsynced(self, *args: Any, **kwargs: Any) -> Any:
        if self.compute_on_cpu:
            return self._compute_on_cpu_device(*args, **kwargs)
        if self._can_jit_compute() and not args and not kwargs:
            if self._compute_jit is None:
                self._compute_jit = self._make_compute_jit()
            try:
                return self._compute_jit(dict(self._state))
            except _TRACE_ERRORS:
                object.__setattr__(self, "jittable_compute", False)
        return self._original_compute(*args, **kwargs)

    def _compute_on_cpu_device(self, *args: Any, **kwargs: Any) -> Any:
        """The reference's full ``compute_on_cpu`` contract
        (``metric.py:91,396-406``): not just state offload — the final
        compute itself runs on the host CPU backend, so a gathered cat state
        larger than accelerator memory still computes. Every state leaf is
        pulled to host, then the eager compute executes under the CPU
        default device; the result is CPU-resident."""
        cpu = jax.devices("cpu")[0]

        def to_host(v: Any) -> Any:
            # tree_map handles lists and CatBuffers alike
            return jax.tree_util.tree_map(
                lambda leaf: np.asarray(leaf) if isinstance(leaf, jax.Array) else leaf, v
            )

        object.__setattr__(self, "_state", {k: to_host(v) for k, v in self._state.items()})
        with jax.default_device(cpu):
            return self._original_compute(*args, **kwargs)

    # ------------------------------------------------------------------
    # forward protocol (reference ``metric.py:220-346``)
    # ------------------------------------------------------------------

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Accumulate into global state AND return the batch-local value.
        The batch value is kept in ``_forward_cache`` (reference
        ``metric.py:238``; Lightning reads it) until the next ``reset``.

        The whole save/reset/update/restore dance runs under the overlapped
        swap guard (re-entrant: the inner update/compute re-acquire it), so
        an async sync cycle can never snapshot one of the protocol's
        transient states (a reset or batch-only accumulator) as if it were
        the live stream."""
        with self._state_swap_guard():
            if self.full_state_update or self.dist_sync_on_step:
                batch_val = self._forward_full_state_update(*args, **kwargs)
            else:
                batch_val = self._forward_reduce_state_update(*args, **kwargs)
            self._forward_cache = batch_val
        return batch_val

    def _forward_full_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """Two update calls; batch value from a fresh state (reference ``metric.py:241-280``).

        Unlike the reference, the save/restore recurses into child metrics
        (wrappers like MinMax/Classwise/BootStrapper hold their state in
        children), so the second ``update`` never double-counts into a
        child's accumulated state.
        """
        self.update(*args, **kwargs)
        self._to_sync = self.dist_sync_on_step
        cache = self._deep_copy_state()
        self._deep_reset()
        self.update(*args, **kwargs)
        self._should_unsync = False
        reported = self._faults_reported
        try:
            batch_val = self.compute()
        finally:
            # restore global state (self + children) even when compute
            # raises (on_overflow/on_invalid='error'): the epoch's
            # accumulation and the sync flags must survive the exception.
            # The fault-warn watermark is batch-scoped inside this compute —
            # restore it too, or a large first batch would suppress warnings
            # for every smaller later batch
            self._deep_restore(cache)
            self._faults_reported = reported
            self._should_unsync = True
            self._to_sync = True
            self._computed = None
            self._is_synced = False
        return batch_val

    def _forward_reduce_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """One update on a reset state, then merge into the global state
        (reference ``metric.py:282-346``); snapshot/merge recurse into child
        metrics (see :meth:`_forward_full_state_update`)."""
        global_snap = self._deep_copy_state()
        self._deep_reset()
        self.update(*args, **kwargs)
        self._to_sync = self.dist_sync_on_step
        self._should_unsync = False
        reported = self._faults_reported
        try:
            batch_val = self.compute()
        finally:
            # merge batch state into global state (reference ``metric.py:319``)
            # even when compute raises (on_overflow/on_invalid='error'): the
            # accumulated stream — including this batch and its fault
            # counters — and the sync flags must survive the exception. The
            # fault-warn watermark was batch-scoped inside this compute:
            # restore it so per-batch warnings stay order-independent
            self._faults_reported = reported
            self._deep_merge(global_snap)
            self._should_unsync = True
            self._to_sync = True
            self._computed = None
            self._is_synced = False
        return batch_val

    # ------------------------------------------------------------------
    # recursive state snapshots over child metrics (no reference analogue:
    # the reference restores own states only, silently double-updating
    # wrapper children driven through forward)
    # ------------------------------------------------------------------

    def _child_metrics(self):
        for key, v in self.__dict__.items():
            if key in ("metric_a", "metric_b") and isinstance(self, CompositionalMetric):
                continue  # CompositionalMetric overrides forward entirely
            if isinstance(v, Metric):
                yield v
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, Metric):
                        yield x

    def _deep_copy_state(self):
        return (
            self._copy_state(),
            self._update_count,
            [c._deep_copy_state() for c in self._child_metrics()],
        )

    def _deep_restore(self, snapshot) -> None:
        state, count, children = snapshot
        object.__setattr__(self, "_state", state)
        self._update_count = count
        self._computed = None
        for c, cs in zip(self._child_metrics(), children):
            c._deep_restore(cs)

    def _deep_reset(self) -> None:
        self._restore_defaults()
        self._update_count = 0
        self._computed = None
        for c in self._child_metrics():
            c._deep_reset()

    def _deep_merge(self, global_snap) -> None:
        g_state, g_count, g_children = global_snap
        merged = self._reduce_states(g_state, self._copy_state(), g_count)
        object.__setattr__(self, "_state", merged)
        self._update_count = g_count + 1
        self._computed = None  # the pre-merge compute cache holds the batch value
        for c, cs in zip(self._child_metrics(), g_children):
            c._deep_merge(cs)

    def _reduce_states(
        self,
        global_state: Dict[str, Any],
        batch_state: Dict[str, Any],
        global_count: int,
        batch_count: int = 1,
    ) -> Dict[str, Any]:
        """Merge rules keyed by reduction tag (reference ``metric.py:319-346``).

        ``batch_count`` is the number of updates ``batch_state`` accumulated
        (1 for the forward protocol's single-batch merge; serving replica
        merges — ``metrics_tpu/serving`` — pass each replica's update count
        so 'mean' states weight correctly)."""
        merged: Dict[str, Any] = {}
        for name, reduce_fn in self._reductions.items():
            g, b = global_state[name], batch_state[name]
            if getattr(type(g), "is_sketch_state", False):
                # mergeable sketches define their own associative+commutative
                # union (streaming/sketches.py) — the tag is documentary
                merged[name] = g.sketch_merge(b)
            elif reduce_fn == "sum":
                merged[name] = g + b
            elif reduce_fn == "mean":
                if global_count == 0:
                    merged[name] = b
                else:
                    merged[name] = (g * global_count + b * batch_count) / (global_count + batch_count)
            elif reduce_fn == "max":
                merged[name] = jnp.maximum(g, b)
            elif reduce_fn == "min":
                merged[name] = jnp.minimum(g, b)
            elif reduce_fn == "cat" or (reduce_fn is None and isinstance(g, list)):
                from metrics_tpu.utilities.ringbuffer import CatBuffer, cat_append

                if isinstance(g, CatBuffer):
                    # fold the batch buffer's valid rows into the global ring
                    # (capacity preserved; overflow rows drop-and-count, as
                    # in update; the batch buffer's own drops carry over)
                    m = cat_append(g, b.data, valid=b.mask)
                    b_dropped = b.dropped if b.dropped is not None else jnp.zeros((), jnp.int32)
                    merged[name] = CatBuffer(m.data, m.mask, m.dropped + b_dropped)
                else:
                    merged[name] = list(g) + list(b)
            elif callable(reduce_fn):
                # same contract as every other call site (and reference
                # ``metric.py:344``): one stacked array argument
                merged[name] = reduce_fn(jnp.stack([g, b]))
            else:
                # no valid merge rule: keep the batch-updated-on-global result
                # by re-running update on the global state
                raise MetricsTPUUserError(
                    f"State {name!r} has dist_reduce_fx={reduce_fn!r} which has no forward merge rule; "
                    f"set class attribute ``full_state_update = True`` for {type(self).__name__}."
                )
        return merged

    def _copy_state(self) -> Dict[str, Any]:
        # jax arrays are immutable → shallow copy suffices; lists copied
        return {k: (list(v) if isinstance(v, list) else v) for k, v in self._state.items()}

    def _restore_defaults(self) -> None:
        state = {}
        for name, default in self._defaults.items():
            state[name] = deepcopy(default) if isinstance(default, list) else default
        object.__setattr__(self, "_state", state)

    # ------------------------------------------------------------------
    # distributed sync lifecycle (reference ``metric.py:408-498``)
    # ------------------------------------------------------------------

    def _sync_dist(self, dist_sync_fn: Callable = gather_all_arrays, process_group: Optional[Any] = None) -> None:
        """Gather + reduce every state across processes (reference ``metric.py:348-374``)."""
        with _obs_trace.span("metric.sync_dist", metric=type(self).__name__):
            object.__setattr__(
                self, "_state", self._gathered_state(self._copy_state(), dist_sync_fn, process_group)
            )

    def _gathered_state(
        self,
        state: Dict[str, Any],
        dist_sync_fn: Callable = gather_all_arrays,
        process_group: Optional[Any] = None,
    ) -> Dict[str, Any]:
        """The gather+reduce core of :meth:`_sync_dist`, as an explicit
        ``state -> synced state`` function. It reads only immutable config
        (``_reductions``, ``process_group``) — never ``self._state`` — so the
        overlapped sync scheduler (``parallel/async_sync.py``) can run it on
        its worker thread against a snapshot buffer while the live
        accumulator keeps absorbing updates.

        The whole multi-leaf gather sequence holds the process-wide
        ``gather_sequence_lock``: process-level collectives pair across
        hosts by issue order, so a scheduler cycle and a concurrent
        blocking sync on another thread must serialize, never interleave
        their per-leaf gathers (ordering contract in
        ``parallel/async_sync.py``).

        With ``METRICS_TPU_SYNC_CHUNKS`` > 1 and at least two states, the
        sequence pipelines (ISSUE 16): per-state gathers still ISSUE in the
        exact pre-existing order (the cross-host pairing contract), but each
        state's fold — sketch rebuild+merge, stack+reduce — runs one job
        behind on this thread while the next state's wire time elapses on
        the issuer thread. Same knob as the in-graph chunk schedule, same
        bit-identical guarantee (folds are order-preserving per state)."""
        from metrics_tpu.parallel.sync import gather_sequence_lock, resolve_sync_chunks

        pipeline = resolve_sync_chunks(None) > 1
        with gather_sequence_lock:
            return self._gathered_state_seq(state, dist_sync_fn, process_group, pipeline=pipeline)

    def _gathered_state_seq(
        self,
        state: Dict[str, Any],
        dist_sync_fn: Callable,
        process_group: Optional[Any],
        pipeline: bool = False,
    ) -> Dict[str, Any]:
        from metrics_tpu.parallel.sync import run_gather_jobs
        from metrics_tpu.utilities.ringbuffer import CatBuffer

        from metrics_tpu.utilities.guard import FaultCounters

        state = dict(state)
        group = self.process_group if process_group is None else process_group
        gather = lambda x: dist_sync_fn(x, group)  # noqa: E731

        # Each state becomes one (attr, issue, fold) job: `issue` performs
        # its transport gathers, `fold` builds the synced value. Job order —
        # special states (sketch/FaultCounters/CatBuffer) in state order,
        # then plain/list states in state order — is the pre-refactor issue
        # order, so cross-host collective pairing is unchanged whether the
        # jobs run sequentially or pipelined (run_gather_jobs).
        special_jobs = []
        plain_attrs = []
        for attr in self._reductions:
            value = state[attr]
            if getattr(type(value), "is_sketch_state", False):
                # gather every leaf per rank, rebuild the per-rank sketches,
                # fold them through the sketch's own merge — the process-level
                # analogue of fused_sync's sketch handling
                leaves, treedef = jax.tree_util.tree_flatten(value)

                def issue(leaves=leaves):
                    return [gather(leaf) for leaf in leaves]

                def fold(gathered, treedef=treedef):
                    n_ranks = len(gathered[0])
                    ranks = [
                        jax.tree_util.tree_unflatten(treedef, [g[r] for g in gathered])
                        for r in range(n_ranks)
                    ]
                    merged = ranks[0]
                    for other in ranks[1:]:
                        merged = merged.sketch_merge(other)
                    return merged

                special_jobs.append((attr, issue, fold))
            elif isinstance(value, FaultCounters):

                def issue(value=value):
                    return gather(value.counts)

                def fold(gathered):
                    return FaultCounters(counts=sum(jnp.asarray(g) for g in gathered))

                special_jobs.append((attr, issue, fold))
            elif isinstance(value, CatBuffer):
                # gather data and mask; the union of valid rows is the
                # stacked buffers (masked rows stay masked)

                def issue(value=value):
                    local_dropped = (
                        value.dropped if value.dropped is not None else jnp.zeros((), jnp.int32)
                    )
                    return (gather(value.data), gather(value.mask), gather(local_dropped))

                def fold(gathered):
                    data, mask, dropped = gathered
                    return CatBuffer(
                        data=jnp.concatenate(data, axis=0),
                        mask=jnp.concatenate(mask, axis=0),
                        dropped=sum(dropped),
                    )

                special_jobs.append((attr, issue, fold))
            else:
                plain_attrs.append(attr)

        jobs = special_jobs
        for attr in plain_attrs:
            value = state[attr]
            reduction_fn = self._reductions[attr]
            if isinstance(value, list):
                # pre-concat list states to minimize gathers (reference
                # ``metric.py:352-354``)
                pre = [dim_zero_cat(value)] if len(value) >= 1 else []

                def issue(pre=pre):
                    return [gather(x) for x in pre]

                def fold(out):
                    return _flatten(out) if out else []

                jobs.append((attr, issue, fold))
            else:

                def issue(value=value):
                    return gather(value)

                def fold(out, reduction_fn=reduction_fn):
                    # out is a list of per-rank arrays
                    stacked = jnp.stack(out, axis=0)
                    if reduction_fn == "sum":
                        return jnp.sum(stacked, axis=0)
                    if reduction_fn == "mean":
                        return jnp.mean(stacked, axis=0)
                    if reduction_fn == "max":
                        return jnp.max(stacked, axis=0)
                    if reduction_fn == "min":
                        return jnp.min(stacked, axis=0)
                    if reduction_fn == "cat":
                        return jnp.concatenate([jnp.atleast_1d(o) for o in out], axis=0)
                    if callable(reduction_fn):
                        return reduction_fn(stacked)
                    if reduction_fn is None:
                        return stacked
                    raise MetricsTPUUserError(f"Unsupported reduction: {reduction_fn}")

                jobs.append((attr, issue, fold))

        state.update(run_gather_jobs(jobs, pipeline=pipeline))
        return state

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available_fn: Optional[Callable] = None,
    ) -> None:
        """Cache local state, replace with gathered+reduced state (reference ``metric.py:408-442``)."""
        if self._is_synced and should_sync:
            raise MetricsTPUUserError("The Metric has already been synced.")
        is_distributed = (distributed_available_fn or distributed_available)()
        if not should_sync or not is_distributed:
            return
        if dist_sync_fn is None:
            dist_sync_fn = gather_all_arrays
        self._cache = self._copy_state()
        self._sync_dist(dist_sync_fn, process_group=process_group)
        self._is_synced = True

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore cached local state (reference ``metric.py:444-464``)."""
        if not should_unsync:
            return
        if not self._is_synced:
            raise MetricsTPUUserError("The Metric has already been un-synced.")
        if self._cache is None:
            raise MetricsTPUUserError("The internal cache should exist to unsync the Metric.")
        object.__setattr__(self, "_state", self._cache)
        self._is_synced = False
        self._cache = None

    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available_fn: Optional[Callable] = None,
    ):
        """RAII sync/unsync wrapper used by compute (reference ``metric.py:466-498``)."""
        metric = self

        class _SyncCtx:
            def __enter__(self_ctx):
                metric.sync(
                    dist_sync_fn=dist_sync_fn,
                    process_group=process_group,
                    should_sync=should_sync,
                    distributed_available_fn=distributed_available_fn,
                )
                return self_ctx

            def __exit__(self_ctx, *exc):
                if metric._is_synced and should_unsync:
                    metric.unsync()
                return False

        return _SyncCtx()

    # ------------------------------------------------------------------
    # abstract interface
    # ------------------------------------------------------------------

    def update(self, *args: Any, **kwargs: Any) -> None:  # pragma: no cover - abstract
        """Override to update state with batch data (reference ``metric.py:530``)."""
        raise NotImplementedError

    def compute(self) -> Any:  # pragma: no cover - abstract
        """Override to compute the final value from state (reference ``metric.py:535``)."""
        raise NotImplementedError

    # ------------------------------------------------------------------
    # reset / clone / persistence (reference ``metric.py:539-569,649-692``)
    # ------------------------------------------------------------------

    def reset(self) -> None:
        """Restore default state (reference ``metric.py:539``)."""
        sched = self.__dict__.get("_sync_scheduler")
        if sched is not None:
            # the scheduler's view covers the pre-reset stream; stop it
            # (no final cycle needed) and lazily rebuild on the next update
            sched.stop(final=False, timeout_s=5.0)
            object.__setattr__(self, "_sync_scheduler", None)
        self._update_count = 0
        self._update_called = False
        # staleness restarts with the epoch: a reset-but-unfed metric must
        # read as never_updated, not as fed-at-step-0 with a stale clock
        self._last_update_unix = None
        self._computed = None
        self._forward_cache = None
        self._restore_defaults()
        self._cache = None
        self._is_synced = False
        self._faults_reported = 0  # counters reset with the state; so must the warn watermark

    def clone(self) -> "Metric":
        """Deep copy (reference ``metric.py:556``)."""
        return deepcopy(self)

    def persistent(self, mode: bool = False) -> None:
        """Flip the persistence flag of all states (reference ``metric.py:649``)."""
        for key in self._persistent:
            self._persistent[key] = mode

    @staticmethod
    def _serialize_state_value(current: Any) -> Any:
        """One state leaf as checkpoint-friendly primitives: lists of numpy
        arrays, :class:`CatBuffer` as a ``{"data", "mask", "dropped"}`` dict,
        :class:`FaultCounters` as its raw counts vector — all round-trip
        through orbax/pickle with no custom node handling and are rebuilt
        (and validated) by :meth:`_validated_state_value`."""
        from metrics_tpu.utilities.guard import FaultCounters
        from metrics_tpu.utilities.ringbuffer import CatBuffer

        if isinstance(current, list):
            return [np.asarray(x) for x in current]
        if isinstance(current, CatBuffer):
            dropped = current.dropped if current.dropped is not None else jnp.zeros((), jnp.int32)
            return {
                "data": np.asarray(current.data),
                "mask": np.asarray(current.mask),
                "dropped": np.asarray(dropped),
            }
        if isinstance(current, FaultCounters):
            return np.asarray(current.counts)
        if getattr(type(current), "is_sketch_state", False):
            return current.to_primitives()
        return np.asarray(current)

    def state_dict(self, prefix: str = "") -> Dict[str, Any]:
        """Persistent states as numpy copies (reference ``metric.py:654-672``),
        serialized per :meth:`_serialize_state_value`."""
        out: Dict[str, Any] = {}
        for key in self._defaults:
            if not self._persistent[key]:
                continue
            out[prefix + key] = self._serialize_state_value(self._state[key])
        return out

    # ------------------------------------------------------------------
    # crash-safe snapshots (metrics_tpu/resilience/snapshot.py)
    # ------------------------------------------------------------------

    def _named_child_metrics(self):
        """(name, child) pairs for every Metric held in an attribute or an
        attribute list/tuple — the snapshot recursion set. Unlike
        :meth:`_child_metrics` (the forward-protocol set) this includes a
        ``CompositionalMetric``'s operands: snapshots must capture the whole
        state tree, not just the forward-managed part."""
        for key, v in self.__dict__.items():
            if isinstance(v, Metric):
                yield key, v
            elif isinstance(v, (list, tuple)):
                for i, x in enumerate(v):
                    if isinstance(x, Metric):
                        yield f"{key}[{i}]", x

    def snapshot_state(self) -> Dict[str, Any]:
        """EVERY state leaf (persistence flags ignored — a crash-recovery
        snapshot that skipped non-persistent accumulators would restore a
        different value) plus the update counter, recursively over child
        metrics (wrappers hold their state in children). Values serialize
        per :meth:`_serialize_state_value`; rebuilt by
        :meth:`load_snapshot_state`.

        Overlapped-sync metrics serialize under the swap guard, so the
        captured buffer is always a consistent live state — never a torn
        mid-swap pair from a concurrent scheduler cycle or blocking read."""
        with self._state_swap_guard():
            out: Dict[str, Any] = {
                "states": {
                    key: self._serialize_state_value(self._state[key]) for key in self._defaults
                },
                "update_count": self._update_count,
            }
            if self._last_update_unix is not None:
                # the staleness clock must survive crash recovery: a restored
                # metric with 500 updates reporting "never updated" would tell
                # operators the opposite of the truth (resilience/health.py)
                out["last_update_unix"] = self._last_update_unix
            attrs = {
                name: getattr(self, name)
                for name in self._snapshot_attrs
                if getattr(self, name, None) is not None
            }
            if attrs:
                out["attrs"] = attrs
            children = {
                name: child.snapshot_state() for name, child in self._named_child_metrics()
            }
            if children:
                out["children"] = children
            return out

    def load_snapshot_state(self, payload: Dict[str, Any]) -> None:
        """Restore a :meth:`snapshot_state` payload. Every value is validated
        against the registered defaults (see :meth:`_validated_state_value`);
        unknown state keys or missing children raise naming the offender.
        Transactional over the WHOLE metric tree: validation of every state
        and every child runs before anything commits, so a rejected payload
        leaves this metric (and its children) untouched."""
        self._commit_snapshot_state(self._prepare_snapshot_state(payload))

    def _prepare_snapshot_state(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """The validate half: check every state/attr/child of ``payload``
        recursively WITHOUT mutating anything; returns the prepared tree
        :meth:`_commit_snapshot_state` applies."""
        states = payload.get("states", {})
        for key in states:
            if key not in self._defaults:
                raise ValueError(
                    f"{type(self).__name__}.load_snapshot_state: snapshot carries unknown state "
                    f"{key!r}; refusing to load (metric config mismatch?)"
                )
        loaded = {
            key: self._validated_state_value(key, value, via="load_snapshot_state")
            for key, value in states.items()
        }
        self._check_ring_capacity_consistency("load_snapshot_state", {**self._state, **loaded})
        attrs = dict(payload.get("attrs", {}))
        for name in attrs:
            if name not in self._snapshot_attrs:
                raise ValueError(
                    f"{type(self).__name__}.load_snapshot_state: snapshot carries data-inferred "
                    f"attribute {name!r} this class does not declare in `_snapshot_attrs`"
                )
        mine = dict(self._named_child_metrics())
        children = {}
        for name, child_payload in payload.get("children", {}).items():
            if name not in mine:
                raise ValueError(
                    f"{type(self).__name__}.load_snapshot_state: snapshot carries child metric "
                    f"{name!r} this instance does not have; refusing to load"
                )
            children[name] = (mine[name], mine[name]._prepare_snapshot_state(child_payload))
        return {
            "loaded": loaded,
            "update_count": int(payload.get("update_count", self._update_count)),
            "last_update_unix": payload.get("last_update_unix"),
            "attrs": attrs,
            "children": children,
        }

    def _commit_snapshot_state(self, prepared: Dict[str, Any]) -> None:
        self._state.update(prepared["loaded"])
        self._update_count = prepared["update_count"]
        self._update_called = self._update_count > 0
        if prepared.get("last_update_unix") is not None:
            self._last_update_unix = prepared["last_update_unix"]
        elif self._update_count > 0 and self._last_update_unix is None:
            # pre-staleness snapshot of a fed metric: "restored now" is the
            # honest lower bound, never_updated would be the opposite
            self._last_update_unix = time.time()
        self._computed = None
        self._is_synced = False
        self._cache = None
        for name, value in prepared["attrs"].items():
            current = getattr(self, name, None)
            if current is not None and current != value:
                # an attr can be BOTH ctor config and data-downgraded (e.g.
                # Accuracy.subset_accuracy): honor the snapshot — its states
                # were accumulated under that value — but never silently
                rank_zero_warn(
                    f"{type(self).__name__}.load_snapshot_state: overriding {name}={current!r} "
                    f"with the snapshot's {value!r} (the restored states were accumulated "
                    "under it)",
                    UserWarning,
                )
            setattr(self, name, value)
        for child, child_prepared in prepared["children"].values():
            child._commit_snapshot_state(child_prepared)

    def load_state_dict(self, state_dict: Dict[str, Any], prefix: str = "") -> None:
        """Restore states saved by :meth:`state_dict` (reference ``metric.py:674-692``).

        Every loaded value is validated against the registered default's
        shape/dtype/structure before it replaces state — a corrupt or
        mismatched checkpoint raises a ``ValueError`` naming the offending
        state key instead of silently loading garbage accumulators.
        """
        # validate-then-commit: a rejected value must leave state untouched
        loaded = {
            key: self._validated_state_value(key, state_dict[prefix + key])
            for key in self._defaults
            if prefix + key in state_dict
        }
        self._check_ring_capacity_consistency("load_state_dict", {**self._state, **loaded})
        if loaded:
            self._state.update(loaded)
            self._update_called = True
            if self._last_update_unix is None:
                # the state_dict format carries no clock; a just-restored
                # accumulator reads as fed-at-restore, not never_updated
                self._last_update_unix = time.time()

    def _check_ring_capacity_consistency(self, via: str, state: Dict[str, Any]) -> None:
        """Paired (lockstep) ring states must share ONE capacity — compute
        pairs their rows positionally under a shared mask, so a preds ring
        loaded at 16 with a target ring at 8 would silently misalign.
        Classes with independently-filled rings (``_independent_ring_drops``,
        FID/KID real-vs-fake) are exempt. Checked on the would-be state
        BEFORE commit, so a refused load leaves state untouched."""
        from metrics_tpu.utilities.ringbuffer import CatBuffer

        if self._independent_ring_drops:
            return
        caps = {key: v.capacity for key, v in state.items() if isinstance(v, CatBuffer)}
        if len(set(caps.values())) > 1:
            raise ValueError(
                f"{type(self).__name__}.{via}: lockstep ring states loaded at different "
                f"capacities ({caps}); their rows pair positionally, so a partial or "
                "mismatched load would silently misalign them. Load all rings of this "
                "metric at one capacity."
            )

    def _validated_state_value(self, key: str, v: Any, via: str = "load_state_dict") -> Any:
        """Check one loaded state value against ``self._defaults[key]``.
        ``via`` names the loading entry point in error messages (accurate
        provenance matters most during crash-recovery debugging)."""
        from metrics_tpu.utilities.guard import NUM_FAULT_CLASSES, FaultCounters
        from metrics_tpu.utilities.ringbuffer import CatBuffer

        default = self._defaults[key]

        def fail(why: str) -> None:
            raise ValueError(
                f"{type(self).__name__}.{via}: state {key!r} {why}; refusing to load a "
                "corrupt checkpoint."
            )

        def as_leaf(value: Any, like: Array, part: str = "", free_leading: bool = False) -> Array:
            try:
                arr = np.asarray(value)
            except Exception:
                fail(f"{part}is not array-like (got {type(value).__name__})")
            if arr.dtype == object:
                fail(f"{part}is not a numeric array (object dtype)")
            # free_leading: ring (CatBuffer) slots may load at a different
            # capacity — distributed sync and elastic world-size restore both
            # legitimately produce grown union buffers; row shape stays fixed
            want = tuple(like.shape[1:]) if free_leading else tuple(like.shape)
            got = tuple(arr.shape[1:]) if free_leading else tuple(arr.shape)
            if got != want or (free_leading and arr.ndim != like.ndim):
                fail(f"{part}has shape {tuple(arr.shape)}, expected {tuple(like.shape)}"
                     + (" (any capacity)" if free_leading else ""))
            if not np.can_cast(arr.dtype, np.dtype(like.dtype), casting="same_kind"):
                fail(f"{part}has dtype {arr.dtype}, incompatible with expected {like.dtype}")
            return jnp.asarray(arr).astype(like.dtype)

        if isinstance(default, CatBuffer):
            if isinstance(v, CatBuffer):
                v = {"data": v.data, "mask": v.mask, "dropped": v.dropped}
            if not isinstance(v, dict) or not {"data", "mask"} <= set(v):
                fail(
                    "is a CatBuffer ring state and must load from a {'data', 'mask', 'dropped'} "
                    f"mapping (got {type(v).__name__})"
                )
            dropped_like = default.dropped if default.dropped is not None else jnp.zeros((), jnp.int32)
            loaded_dropped = v.get("dropped")
            data = as_leaf(v["data"], default.data, "slot 'data' ", free_leading=True)
            mask = as_leaf(v["mask"], default.mask, "slot 'mask' ", free_leading=True)
            if mask.shape[0] != data.shape[0]:
                fail(f"has mask length {mask.shape[0]} != data capacity {data.shape[0]}")
            return CatBuffer(
                data=data,
                mask=mask,
                dropped=(
                    as_leaf(loaded_dropped, dropped_like, "slot 'dropped' ")
                    if loaded_dropped is not None
                    else jnp.zeros((), jnp.int32)
                ),
            )
        if isinstance(default, FaultCounters):
            if isinstance(v, FaultCounters):
                v = v.counts
            arr = np.asarray(v).reshape(-1)
            if arr.dtype == object or not np.issubdtype(arr.dtype, np.number):
                fail("is a FaultCounters state and must load from a numeric counts vector")
            # FAULT_CLASSES is appends-only, so both directions stay loadable:
            # older checkpoints (shorter vector) zero-pad the new classes,
            # newer ones (longer) keep the classes this build knows
            if arr.shape[0] < NUM_FAULT_CLASSES:
                arr = np.concatenate([arr, np.zeros(NUM_FAULT_CLASSES - arr.shape[0], arr.dtype)])
            return FaultCounters(counts=jnp.asarray(arr[:NUM_FAULT_CLASSES], jnp.uint32))
        if getattr(type(default), "is_sketch_state", False):
            try:
                return type(default).from_primitives(v, like=default)
            except ValueError as err:
                fail(f"failed sketch-state validation: {err}")
        if isinstance(default, list):
            if not isinstance(v, (list, tuple)):
                fail(f"is a list ('cat') state and must load from a list (got {type(v).__name__})")
            return [jnp.asarray(x) for x in v]
        return as_leaf(v, default)

    def __getstate__(self) -> Dict[str, Any]:
        """Pickle support: drop wrapped/bound/jitted fns (reference ``metric.py:560-569``)."""
        skip = {"update", "compute", "_original_update", "_original_compute", "_update_jit", "_compute_jit", "_update_signature", "_bucket_kernels", "_sync_scheduler", "_overlap_lock"}
        state = {k: v for k, v in self.__dict__.items() if k not in skip}
        state["_state"] = jax.tree_util.tree_map(np.asarray, self.__dict__["_state"])
        state["_defaults"] = jax.tree_util.tree_map(np.asarray, self.__dict__["_defaults"])
        state["_cache"] = jax.tree_util.tree_map(np.asarray, self.__dict__.get("_cache"))
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        # pickles from before the fault channel / padding ladder lack the knobs
        self.__dict__.setdefault("on_invalid", "ignore")
        self.__dict__.setdefault("debug_checks", False)
        self.__dict__.setdefault("pad_batches", False)
        self.__dict__.setdefault("_faults_reported", 0)
        self.__dict__.setdefault("_last_update_unix", None)
        # pickles never carry the scheduler thread or its lock — the copy
        # rebuilds both on first use (pre-overlap pickles default to blocking)
        self.__dict__.setdefault("sync_mode", "blocking")
        self.__dict__.setdefault("sync_every_n", None)
        self.__dict__.setdefault("sync_every_s", None)
        self.__dict__["_sync_scheduler"] = None
        # a standalone copy is no longer wired to a collection's shared
        # scheduler; its own (plain-state) views carry no head keying
        self.__dict__["_sync_view_key"] = None
        if self.sync_mode == "overlapped":
            self.__dict__["_overlap_lock"] = named_lock("metric._overlap_lock", threading.RLock())
        self.__dict__["_state"] = _migrate_fault_vectors(
            jax.tree_util.tree_map(jnp.asarray, state["_state"])
        )
        self.__dict__["_defaults"] = _migrate_fault_vectors(
            jax.tree_util.tree_map(jnp.asarray, state["_defaults"])
        )
        object.__setattr__(self, "_original_update", self._maybe_guard(type(self).update.__get__(self)))
        object.__setattr__(self, "_original_compute", type(self).compute.__get__(self))
        object.__setattr__(self, "update", self._wrap_update(self._original_update))
        object.__setattr__(self, "compute", self._wrap_compute(self._original_compute))
        self._update_jit = None
        self._compute_jit = None
        self._update_signature = inspect.signature(self._original_update)

    def __deepcopy__(self, memo: dict) -> "Metric":
        cls = type(self)
        new = cls.__new__(cls)
        memo[id(self)] = new
        skip = {"update", "compute", "_original_update", "_original_compute", "_update_jit", "_compute_jit", "_sync_scheduler", "_overlap_lock"}
        for k, v in self.__dict__.items():
            if k in skip:
                continue
            if k in ("_state", "_defaults", "_cache"):
                # arrays are immutable; copy containers only
                object.__setattr__(new, k, jax.tree_util.tree_map(lambda x: x, v) if v is not None else None)
            else:
                object.__setattr__(new, k, deepcopy(v, memo))
        object.__setattr__(new, "_original_update", new._maybe_guard(type(new).update.__get__(new)))
        object.__setattr__(new, "_original_compute", type(new).compute.__get__(new))
        object.__setattr__(new, "update", new._wrap_update(new._original_update))
        object.__setattr__(new, "compute", new._wrap_compute(new._original_compute))
        object.__setattr__(new, "_update_jit", None)
        object.__setattr__(new, "_compute_jit", None)
        # scheduler threads and locks are per-instance: the clone starts
        # with no in-flight cycles and builds its own scheduler lazily
        # (and is no longer wired to any collection's shared scheduler)
        object.__setattr__(new, "_sync_scheduler", None)
        object.__setattr__(new, "_sync_view_key", None)
        if getattr(new, "sync_mode", "blocking") == "overlapped":
            object.__setattr__(
                new, "_overlap_lock", named_lock("metric._overlap_lock", threading.RLock())
            )
        return new

    # ------------------------------------------------------------------
    # misc (reference ``metric.py:694-733``)
    # ------------------------------------------------------------------

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Filter kwargs against the update signature (reference ``metric.py:694-714``)."""
        _params = (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD)
        _sign_params = self._update_signature.parameters
        filtered_kwargs = {
            k: v for k, v in kwargs.items() if (k in _sign_params and _sign_params[k].kind not in _params)
        }
        exists_var_keyword = any(v.kind == inspect.Parameter.VAR_KEYWORD for v in _sign_params.values())
        if exists_var_keyword:
            filtered_kwargs = kwargs
        return filtered_kwargs

    def __hash__(self) -> int:
        # include list-state ids so equal-config metrics hash differently
        # (reference ``metric.py:716-724``)
        hash_vals = [type(self).__name__]
        for key in self._defaults:
            val = self._state.get(key)
            if isinstance(val, list):
                hash_vals.append(id(val))
                hash_vals.extend(id(v) for v in val)
            else:
                hash_vals.append(id(val))
        return hash(tuple(hash_vals))

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"

    def type(self, *_: Any, **__: Any) -> "Metric":
        """No-op (reference makes float/double/half no-ops, ``metric.py:598-614``)."""
        return self

    float = double = half = type

    def set_dtype(self, dst_type: Any) -> "Metric":
        """Cast all floating states (reference ``metric.py:616``)."""

        def _cast(x):
            if isinstance(x, jax.Array) and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(dst_type)
            return x

        object.__setattr__(self, "_state", jax.tree_util.tree_map(_cast, self._state))
        object.__setattr__(self, "_defaults", jax.tree_util.tree_map(_cast, self._defaults))
        self._update_jit = None
        self._compute_jit = None
        return self

    # ------------------------------------------------------------------
    # metric arithmetic (reference ``metric.py:735-838``)
    # ------------------------------------------------------------------

    def __add__(self, other): return CompositionalMetric(jnp.add, self, other)
    def __radd__(self, other): return CompositionalMetric(jnp.add, other, self)
    def __sub__(self, other): return CompositionalMetric(jnp.subtract, self, other)
    def __rsub__(self, other): return CompositionalMetric(jnp.subtract, other, self)
    def __mul__(self, other): return CompositionalMetric(jnp.multiply, self, other)
    def __rmul__(self, other): return CompositionalMetric(jnp.multiply, other, self)
    def __truediv__(self, other): return CompositionalMetric(jnp.true_divide, self, other)
    def __rtruediv__(self, other): return CompositionalMetric(jnp.true_divide, other, self)
    def __floordiv__(self, other): return CompositionalMetric(jnp.floor_divide, self, other)
    def __rfloordiv__(self, other): return CompositionalMetric(jnp.floor_divide, other, self)
    def __mod__(self, other): return CompositionalMetric(jnp.mod, self, other)
    def __rmod__(self, other): return CompositionalMetric(jnp.mod, other, self)
    def __pow__(self, other): return CompositionalMetric(jnp.power, self, other)
    def __rpow__(self, other): return CompositionalMetric(jnp.power, other, self)
    def __matmul__(self, other): return CompositionalMetric(jnp.matmul, self, other)
    def __rmatmul__(self, other): return CompositionalMetric(jnp.matmul, other, self)
    def __and__(self, other): return CompositionalMetric(jnp.bitwise_and, self, other)
    def __rand__(self, other): return CompositionalMetric(jnp.bitwise_and, other, self)
    def __or__(self, other): return CompositionalMetric(jnp.bitwise_or, self, other)
    def __ror__(self, other): return CompositionalMetric(jnp.bitwise_or, other, self)
    def __xor__(self, other): return CompositionalMetric(jnp.bitwise_xor, self, other)
    def __rxor__(self, other): return CompositionalMetric(jnp.bitwise_xor, other, self)
    def __eq__(self, other): return CompositionalMetric(jnp.equal, self, other)
    def __ne__(self, other): return CompositionalMetric(jnp.not_equal, self, other)
    def __ge__(self, other): return CompositionalMetric(jnp.greater_equal, self, other)
    def __gt__(self, other): return CompositionalMetric(jnp.greater, self, other)
    def __le__(self, other): return CompositionalMetric(jnp.less_equal, self, other)
    def __lt__(self, other): return CompositionalMetric(jnp.less, self, other)
    def __abs__(self): return CompositionalMetric(jnp.abs, self, None)
    def __neg__(self): return CompositionalMetric(_neg, self, None)
    def __pos__(self): return CompositionalMetric(jnp.abs, self, None)
    def __invert__(self): return CompositionalMetric(jnp.bitwise_not, self, None)
    def __getitem__(self, idx): return CompositionalMetric(lambda x: x[idx], self, None)


def _neg(x: Array) -> Array:
    return -jnp.abs(x)


class CompositionalMetric(Metric):
    """Lazy arithmetic composition of metrics (reference ``metric.py:845-953``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanAbsoluteError, MeanSquaredError
        >>> combined = MeanSquaredError() + MeanAbsoluteError()
        >>> combined.update(jnp.asarray([2.5, 0.0]), jnp.asarray([3.0, -0.5]))
        >>> round(float(combined.compute()), 4)
        0.75
    """

    # children manage their own compilation; tracing through their wrapped
    # compute would cache tracers
    jittable_update = False
    jittable_compute = False

    def __init__(
        self,
        operator: Callable,
        metric_a: Union[Metric, float, int, Array],
        metric_b: Union[Metric, float, int, Array, None],
    ) -> None:
        super().__init__()
        self.op = operator
        self.metric_a = metric_a if isinstance(metric_a, Metric) else (jnp.asarray(metric_a) if metric_a is not None else None)
        self.metric_b = metric_b if isinstance(metric_b, Metric) else (jnp.asarray(metric_b) if metric_b is not None else None)

    def _sync_dist(self, dist_sync_fn=None, process_group=None) -> None:
        pass  # children sync themselves (reference ``metric.py:870``)

    def _wrap_compute(self, compute: Callable) -> Callable:
        # no composition-level cache: children cache their own computes, and
        # a cached composition value would survive reset (reference
        # ``metric.py:938-939`` disables the wrapper the same way)
        return compute

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    @property
    def _update_called(self) -> bool:
        # delegate to children so compute() doesn't warn spuriously
        a = self.metric_a._update_called if isinstance(self.metric_a, Metric) else True
        b = self.metric_b._update_called if isinstance(self.metric_b, Metric) else True
        return a and b

    @_update_called.setter
    def _update_called(self, value: bool) -> None:
        pass  # children own the flag

    def compute(self) -> Any:
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        val_a = (
            self.metric_a(*args, **self.metric_a._filter_kwargs(**kwargs))
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        val_b = (
            self.metric_b(*args, **self.metric_b._filter_kwargs(**kwargs))
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        if val_a is None:
            return None
        if val_b is None:
            if isinstance(self.metric_b, Metric):
                return None
            return self.op(val_a)
        return self.op(val_a, val_b)

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()

    def persistent(self, mode: bool = False) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.persistent(mode=mode)
        if isinstance(self.metric_b, Metric):
            self.metric_b.persistent(mode=mode)

    def __repr__(self) -> str:
        _op_metrics = f"(\n  {self.op.__name__ if hasattr(self.op, '__name__') else self.op}(\n    {repr(self.metric_a)},\n    {repr(self.metric_b)}\n  )\n)"
        return self.__class__.__name__ + _op_metrics

"""Box utilities for detection metrics (the role torchvision's
``box_convert``/``box_area``/``box_iou`` play for the reference
``src/torchmetrics/detection/mean_ap.py:29,61``).

All three are pure jnp, fully vectorized over box sets — a ``(D, G)`` IoU
matrix is one broadcasted min/max block, MXU-free but bandwidth-friendly.
"""
import jax
import jax.numpy as jnp

Array = jax.Array


def box_convert(boxes: Array, in_fmt: str, out_fmt: str = "xyxy") -> Array:
    """Convert ``(N, 4)`` boxes between ``xyxy`` / ``xywh`` / ``cxcywh``."""
    if in_fmt == out_fmt:
        return boxes
    if in_fmt == "xyxy":
        x1, y1, x2, y2 = jnp.moveaxis(boxes, -1, 0)
    elif in_fmt == "xywh":
        x, y, w, h = jnp.moveaxis(boxes, -1, 0)
        x1, y1, x2, y2 = x, y, x + w, y + h
    elif in_fmt == "cxcywh":
        cx, cy, w, h = jnp.moveaxis(boxes, -1, 0)
        x1, y1, x2, y2 = cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2
    else:
        raise ValueError(f"Unsupported box format {in_fmt}")
    if out_fmt == "xyxy":
        out = (x1, y1, x2, y2)
    elif out_fmt == "xywh":
        out = (x1, y1, x2 - x1, y2 - y1)
    elif out_fmt == "cxcywh":
        out = ((x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1)
    else:
        raise ValueError(f"Unsupported box format {out_fmt}")
    return jnp.stack(out, axis=-1)


def box_area(boxes: Array) -> Array:
    """Area of ``(N, 4)`` xyxy boxes."""
    return (boxes[..., 2] - boxes[..., 0]) * (boxes[..., 3] - boxes[..., 1])


def box_iou(boxes1: Array, boxes2: Array) -> Array:
    """Pairwise IoU matrix ``(N, M)`` for xyxy boxes."""
    area1 = box_area(boxes1)
    area2 = box_area(boxes2)
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, min=0)
    inter = wh[..., 0] * wh[..., 1]
    union = area1[:, None] + area2[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)

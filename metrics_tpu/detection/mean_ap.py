"""COCO-style mean Average Precision / Recall (reference
``src/torchmetrics/detection/mean_ap.py``, 928 LoC).

Architecture: the states are per-image ragged arrays gathered with the union
(``dist_reduce_fx=None``) semantics, exactly like the reference's five list
states (``mean_ap.py:339-343``). Unlike the reference — whose matching is a
sequential Python loop per (image, class, area, detection)
(``mean_ap.py:537-616``) — IoU computation AND greedy matching run on device
as one batched XLA program (``detection/matcher.py``): cells padded to
static caps, a ``lax.scan`` over score-ranked detections carrying the
``(T, G)`` taken-mask, ``vmap`` over area ranges and cells. Only input
canonicalization and the final precision/recall accumulation stay on the
host.

Improvements over the reference: ``iou_type="segm"`` needs no pycocotools —
mask IoU runs on device as one batched GEMM over flatten-padded masks
(``matcher.batched_mask_iou``; mixed resolutions pad to a per-bucket pixel
cap under a device-memory budget) — and matching cost is O(max dets per
cell) compiled scan steps instead of O(total detections) interpreter
iterations.
"""
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.detection.helpers import box_convert
from metrics_tpu.metric import Metric

Array = jax.Array


def _input_validator(preds: Sequence[Dict[str, Any]], targets: Sequence[Dict[str, Any]], iou_type: str = "bbox") -> None:
    """Validate the list-of-dicts input contract (reference ``mean_ap.py:138-183``)."""
    item_key = "boxes" if iou_type == "bbox" else "masks"
    if not isinstance(preds, Sequence):
        raise ValueError("Expected argument `preds` to be of type Sequence")
    if not isinstance(targets, Sequence):
        raise ValueError("Expected argument `target` to be of type Sequence")
    if len(preds) != len(targets):
        raise ValueError("Expected argument `preds` and `target` to have the same length")

    for k in (item_key, "scores", "labels"):
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in (item_key, "labels"):
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")

    for i, item in enumerate(preds):
        n = np.asarray(item[item_key]).shape[0]
        if np.asarray(item["scores"]).shape[0] != n or np.asarray(item["labels"]).shape[0] != n:
            raise ValueError(
                f"Input {item_key} scores and labels of sample {i} in predictions have a different length"
            )
    for i, item in enumerate(targets):
        if np.asarray(item[item_key]).shape[0] != np.asarray(item["labels"]).shape[0]:
            raise ValueError(f"Input {item_key} and labels of sample {i} in targets have a different length")


def _fix_empty_boxes(boxes: np.ndarray) -> np.ndarray:
    if boxes.size == 0:
        return boxes.reshape(0, 4).astype(np.float32)
    return boxes


class MeanAveragePrecision(Metric):
    """COCO mAP / mAR (reference ``detection/mean_ap.py:199``).

    Accepts per-image prediction dicts (``boxes``/``scores``/``labels`` —
    ``masks`` instead of boxes for ``iou_type="segm"``) and target dicts
    (``boxes``/``labels``), accumulates them as ragged union states, and
    computes the full COCO summary at 10 IoU thresholds, 101 recall points,
    4 area ranges and 3 max-detection caps.
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = True

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: str = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        if iou_type not in ("bbox", "segm"):
            raise ValueError(f"Expected argument `iou_type` to be one of ('segm', 'bbox') but got {iou_type}")
        self.iou_type = iou_type
        self.iou_thresholds = iou_thresholds or np.linspace(0.5, 0.95, 10).tolist()
        self.rec_thresholds = rec_thresholds or np.linspace(0.0, 1.0, 101).tolist()
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics
        self.bbox_area_ranges = {
            "all": (0**2, int(1e5**2)),
            "small": (0**2, 32**2),
            "medium": (32**2, 96**2),
            "large": (96**2, int(1e5**2)),
        }

        # detections/groundtruths rows are ragged by construction — (n, 4)
        # boxes or (h, w) masks depending on `iou_type` — so they declare
        # template=None; scores/labels have a static scalar row
        self.add_state("detections", default=[], dist_reduce_fx=None, template=None)
        self.add_state(
            "detection_scores", default=[], dist_reduce_fx=None, template=jnp.zeros((0,), jnp.float32)
        )
        self.add_state(
            "detection_labels", default=[], dist_reduce_fx=None, template=jnp.zeros((0,), jnp.int32)
        )
        self.add_state("groundtruths", default=[], dist_reduce_fx=None, template=None)
        self.add_state(
            "groundtruth_labels", default=[], dist_reduce_fx=None, template=jnp.zeros((0,), jnp.int32)
        )

    def update(self, preds: Sequence[Dict[str, Any]], target: Sequence[Dict[str, Any]]) -> None:
        _input_validator(preds, target, iou_type=self.iou_type)

        for item in preds:
            self.detections.append(self._get_safe_item_values(item))
            self.detection_labels.append(np.asarray(item["labels"]).astype(np.int64).reshape(-1))
            self.detection_scores.append(np.asarray(item["scores"]).astype(np.float32).reshape(-1))

        for item in target:
            self.groundtruths.append(self._get_safe_item_values(item))
            self.groundtruth_labels.append(np.asarray(item["labels"]).astype(np.int64).reshape(-1))

    def _get_safe_item_values(self, item: Dict[str, Any]) -> np.ndarray:
        if self.iou_type == "bbox":
            boxes = _fix_empty_boxes(np.asarray(item["boxes"], dtype=np.float32))
            return np.asarray(box_convert(jnp.asarray(boxes), in_fmt=self.box_format, out_fmt="xyxy"))
        return np.asarray(item["masks"]).astype(bool)

    # ---- evaluation -----------------------------------------------------

    def _get_classes(self) -> List[int]:
        labels = list(self.detection_labels) + list(self.groundtruth_labels)
        if not labels:
            return []
        return sorted(np.unique(np.concatenate([np.asarray(la) for la in labels])).astype(int).tolist())

    def _area(self, items: np.ndarray) -> np.ndarray:
        # host numpy: areas feed the accumulate stage and the ignore masks
        if self.iou_type == "bbox":
            return (items[:, 2] - items[:, 0]) * (items[:, 3] - items[:, 1])
        return items.reshape(items.shape[0], -1).sum(-1).astype(np.float64)

    def _build_cells(self, class_ids: List[int], max_det: int) -> List[Dict[str, np.ndarray]]:
        """One cell per (image, class-with-content): label-filter, stable
        score-descending sort, cap at the largest max_det — the reference's
        per-(image, class) prep (``mean_ap.py:722-729``). Area ranges only
        change ignore masks downstream, so cells are area-independent."""
        cls_index = {c: k for k, c in enumerate(class_ids)}
        cells = []
        for i in range(len(self.groundtruths)):
            det_labels = np.asarray(self.detection_labels[i])
            gt_labels = np.asarray(self.groundtruth_labels[i])
            all_scores = np.asarray(self.detection_scores[i])
            all_det = np.asarray(self.detections[i])
            all_gt = np.asarray(self.groundtruths[i])
            # one lexsort groups dets by label with scores descending inside
            # each group (stable, matching per-class argsort(-scores)) —
            # per-class work becomes slicing instead of full-array masking
            det_order = np.lexsort((-all_scores, det_labels))
            det_sorted_labels = det_labels[det_order]
            det_uniq, det_starts = np.unique(det_sorted_labels, return_index=True)
            det_slices = {
                int(c): det_order[s:e]
                for c, s, e in zip(det_uniq, det_starts, np.append(det_starts[1:], det_sorted_labels.size))
            }
            gt_order = np.argsort(gt_labels, kind="stable")
            gt_sorted_labels = gt_labels[gt_order]
            gt_uniq, gt_starts = np.unique(gt_sorted_labels, return_index=True)
            gt_slices = {
                int(c): gt_order[s:e]
                for c, s, e in zip(gt_uniq, gt_starts, np.append(gt_starts[1:], gt_sorted_labels.size))
            }
            for c in sorted(det_slices.keys() | gt_slices.keys()):
                if c not in cls_index:
                    continue
                dsel = det_slices.get(c, np.zeros(0, np.int64))[:max_det]
                det = all_det[dsel]
                gt = all_gt[gt_slices.get(c, np.zeros(0, np.int64))]
                cells.append(
                    {
                        "cls": cls_index[c],
                        "scores": all_scores[dsel],
                        "det": det,
                        "gt": gt,
                        "det_areas": self._area(det) if det.shape[0] else np.zeros(0),
                        "gt_areas": self._area(gt) if gt.shape[0] else np.zeros(0),
                    }
                )
        return cells

    # matcher batch chunk: bounds device memory at COCO scale (a chunk of
    # 1024 cells × 128 dets × G_cap IoUs) while amortizing one compilation
    # across all chunks of an evaluation
    _MATCH_CHUNK = 1024
    # padded (det + gt) flattened-mask bytes allowed per segm matcher batch
    _MASK_BYTES_BUDGET = 1 << 28  # 256 MB

    def _match_all_cells(self, cells: List[Dict[str, np.ndarray]], area_ranges: np.ndarray) -> None:
        """Run the device matcher over every cell, attaching per-cell
        ``m (A, T, nd)`` match and ``ig (A, T, nd)`` matched-to-ignored
        arrays.

        Cells are bucketed by detection count (power-of-two caps): the greedy
        scan's length is the detection axis, so a cell with 6 dets in a
        128-cap batch would pay 128 sequential steps for 6 rows of work.
        Bucketing keeps total scan work proportional to the real detection
        count while bounding distinct compiled shapes to O(log max_det)."""
        from metrics_tpu.detection.matcher import (
            batched_box_iou,
            batched_mask_iou,
            match_cells,
            next_pow2,
        )

        nb_areas = area_ranges.shape[0]
        thrs = jnp.asarray(self.iou_thresholds, jnp.float32)

        buckets: Dict[int, List[int]] = {}
        for j, cell in enumerate(cells):
            buckets.setdefault(max(next_pow2(cell["scores"].shape[0]), 8), []).append(j)
            # single source for the gt area-ignore mask: matcher input here,
            # npig accumulation in _calculate
            cell["gt_ig"] = (
                (cell["gt_areas"][None, :] < area_ranges[:, :1]) | (cell["gt_areas"][None, :] > area_ranges[:, 1:])
                if cell["gt"].shape[0]
                else np.zeros((nb_areas, 0), bool)
            )

        in_flight = []  # dispatch everything, fetch at the end: the device
        # queue drains while the host pads the next chunk
        for d_cap, idxs in sorted(buckets.items()):
            g_cap = next_pow2(max(cells[j]["gt"].shape[0] for j in idxs))
            chunk = min(self._MATCH_CHUNK, next_pow2(len(idxs)))
            if self.iou_type == "segm":
                # one flattened-pixel cap per bucket (compile caching), and a
                # batch size bounded so the padded mask tensors stay within
                # the device-memory budget
                hw_cap = next_pow2(
                    max(
                        int(np.prod(c.shape[1:]))
                        for j in idxs
                        for c in (cells[j]["det"], cells[j]["gt"])
                        if c.shape[0]
                    )
                    if any(cells[j]["det"].shape[0] or cells[j]["gt"].shape[0] for j in idxs)
                    else 1
                )
                per_cell_bytes = (d_cap + g_cap) * hw_cap * 4
                chunk = min(chunk, max(1, next_pow2(self._MASK_BYTES_BUDGET // per_cell_bytes + 1) // 2))
            for start in range(0, len(idxs), chunk):
                batch = idxs[start : start + chunk]
                det_valid = np.zeros((chunk, d_cap), bool)
                gt_valid = np.zeros((chunk, g_cap), bool)
                gt_ig = np.zeros((chunk, nb_areas, g_cap), bool)
                if self.iou_type == "bbox":
                    det_boxes = np.zeros((chunk, d_cap, 4), np.float32)
                    gt_boxes = np.zeros((chunk, g_cap, 4), np.float32)
                else:
                    det_masks = np.zeros((chunk, d_cap, hw_cap), np.uint8)
                    gt_masks = np.zeros((chunk, g_cap, hw_cap), np.uint8)
                for k, j in enumerate(batch):
                    cell = cells[j]
                    nd, ng = cell["scores"].shape[0], cell["gt"].shape[0]
                    det_valid[k, :nd] = True
                    gt_valid[k, :ng] = True
                    if ng:
                        gt_ig[k, :, :ng] = cell["gt_ig"]
                    if self.iou_type == "bbox":
                        det_boxes[k, :nd] = cell["det"]
                        gt_boxes[k, :ng] = cell["gt"]
                    else:
                        # flatten-pad: each cell fills its own H*W prefix;
                        # zero pixels are IoU-neutral (see batched_mask_iou)
                        if nd:
                            det_masks[k, :nd, : int(np.prod(cell["det"].shape[1:]))] = cell[
                                "det"
                            ].reshape(nd, -1)
                        if ng:
                            gt_masks[k, :ng, : int(np.prod(cell["gt"].shape[1:]))] = cell[
                                "gt"
                            ].reshape(ng, -1)
                if self.iou_type == "bbox":
                    ious_dev = batched_box_iou(jnp.asarray(det_boxes), jnp.asarray(gt_boxes))
                else:
                    ious_dev = batched_mask_iou(jnp.asarray(det_masks), jnp.asarray(gt_masks))
                m, ig = match_cells(
                    ious_dev, jnp.asarray(det_valid), jnp.asarray(gt_valid), jnp.asarray(gt_ig), thrs
                )
                in_flight.append((batch, m, ig))
        for batch, m, ig in in_flight:
            m, ig = np.asarray(m), np.asarray(ig)
            for k, j in enumerate(batch):
                nd = cells[j]["scores"].shape[0]
                cells[j]["m"] = m[k, :, :, :nd]
                cells[j]["ig"] = ig[k, :, :, :nd].copy()  # |= area-ignore below

    def _calculate(self, class_ids: List[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Device-matched precision/recall accumulation over all
        (class, area, max_det) cells (reference ``mean_ap.py:711-870``)."""
        nb_thrs = len(self.iou_thresholds)
        nb_rec = len(self.rec_thresholds)
        nb_cls = len(class_ids)
        nb_areas = len(self.bbox_area_ranges)
        nb_mdets = len(self.max_detection_thresholds)
        max_det = self.max_detection_thresholds[-1]
        rec_thrs = np.asarray(self.rec_thresholds)
        area_ranges = np.asarray(list(self.bbox_area_ranges.values()), np.float64)

        precision = -np.ones((nb_thrs, nb_rec, nb_cls, nb_areas, nb_mdets))
        recall = -np.ones((nb_thrs, nb_cls, nb_areas, nb_mdets))

        cells = self._build_cells(class_ids, max_det)
        if not cells:
            return precision, recall
        self._match_all_cells(cells, area_ranges)  # attaches cell["m"]/["ig"]

        # host-side ignore completion: unmatched dets outside the area range
        # (reference ``mean_ap.py:607-611``)
        for cell in cells:
            nd = cell["scores"].shape[0]
            if nd:
                da = cell["det_areas"]
                out = (da[None, :] < area_ranges[:, :1]) | (da[None, :] > area_ranges[:, 1:])  # (A, nd)
                cell["ig"] |= ~cell["m"] & out[:, None, :]

        by_class: List[List[int]] = [[] for _ in range(nb_cls)]
        for j, cell in enumerate(cells):
            by_class[cell["cls"]].append(j)

        for idx_cls in range(nb_cls):
            cell_ids = by_class[idx_cls]
            if not cell_ids:
                continue
            # concat + sort ONCE per class: the per-mdet subset of a
            # score-sorted concat is selected by a positional mask, and the
            # per-area match arrays concat once instead of once per mdet
            det_scores_all = np.concatenate([cells[j]["scores"] for j in cell_ids])
            cell_pos = np.concatenate([np.arange(cells[j]["scores"].shape[0]) for j in cell_ids])
            order = np.argsort(-det_scores_all, kind="stable")
            pos_sorted = cell_pos[order]
            for idx_area in range(nb_areas):
                npig = int(sum((~cells[j]["gt_ig"][idx_area]).sum() for j in cell_ids))
                if npig == 0:
                    continue  # before the concat work — empty areas stay free
                m_area = np.concatenate([cells[j]["m"][idx_area] for j in cell_ids], axis=1)[:, order]
                ig_area = np.concatenate([cells[j]["ig"][idx_area] for j in cell_ids], axis=1)[:, order]
                for idx_mdet, mdet in enumerate(self.max_detection_thresholds):
                    keep = pos_sorted < mdet
                    det_matches = m_area[:, keep]
                    det_ignore = ig_area[:, keep]
                    tps = det_matches & ~det_ignore
                    fps = ~det_matches & ~det_ignore
                    tp_sum = tps.cumsum(axis=1).astype(np.float64)
                    fp_sum = fps.cumsum(axis=1).astype(np.float64)
                    nd = tp_sum.shape[1]
                    rc = tp_sum / npig
                    pr = tp_sum / (fp_sum + tp_sum + np.finfo(np.float64).eps)
                    recall[:, idx_cls, idx_area, idx_mdet] = rc[:, -1] if nd else 0.0
                    # precision envelope: non-increasing from the right
                    pr = np.maximum.accumulate(pr[:, ::-1], axis=1)[:, ::-1]
                    for idx_thr in range(nb_thrs):
                        inds_r = np.searchsorted(rc[idx_thr], rec_thrs, side="left")
                        num_inds = int(inds_r.argmax()) if inds_r.max() >= nd else nb_rec
                        prec = np.zeros(nb_rec)
                        prec[:num_inds] = pr[idx_thr][inds_r[:num_inds]]
                        precision[idx_thr, :, idx_cls, idx_area, idx_mdet] = prec

        return precision, recall

    def _summarize(
        self,
        precision: np.ndarray,
        recall: np.ndarray,
        avg_prec: bool = True,
        iou_threshold: Optional[float] = None,
        area_range: str = "all",
        max_dets: int = 100,
    ) -> float:
        area_idx = list(self.bbox_area_ranges).index(area_range)
        mdet_idx = self.max_detection_thresholds.index(max_dets)
        if avg_prec:
            prec = precision[..., area_idx, mdet_idx]
            if iou_threshold is not None:
                prec = prec[self.iou_thresholds.index(iou_threshold)]
        else:
            prec = recall[..., area_idx, mdet_idx]
            if iou_threshold is not None:
                prec = prec[self.iou_thresholds.index(iou_threshold)]
        valid = prec[prec > -1]
        return float(valid.mean()) if valid.size else -1.0

    def _summarize_results(self, precision: np.ndarray, recall: np.ndarray) -> Dict[str, float]:
        last_mdet = self.max_detection_thresholds[-1]
        res = {
            "map": self._summarize(precision, recall, True, max_dets=last_mdet),
            "map_small": self._summarize(precision, recall, True, area_range="small", max_dets=last_mdet),
            "map_medium": self._summarize(precision, recall, True, area_range="medium", max_dets=last_mdet),
            "map_large": self._summarize(precision, recall, True, area_range="large", max_dets=last_mdet),
            "mar_small": self._summarize(precision, recall, False, area_range="small", max_dets=last_mdet),
            "mar_medium": self._summarize(precision, recall, False, area_range="medium", max_dets=last_mdet),
            "mar_large": self._summarize(precision, recall, False, area_range="large", max_dets=last_mdet),
        }
        res["map_50"] = (
            self._summarize(precision, recall, True, iou_threshold=0.5, max_dets=last_mdet)
            if 0.5 in self.iou_thresholds
            else -1.0
        )
        res["map_75"] = (
            self._summarize(precision, recall, True, iou_threshold=0.75, max_dets=last_mdet)
            if 0.75 in self.iou_thresholds
            else -1.0
        )
        for mdet in self.max_detection_thresholds:
            res[f"mar_{mdet}"] = self._summarize(precision, recall, False, max_dets=mdet)
        return res

    def compute(self) -> Dict[str, Array]:
        classes = self._get_classes()
        precision, recall = self._calculate(classes)
        results = self._summarize_results(precision, recall)

        map_per_class: Any = [-1.0]
        mar_per_class: Any = [-1.0]
        if self.class_metrics:
            map_per_class = []
            mar_per_class = []
            for idx_cls in range(len(classes)):
                cls_prec = precision[:, :, idx_cls : idx_cls + 1]
                cls_rec = recall[:, idx_cls : idx_cls + 1]
                cls_res = self._summarize_results(cls_prec, cls_rec)
                map_per_class.append(cls_res["map"])
                mar_per_class.append(cls_res[f"mar_{self.max_detection_thresholds[-1]}"])

        out = {k: jnp.asarray(v, dtype=jnp.float32) for k, v in results.items()}
        # always 1-D, matching the reference's shape contract (sentinel [-1.])
        out["map_per_class"] = jnp.asarray(np.asarray(map_per_class, dtype=np.float32))
        out[f"mar_{self.max_detection_thresholds[-1]}_per_class"] = jnp.asarray(
            np.asarray(mar_per_class, dtype=np.float32)
        )
        return out

"""COCO-style mean Average Precision / Recall (reference
``src/torchmetrics/detection/mean_ap.py``, 928 LoC).

Architecture: the states are per-image ragged arrays gathered with the union
(``dist_reduce_fx=None``) semantics, exactly like the reference's five list
states (``mean_ap.py:339-343``). Box conversion and pairwise IoU are device
jnp kernels (``detection/helpers.py``); the greedy per-image matching and the
COCO accumulation are an explicit host boundary — the matching is a
sequential loop over score-ranked detections (vectorized across IoU
thresholds), which is the role the reference delegates to
pycocotools-style Python/numpy (``mean_ap.py:537-616``).

Improvement over the reference: ``iou_type="segm"`` needs no pycocotools —
mask IoU is a dense intersection matmul over flattened masks.
"""
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.detection.helpers import box_convert
from metrics_tpu.metric import Metric

Array = jax.Array


def _input_validator(preds: Sequence[Dict[str, Any]], targets: Sequence[Dict[str, Any]], iou_type: str = "bbox") -> None:
    """Validate the list-of-dicts input contract (reference ``mean_ap.py:138-183``)."""
    item_key = "boxes" if iou_type == "bbox" else "masks"
    if not isinstance(preds, Sequence):
        raise ValueError("Expected argument `preds` to be of type Sequence")
    if not isinstance(targets, Sequence):
        raise ValueError("Expected argument `target` to be of type Sequence")
    if len(preds) != len(targets):
        raise ValueError("Expected argument `preds` and `target` to have the same length")

    for k in (item_key, "scores", "labels"):
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in (item_key, "labels"):
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")

    for i, item in enumerate(preds):
        n = np.asarray(item[item_key]).shape[0]
        if np.asarray(item["scores"]).shape[0] != n or np.asarray(item["labels"]).shape[0] != n:
            raise ValueError(
                f"Input {item_key} scores and labels of sample {i} in predictions have a different length"
            )
    for i, item in enumerate(targets):
        if np.asarray(item[item_key]).shape[0] != np.asarray(item["labels"]).shape[0]:
            raise ValueError(f"Input {item_key} and labels of sample {i} in targets have a different length")


def _fix_empty_boxes(boxes: np.ndarray) -> np.ndarray:
    if boxes.size == 0:
        return boxes.reshape(0, 4).astype(np.float32)
    return boxes


def _mask_iou(det: np.ndarray, gt: np.ndarray) -> np.ndarray:
    """Pairwise mask IoU ``(D, G)`` from dense ``(N, H, W)`` bool masks."""
    d = det.reshape(det.shape[0], -1).astype(np.float32)
    g = gt.reshape(gt.shape[0], -1).astype(np.float32)
    inter = d @ g.T
    union = d.sum(1)[:, None] + g.sum(1)[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1), 0.0)


class MeanAveragePrecision(Metric):
    """COCO mAP / mAR (reference ``detection/mean_ap.py:199``).

    Accepts per-image prediction dicts (``boxes``/``scores``/``labels`` —
    ``masks`` instead of boxes for ``iou_type="segm"``) and target dicts
    (``boxes``/``labels``), accumulates them as ragged union states, and
    computes the full COCO summary at 10 IoU thresholds, 101 recall points,
    4 area ranges and 3 max-detection caps.
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = True

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: str = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        if iou_type not in ("bbox", "segm"):
            raise ValueError(f"Expected argument `iou_type` to be one of ('segm', 'bbox') but got {iou_type}")
        self.iou_type = iou_type
        self.iou_thresholds = iou_thresholds or np.linspace(0.5, 0.95, 10).tolist()
        self.rec_thresholds = rec_thresholds or np.linspace(0.0, 1.0, 101).tolist()
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics
        self.bbox_area_ranges = {
            "all": (0**2, int(1e5**2)),
            "small": (0**2, 32**2),
            "medium": (32**2, 96**2),
            "large": (96**2, int(1e5**2)),
        }

        self.add_state("detections", default=[], dist_reduce_fx=None)
        self.add_state("detection_scores", default=[], dist_reduce_fx=None)
        self.add_state("detection_labels", default=[], dist_reduce_fx=None)
        self.add_state("groundtruths", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", default=[], dist_reduce_fx=None)

    def update(self, preds: Sequence[Dict[str, Any]], target: Sequence[Dict[str, Any]]) -> None:
        _input_validator(preds, target, iou_type=self.iou_type)

        for item in preds:
            self.detections.append(self._get_safe_item_values(item))
            self.detection_labels.append(np.asarray(item["labels"]).astype(np.int64).reshape(-1))
            self.detection_scores.append(np.asarray(item["scores"]).astype(np.float32).reshape(-1))

        for item in target:
            self.groundtruths.append(self._get_safe_item_values(item))
            self.groundtruth_labels.append(np.asarray(item["labels"]).astype(np.int64).reshape(-1))

    def _get_safe_item_values(self, item: Dict[str, Any]) -> np.ndarray:
        if self.iou_type == "bbox":
            boxes = _fix_empty_boxes(np.asarray(item["boxes"], dtype=np.float32))
            return np.asarray(box_convert(jnp.asarray(boxes), in_fmt=self.box_format, out_fmt="xyxy"))
        return np.asarray(item["masks"]).astype(bool)

    # ---- evaluation (host boundary) -------------------------------------

    def _get_classes(self) -> List[int]:
        labels = list(self.detection_labels) + list(self.groundtruth_labels)
        if not labels:
            return []
        return sorted(np.unique(np.concatenate([np.asarray(la) for la in labels])).astype(int).tolist())

    def _area(self, items: np.ndarray) -> np.ndarray:
        # numpy, not jnp: this runs inside the per-(image, class) host loop
        # where a device dispatch per call would dominate compute() wall time
        if self.iou_type == "bbox":
            return (items[:, 2] - items[:, 0]) * (items[:, 3] - items[:, 1])
        return items.reshape(items.shape[0], -1).sum(-1).astype(np.float64)

    def _iou(self, det: np.ndarray, gt: np.ndarray) -> np.ndarray:
        if self.iou_type == "bbox":
            lt = np.maximum(det[:, None, :2], gt[None, :, :2])
            rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
            wh = np.clip(rb - lt, 0, None)
            inter = wh[..., 0] * wh[..., 1]
            union = self._area(det)[:, None] + self._area(gt)[None, :] - inter
            return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)
        return _mask_iou(det, gt)

    def _prepare_image_class(self, idx: int, class_id: int, max_det: int) -> Optional[Dict[str, np.ndarray]]:
        """Label-filter, score-sort, cap, and IoU once per (image, class) —
        the reference's per-(image, class) ious cache (``mean_ap.py:722-729``);
        area ranges only change the ignore masks downstream."""
        gt_all = np.asarray(self.groundtruths[idx])
        det_all = np.asarray(self.detections[idx])
        gt_mask = np.asarray(self.groundtruth_labels[idx]) == class_id
        det_mask = np.asarray(self.detection_labels[idx]) == class_id
        if not gt_mask.any() and not det_mask.any():
            return None

        # detections: score-descending (stable, matlab-style), capped
        scores = np.asarray(self.detection_scores[idx])[det_mask]
        dtind = np.argsort(-scores, kind="stable")[:max_det]
        det = det_all[det_mask][dtind]
        gt = gt_all[gt_mask]
        nb_det, nb_gt = det.shape[0], gt.shape[0]
        return {
            "scores": scores[dtind],
            "det_areas": self._area(det) if nb_det else np.zeros(0),
            "gt_areas": self._area(gt) if nb_gt else np.zeros(0),
            "ious": self._iou(det, gt) if nb_det and nb_gt else np.zeros((nb_det, nb_gt)),
        }

    def _evaluate_image(
        self, entry: Optional[Dict[str, np.ndarray]], area_range: Tuple[int, int]
    ) -> Optional[Dict[str, np.ndarray]]:
        """Greedy matching for one (image, class, area-range) cell (reference
        ``mean_ap.py:537-616``), vectorized over IoU thresholds."""
        if entry is None:
            return None
        nb_thrs = len(self.iou_thresholds)
        scores_sorted = entry["scores"]
        nb_det = scores_sorted.shape[0]
        nb_gt = entry["gt_areas"].shape[0]

        if nb_gt == 0:
            det_ig = (entry["det_areas"] < area_range[0]) | (entry["det_areas"] > area_range[1])
            return {
                "dtMatches": np.zeros((nb_thrs, nb_det), dtype=bool),
                "dtScores": scores_sorted,
                "gtIgnore": np.zeros(0, dtype=bool),
                "dtIgnore": np.broadcast_to(det_ig[None, :], (nb_thrs, nb_det)).copy(),
            }

        # ground truths: ignored-last (stable)
        ignore_area = (entry["gt_areas"] < area_range[0]) | (entry["gt_areas"] > area_range[1])
        gtind = np.argsort(ignore_area.astype(np.uint8), kind="stable")
        gt_ignore = ignore_area[gtind]

        if nb_det == 0:
            return {
                "dtMatches": np.zeros((nb_thrs, 0), dtype=bool),
                "dtScores": np.zeros(0),
                "gtIgnore": gt_ignore,
                "dtIgnore": np.zeros((nb_thrs, 0), dtype=bool),
            }

        ious = entry["ious"][:, gtind]  # rows score-sorted, cols ignored-last
        thrs = np.asarray(self.iou_thresholds)
        gt_matches = np.zeros((nb_thrs, nb_gt), dtype=bool)
        det_matches = np.zeros((nb_thrs, nb_det), dtype=bool)
        det_ignore = np.zeros((nb_thrs, nb_det), dtype=bool)

        for d in range(nb_det):
            # per threshold: best still-available, non-ignored gt
            avail = ~(gt_matches | gt_ignore[None, :])  # (T, G)
            cand = ious[d][None, :] * avail
            m = cand.argmax(axis=1)  # (T,)
            ok = cand[np.arange(nb_thrs), m] > thrs
            det_ignore[ok, d] = gt_ignore[m[ok]]
            det_matches[ok, d] = True
            gt_matches[ok, m[ok]] = True

        det_ig_area = (entry["det_areas"] < area_range[0]) | (entry["det_areas"] > area_range[1])
        det_ignore |= (~det_matches) & det_ig_area[None, :]

        return {
            "dtMatches": det_matches,
            "dtScores": scores_sorted,
            "gtIgnore": gt_ignore,
            "dtIgnore": det_ignore,
        }

    def _calculate(self, class_ids: List[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Accumulate precision/recall over all (class, area, max_det) cells
        (reference ``mean_ap.py:711-870``)."""
        nb_imgs = len(self.groundtruths)
        nb_thrs = len(self.iou_thresholds)
        nb_rec = len(self.rec_thresholds)
        nb_cls = len(class_ids)
        nb_areas = len(self.bbox_area_ranges)
        nb_mdets = len(self.max_detection_thresholds)
        max_det = self.max_detection_thresholds[-1]
        rec_thrs = np.asarray(self.rec_thresholds)

        precision = -np.ones((nb_thrs, nb_rec, nb_cls, nb_areas, nb_mdets))
        recall = -np.ones((nb_thrs, nb_cls, nb_areas, nb_mdets))

        for idx_cls, class_id in enumerate(class_ids):
            entries = [self._prepare_image_class(i, class_id, max_det) for i in range(nb_imgs)]
            for idx_area, area_rng in enumerate(self.bbox_area_ranges.values()):
                evals = [self._evaluate_image(e, area_rng) for e in entries]
                evals = [e for e in evals if e is not None]
                if not evals:
                    continue
                for idx_mdet, mdet in enumerate(self.max_detection_thresholds):
                    det_scores = np.concatenate([e["dtScores"][:mdet] for e in evals])
                    inds = np.argsort(-det_scores, kind="stable")
                    det_scores_sorted = det_scores[inds]
                    det_matches = np.concatenate([e["dtMatches"][:, :mdet] for e in evals], axis=1)[:, inds]
                    det_ignore = np.concatenate([e["dtIgnore"][:, :mdet] for e in evals], axis=1)[:, inds]
                    gt_ignore = np.concatenate([e["gtIgnore"] for e in evals])
                    npig = int((~gt_ignore).sum())
                    if npig == 0:
                        continue
                    tps = det_matches & ~det_ignore
                    fps = ~det_matches & ~det_ignore
                    tp_sum = tps.cumsum(axis=1).astype(np.float64)
                    fp_sum = fps.cumsum(axis=1).astype(np.float64)
                    for idx_thr in range(nb_thrs):
                        tp, fp = tp_sum[idx_thr], fp_sum[idx_thr]
                        nd = tp.shape[0]
                        rc = tp / npig
                        pr = tp / (fp + tp + np.finfo(np.float64).eps)
                        recall[idx_thr, idx_cls, idx_area, idx_mdet] = rc[-1] if nd else 0.0
                        # precision envelope: non-increasing from the right
                        pr = np.maximum.accumulate(pr[::-1])[::-1]
                        inds_r = np.searchsorted(rc, rec_thrs, side="left")
                        num_inds = int(inds_r.argmax()) if inds_r.max() >= nd else nb_rec
                        prec = np.zeros(nb_rec)
                        prec[:num_inds] = pr[inds_r[:num_inds]]
                        precision[idx_thr, :, idx_cls, idx_area, idx_mdet] = prec

        return precision, recall

    def _summarize(
        self,
        precision: np.ndarray,
        recall: np.ndarray,
        avg_prec: bool = True,
        iou_threshold: Optional[float] = None,
        area_range: str = "all",
        max_dets: int = 100,
    ) -> float:
        area_idx = list(self.bbox_area_ranges).index(area_range)
        mdet_idx = self.max_detection_thresholds.index(max_dets)
        if avg_prec:
            prec = precision[..., area_idx, mdet_idx]
            if iou_threshold is not None:
                prec = prec[self.iou_thresholds.index(iou_threshold)]
        else:
            prec = recall[..., area_idx, mdet_idx]
            if iou_threshold is not None:
                prec = prec[self.iou_thresholds.index(iou_threshold)]
        valid = prec[prec > -1]
        return float(valid.mean()) if valid.size else -1.0

    def _summarize_results(self, precision: np.ndarray, recall: np.ndarray) -> Dict[str, float]:
        last_mdet = self.max_detection_thresholds[-1]
        res = {
            "map": self._summarize(precision, recall, True, max_dets=last_mdet),
            "map_small": self._summarize(precision, recall, True, area_range="small", max_dets=last_mdet),
            "map_medium": self._summarize(precision, recall, True, area_range="medium", max_dets=last_mdet),
            "map_large": self._summarize(precision, recall, True, area_range="large", max_dets=last_mdet),
            "mar_small": self._summarize(precision, recall, False, area_range="small", max_dets=last_mdet),
            "mar_medium": self._summarize(precision, recall, False, area_range="medium", max_dets=last_mdet),
            "mar_large": self._summarize(precision, recall, False, area_range="large", max_dets=last_mdet),
        }
        res["map_50"] = (
            self._summarize(precision, recall, True, iou_threshold=0.5, max_dets=last_mdet)
            if 0.5 in self.iou_thresholds
            else -1.0
        )
        res["map_75"] = (
            self._summarize(precision, recall, True, iou_threshold=0.75, max_dets=last_mdet)
            if 0.75 in self.iou_thresholds
            else -1.0
        )
        for mdet in self.max_detection_thresholds:
            res[f"mar_{mdet}"] = self._summarize(precision, recall, False, max_dets=mdet)
        return res

    def compute(self) -> Dict[str, Array]:
        classes = self._get_classes()
        precision, recall = self._calculate(classes)
        results = self._summarize_results(precision, recall)

        map_per_class: Any = [-1.0]
        mar_per_class: Any = [-1.0]
        if self.class_metrics:
            map_per_class = []
            mar_per_class = []
            for idx_cls in range(len(classes)):
                cls_prec = precision[:, :, idx_cls : idx_cls + 1]
                cls_rec = recall[:, idx_cls : idx_cls + 1]
                cls_res = self._summarize_results(cls_prec, cls_rec)
                map_per_class.append(cls_res["map"])
                mar_per_class.append(cls_res[f"mar_{self.max_detection_thresholds[-1]}"])

        out = {k: jnp.asarray(v, dtype=jnp.float32) for k, v in results.items()}
        # always 1-D, matching the reference's shape contract (sentinel [-1.])
        out["map_per_class"] = jnp.asarray(np.asarray(map_per_class, dtype=np.float32))
        out[f"mar_{self.max_detection_thresholds[-1]}_per_class"] = jnp.asarray(
            np.asarray(mar_per_class, dtype=np.float32)
        )
        return out

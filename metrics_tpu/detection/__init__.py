"""Detection metrics (reference ``src/torchmetrics/detection/__init__.py``)."""
from metrics_tpu.detection.mean_ap import MeanAveragePrecision  # noqa: F401

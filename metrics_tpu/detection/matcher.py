"""Batched on-device COCO greedy matching (SURVEY.md §2.9 "vectorized IoU
matching").

The reference evaluates each (image, class, area-range) cell with a
sequential Python loop over score-ranked detections
(``src/torchmetrics/detection/mean_ap.py:537-616``, itself a transcription of
``pycocotools.cocoeval.COCOeval.evaluateImg``). That loop is O(cells × dets)
Python dispatches — minutes at COCO scale.

Here the same greedy assignment is one compiled XLA program:

- detections are score-sorted on the host once per cell;
- a ``lax.scan`` walks the detection axis carrying a ``(T, G)`` taken-mask
  (T = IoU thresholds, G = padded ground-truth cap), so the sequential data
  dependence of greedy matching is preserved exactly;
- everything else is vectorized: thresholds broadcast inside the scan step,
  ``vmap`` over area ranges (which only change the ignore mask), ``vmap``
  over cells (image × class pairs with content);
- ragged cells ride static ``(D_cap, G_cap)`` pads with validity masks, so
  one compilation serves a whole evaluation and the scan never sees a
  data-dependent shape.

Matching semantics follow pycocotools precisely:

- a detection prefers the best still-unmatched, non-ignored ground truth
  with IoU ≥ min(t, 1-1e-10); ties go to the later gt (the reference's
  ``>=`` update rule);
- only when no non-ignored gt qualifies may it match an (unmatched) ignored
  gt, which in turn marks the detection ignored;
- matched gts (ignored or not) become unavailable at that threshold.
"""
from functools import partial

import jax
import jax.numpy as jnp

Array = jax.Array


# tier bonus for non-ignored gts: must exceed any IoU bit pattern
# (bits(1.0f) = 0x3F800000) while keeping key sums < 2^31
_TIER = 0x40000000


def _match_one_cell(ious: Array, det_valid: Array, gt_valid: Array, gt_ignore: Array, thrs: Array):
    """Greedy-match one padded cell.

    The two-tier preference (best non-ignored gt first, ignored gts only as
    fallback) is ONE integer argmax per scan step: IoUs are bitcast to int32
    — order-preserving for non-negative floats — and non-ignored candidates
    get a high tier bit, so ``argmax(key)`` picks the pycocotools winner
    exactly, with no float-precision compromise. The threshold comparison
    ``(D, T, G)`` is area-independent and hoisted out of the area vmap.

    Args:
        ious: ``(D, G)`` pairwise IoU, rows score-descending.
        det_valid: ``(D,)`` bool — real (non-pad) detections.
        gt_valid: ``(G,)`` bool — real (non-pad) ground truths.
        gt_ignore: ``(G,)`` bool — gts outside the area range.
        thrs: ``(T,)`` IoU thresholds.

    Returns:
        ``(T, D)`` det-matched bools and ``(T, D)`` matched-to-ignored-gt bools.
    """
    T = thrs.shape[0]
    G = ious.shape[1]
    thr_eff = jnp.minimum(thrs, 1.0 - 1e-10)  # pycocotools' min(t, 1-1e-10)
    iou_bits = jax.lax.bitcast_convert_type(ious, jnp.int32)  # (D, G)
    ok = ious[:, None, :] >= thr_eff[None, :, None]  # (D, T, G)
    key_all = iou_bits + jnp.where(gt_ignore, 0, _TIER)[None, :]  # (D, G)
    gcol = jnp.arange(G)

    def step(taken: Array, inp):
        ok_d, key_d, dvalid = inp  # (T, G), (G,), scalar bool
        cand = ok_d & gt_valid[None, :] & ~taken  # (T, G)
        key = jnp.where(cand, key_d, -1)
        # last index attaining the max key (IoU ties -> later gt)
        m = (G - 1) - jnp.argmax(key[:, ::-1], axis=1)  # (T,)
        matched = (jnp.max(key, axis=1) >= 0) & dvalid
        taken = taken | ((gcol[None, :] == m[:, None]) & matched[:, None])
        return taken, (matched, matched & gt_ignore[m])

    _, (matches, ig) = jax.lax.scan(step, jnp.zeros((T, G), bool), (ok, key_all, det_valid))
    return matches.T, ig.T  # (D, T) -> (T, D)


# vmap over area ranges (only gt_ignore varies), then over cells
_match_areas = jax.vmap(_match_one_cell, in_axes=(None, None, None, 0, None))
_match_cells_inner = jax.vmap(_match_areas, in_axes=(0, 0, 0, 0, None))


@jax.jit
def match_cells(ious: Array, det_valid: Array, gt_valid: Array, gt_ignores: Array, thrs: Array):
    """Batched matcher: ``ious (N, D, G)``, ``det_valid (N, D)``,
    ``gt_valid (N, G)``, ``gt_ignores (N, A, G)``, ``thrs (T,)`` →
    ``matches (N, A, T, D)``, ``matched_to_ignored (N, A, T, D)``."""
    return _match_cells_inner(ious, det_valid, gt_valid, gt_ignores, thrs)


@jax.jit
def batched_box_iou(det_boxes: Array, gt_boxes: Array) -> Array:
    """``(N, D, 4)`` × ``(N, G, 4)`` → ``(N, D, G)`` per-cell IoU; zero-area
    pads yield IoU 0 via ``box_iou``'s union guard."""
    from metrics_tpu.detection.helpers import box_iou

    return jax.vmap(box_iou)(det_boxes, gt_boxes)


@jax.jit
def batched_mask_iou(det_masks: Array, gt_masks: Array) -> Array:
    """``(N, D, HW)`` × ``(N, G, HW)`` flattened binary masks →
    ``(N, D, G)`` per-cell mask IoU, on device.

    The intersection is one batched GEMM (``einsum`` over the flattened
    pixel axis — MXU work on TPU), unions come from the same row sums, and
    zero padding is free: padded pixels and padded rows contribute nothing
    to either, and all-zero pads hit the ``union > 0`` guard. Mixed
    resolutions batch together by flatten-padding each cell's masks to the
    common ``HW`` cap. Replaces the reference's pycocotools C mask routines
    (``src/torchmetrics/detection/mean_ap.py:127-140``) with device math
    (SURVEY.md §2.9).

    Counts are exact in float32 for masks up to 2^24 pixels.
    """
    d = det_masks.astype(jnp.float32)
    g = gt_masks.astype(jnp.float32)
    inter = jnp.einsum("ndh,ngh->ndg", d, g)
    union = d.sum(-1)[:, :, None] + g.sum(-1)[:, None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1.0), 0.0)


def next_pow2(n: int) -> int:
    """Smallest power of two ≥ max(n, 1) — pad caps to bounded shapes so the
    jitted matcher compiles O(log) times across evaluations, not per eval."""
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()

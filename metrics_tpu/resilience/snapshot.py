"""Elastic, crash-safe snapshots of metric state.

The reference delegates persistence to torch's module ``state_dict`` with no
atomicity, versioning, or topology story (SURVEY §5.4): a preemption
mid-write leaves a torn file, and a job that saved on 8 workers cannot
restore on 4. This module is the TPU-native answer:

- **Atomic writes.** Each snapshot is one file written to a ``.tmp`` sibling,
  flushed, fsync'd, then ``os.replace``'d into place — a crash mid-save
  leaves the previous snapshot untouched and at worst a stale ``.tmp``.
- **Integrity.** Every state leaf carries a sha256 digest over its
  dtype/shape/bytes — and the header fields are digested too (a flipped
  ``reduced``/``world_size`` would change restore *semantics*) — plus a
  magic string and a schema-version header.
  A torn or bit-flipped file fails loudly, naming the file and the leaf;
  :meth:`SnapshotManager.restore` then falls back to the newest intact
  snapshot (recording the fallback in ``metrics_tpu.health_report()``).
- **Elastic topology.** Each rank saves its *local* (unsynced) partial
  state with ``(rank, world_size)`` recorded in the header and filename.
  On restore at a different world size, old ranks are partitioned
  contiguously over the new ranks and each partition is re-merged through
  the state's registered reduction (sum / cat / min / max, CatBuffer
  union, FaultCounters sum) — so a job preempted on 8 devices resumes on
  4 (or 1) with value-parity ``compute()`` after the next sync, instead of
  refusing to load. This is the checkpoint-side analogue of re-sharding
  replicated state across replica counts ("Automatic Cross-Replica
  Sharding of Weight Update in Data-Parallel Training", PAPERS.md).

The payload format rides :meth:`Metric.snapshot_state` /
:meth:`Metric.load_snapshot_state` (every state leaf, persistence flags
ignored, recursive over wrapper children) and the ``MetricCollection``
equivalents. Files are Python pickles of numpy trees — snapshots are
**trusted** artifacts from your own job, same trust model as torch/orbax
checkpoints.

Merge caveats: ``mean``-reduced states merge as the unweighted mean of the
per-rank partials, which is exact ONLY when the new world size divides the
old one (equal partitions — 8→4→2→1 all qualify). Uneven shrinks AND grown
worlds (share-less new ranks reset to defaults, which is not an identity
for an unweighted mean) warn loudly and record a ``snapshot_mean_approx``
health event; prefer sum+count states over ``mean`` for elastic jobs.
``dist_reduce_fx=None`` non-list states (rare) have no merge rule and
require a matching world size.
"""
import hashlib
import os
import pickle
import re
import time
import warnings
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

MAGIC = "metrics-tpu-snapshot"
SCHEMA_VERSION = 1

_FILE_RE = re.compile(r"^(?P<tag>.+)\.step(?P<step>\d+)\.rank(?P<rank>\d+)\.of(?P<world>\d+)\.snap$")
_TMP_TTL_S = 3600.0


class SnapshotError(RuntimeError):
    """Base class for snapshot load/save failures."""


class SnapshotCorruptionError(SnapshotError):
    """A snapshot file failed integrity verification (torn write, bit flip)."""


class SnapshotSchemaError(SnapshotError):
    """A snapshot was written by a newer schema than this build understands."""


# --------------------------------------------------------------------------
# integrity: per-leaf digests over a deterministic walk of the payload tree
# --------------------------------------------------------------------------


def _iter_leaves(node: Any, path: str = "") -> Iterator[Tuple[str, Any]]:
    if isinstance(node, dict):
        for k in sorted(node):
            yield from _iter_leaves(node[k], f"{path}/{k}")
    elif isinstance(node, (list, tuple)):
        for i, x in enumerate(node):
            yield from _iter_leaves(x, f"{path}/[{i}]")
    else:
        yield path, node


def _leaf_digest(leaf: Any) -> str:
    h = hashlib.sha256()
    if isinstance(leaf, np.ndarray) or hasattr(leaf, "dtype"):
        arr = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    else:
        h.update(repr(leaf).encode())
    return h.hexdigest()


def _checksum_tree(payload: Any) -> Dict[str, str]:
    return {path: _leaf_digest(leaf) for path, leaf in _iter_leaves(payload)}


def atomic_write_bytes(path: str, blob: bytes) -> None:
    """The tmp-fsync-replace write discipline, factored once: write to a
    pid-suffixed ``.tmp`` sibling, flush, fsync, ``os.replace`` into place,
    fsync the directory — a crash mid-write leaves the previous file
    untouched and at worst a stale tmp. Shared by the snapshot writer and
    the flight recorder (``obs/flightrec.py``), so the atomicity argument
    lives in exactly one implementation."""
    # fsync is a blocking seam: the lock witness flags reaching it while a
    # hot lock is held (lazy import — the lint/witness layer must never be
    # on this module's import path)
    from metrics_tpu.analysis.lockwitness import note_blocking

    note_blocking("fsync", path)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic on POSIX: readers see old or new, never torn
    try:
        dir_fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
    except OSError:  # pragma: no cover - platform-dependent (e.g. no dir fsync)
        pass


# --------------------------------------------------------------------------
# elastic merge: per-rank payloads -> one payload, through the registered
# reductions of the live target object
# --------------------------------------------------------------------------


def _merge_state_values(values: List[Any], fx: Any, default: Any, key: str, owner: str) -> Any:
    """Merge one state's per-rank serialized values, mirroring the reduce
    semantics of ``Metric._sync_dist`` (sum/mean/max/min/cat) on host numpy."""
    from metrics_tpu.utilities.guard import FaultCounters
    from metrics_tpu.utilities.ringbuffer import CatBuffer

    if len(values) == 1 and not isinstance(default, CatBuffer):
        return values[0]
    if getattr(type(default), "is_sketch_state", False):
        # per-rank sketches re-merge through their own associative union —
        # the same path a live sync runs, so 8->4->1 restores value-parity
        states = [type(default).from_primitives(v, like=default) for v in values]
        merged = states[0]
        for s in states[1:]:
            merged = merged.sketch_merge(s)
        return merged.to_primitives()
    if isinstance(default, FaultCounters):
        n = max(np.asarray(v).reshape(-1).shape[0] for v in values)
        total = np.zeros((n,), np.uint64)
        for v in values:
            arr = np.asarray(v).reshape(-1)
            total[: arr.shape[0]] += arr.astype(np.uint64)
        return total.astype(np.uint32)
    if isinstance(default, CatBuffer):
        # union-and-compact: valid rows of every rank, in (rank, slot) order,
        # packed to the front of a buffer whose capacity is the sum of the
        # partials' capacities — the same union `_sync_dist` produces, but
        # contiguous so later `cat_append`s stay well-defined
        rows, caps, dropped = [], 0, np.zeros((), np.int64)
        for v in values:
            data, mask = np.asarray(v["data"]), np.asarray(v["mask"], bool)
            rows.append(data[mask])
            caps += data.shape[0]
            if v.get("dropped") is not None:
                dropped = dropped + np.asarray(v["dropped"]).astype(np.int64)
        packed = (
            np.concatenate(rows, axis=0)
            if rows  # callers guard non-empty values, but keep the dtype right regardless
            else np.zeros((0,) + np.asarray(default.data).shape[1:], np.asarray(default.data).dtype)
        )
        data = np.zeros((caps,) + packed.shape[1:], packed.dtype)
        data[: packed.shape[0]] = packed
        mask = np.zeros((caps,), bool)
        mask[: packed.shape[0]] = True
        return {"data": data, "mask": mask, "dropped": dropped.astype(np.int32)}
    if isinstance(default, list):
        merged: List[Any] = []
        for v in values:
            merged.extend(list(v))
        return merged
    stacked = [np.asarray(v) for v in values]
    if fx == "sum":
        return np.sum(np.stack(stacked, axis=0), axis=0)
    if fx == "mean":
        return np.mean(np.stack(stacked, axis=0), axis=0)
    if fx == "max":
        return np.max(np.stack(stacked, axis=0), axis=0)
    if fx == "min":
        return np.min(np.stack(stacked, axis=0), axis=0)
    if fx == "cat":
        return np.concatenate([np.atleast_1d(v) for v in stacked], axis=0)
    raise SnapshotError(
        f"{owner}: state {key!r} has dist_reduce_fx={fx!r}, which has no elastic merge rule — "
        "restore this snapshot at its original world size"
    )


def _merge_metric_payloads(metric: Any, payloads: List[Dict[str, Any]]) -> Dict[str, Any]:
    # the bit-identical load path refuses snapshot states the target does
    # not register; the merge path must refuse identically, or a
    # config-mismatch restore silently loses state exactly when merging
    unknown = sorted(
        {k for p in payloads for k in p.get("states", {})} - set(metric._reductions)
    )
    if unknown:
        raise ValueError(
            f"{type(metric).__name__}: snapshot carries unknown state {unknown[0]!r}; "
            "refusing to merge (metric config mismatch?)"
        )
    states: Dict[str, Any] = {}
    for key, fx in metric._reductions.items():
        values = [p["states"][key] for p in payloads if key in p.get("states", {})]
        if values:
            states[key] = _merge_state_values(values, fx, metric._defaults[key], key, type(metric).__name__)
    out: Dict[str, Any] = {
        "states": states,
        "update_count": sum(int(p.get("update_count", 0)) for p in payloads),
    }
    clocks = [p["last_update_unix"] for p in payloads if p.get("last_update_unix") is not None]
    if clocks:
        out["last_update_unix"] = max(clocks)  # freshest rank wins
    attrs: Dict[str, Any] = {}
    for p in payloads:  # data-inferred attrs are rank-invariant; first wins
        for k, v in p.get("attrs", {}).items():
            attrs.setdefault(k, v)
    if attrs:
        out["attrs"] = attrs
    children = {}
    mine = dict(metric._named_child_metrics())
    unknown_children = sorted({k for p in payloads for k in p.get("children", {})} - set(mine))
    if unknown_children:
        raise ValueError(
            f"{type(metric).__name__}: snapshot carries child metric {unknown_children[0]!r} "
            "this instance does not have; refusing to merge"
        )
    for name in mine:
        child_payloads = [p["children"][name] for p in payloads if name in p.get("children", {})]
        if child_payloads:
            children[name] = _merge_metric_payloads(mine[name], child_payloads)
    if children:
        out["children"] = children
    return out


def _merge_payloads(obj: Any, payloads: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-rank snapshot payloads through ``obj``'s reduction tags.
    ``obj`` is the live restore target (Metric or MetricCollection) — it
    supplies the reduction registry the serialized payloads lack."""
    if _is_collection(obj):
        members: Dict[str, Any] = {}
        modules = dict(obj._modules)
        unknown = sorted({k for p in payloads for k in p.get("members", {})} - set(modules))
        if unknown:
            raise ValueError(
                f"MetricCollection: snapshot carries member {unknown[0]!r} this collection "
                f"does not have (members: {list(modules)}); refusing to merge"
            )
        for name, member in modules.items():
            member_payloads = [p["members"][name] for p in payloads if name in p.get("members", {})]
            if member_payloads:
                members[name] = _merge_metric_payloads(member, member_payloads)
        return {"members": members}
    return _merge_metric_payloads(obj, payloads)


def _is_collection(obj: Any) -> bool:
    return hasattr(obj, "_modules") and hasattr(obj, "snapshot_state")


def _has_mean_state(obj: Any) -> bool:
    """Whether any state (recursively) merges by unweighted mean — the one
    reduction whose elastic merge is exact only for equal partitions."""
    if _is_collection(obj):
        return any(_has_mean_state(m) for m in obj._modules.values())
    if any(fx == "mean" for fx in obj._reductions.values()):
        return True
    return any(_has_mean_state(child) for _name, child in obj._named_child_metrics())


# --------------------------------------------------------------------------
# the manager
# --------------------------------------------------------------------------


class SnapshotManager:
    """Rolling, checksummed, topology-aware snapshots in one directory.

    Example (single process)::

        mgr = SnapshotManager("/ckpt/metrics", keep=3)
        mgr.save(collection, step=epoch)            # atomic + pruned to 3
        info = mgr.restore(collection)              # newest intact snapshot

    Multi-host elastic use: every process calls ``save(obj, step, rank=r,
    world_size=W)`` into shared storage; after preemption, the resumed job
    (any world size W') calls ``restore(obj, rank=r', world_size=W')`` and
    each new rank re-merges its contiguous share of the old per-rank
    partials through the registered reductions. Ranks that receive no share
    (W' > W) reset to defaults — the global reduction is preserved for
    sum/cat/min/max/FaultCounters states ('mean' states warn: see the
    module docstring caveat).
    """

    def __init__(
        self,
        directory: str,
        tag: str = "metrics",
        keep: int = 3,
        group_verification: str = "full",
    ) -> None:
        if keep < 1:
            raise ValueError(f"`keep` must be >= 1, got {keep}")
        if group_verification not in ("full", "assigned"):
            raise ValueError(
                f"`group_verification` must be 'full' or 'assigned', got {group_verification!r}"
            )
        self.directory = str(directory)
        self.tag = tag
        self.keep = keep
        # 'full' (default): every restoring rank checksums every rank file of
        # a group, so all ranks make the SAME intact/fallback decision —
        # right for small/medium worlds. 'assigned': each rank fully
        # verifies only its own share (+ old rank 0's header) and
        # presence-checks the rest — O(share) reads instead of O(old world)
        # per rank, for large worlds whose job layer coordinates fallback
        # (a rank whose share is intact can otherwise disagree with one
        # whose share is corrupt)
        self.group_verification = group_verification
        os.makedirs(self.directory, exist_ok=True)

    # -- naming ---------------------------------------------------------

    def _filename(self, step: int, rank: int, world_size: int) -> str:
        return f"{self.tag}.step{step:010d}.rank{rank:05d}.of{world_size:05d}.snap"

    def _scan(self) -> Dict[Tuple[int, int], Dict[int, str]]:
        """{(step, world): {rank: path}} for this manager's tag."""
        groups: Dict[Tuple[int, int], Dict[int, str]] = {}
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return groups
        for name in names:
            m = _FILE_RE.match(name)
            if m is None or m.group("tag") != self.tag:
                continue
            key = (int(m.group("step")), int(m.group("world")))
            groups.setdefault(key, {})[int(m.group("rank"))] = os.path.join(self.directory, name)
        return groups

    def steps(self) -> List[int]:
        """Steps with at least one snapshot file, ascending."""
        return sorted({step for (step, _world) in self._scan()})

    # -- save -----------------------------------------------------------

    def save(
        self,
        obj: Any,
        step: int,
        rank: int = 0,
        world_size: int = 1,
        reduced: bool = False,
        mesh_axes: Optional[Dict[str, int]] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Write one atomic snapshot of ``obj``'s full state; returns its path.

        ``rank``/``world_size`` record the saving topology (``rank`` must be
        this process's rank; every rank saves its *local*, unsynced state).
        ``reduced=True`` marks the state as already globally reduced (saved
        post-sync, e.g. from rank 0 after ``compute()``): on restore it loads
        on rank 0 only, with every other rank reset to defaults, so the next
        sync does not multiply-count it. ``mesh_axes`` (optional
        ``{axis_name: size}``) and ``extra`` are recorded verbatim in the
        header for the resuming job.
        """
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} outside world of size {world_size}")
        if reduced and world_size != 1:
            raise ValueError("reduced=True snapshots are global state — save them with world_size=1")
        from metrics_tpu import __version__
        from metrics_tpu.obs import trace as _obs_trace

        with _obs_trace.span("snapshot.save", step=int(step), rank=int(rank)):
            payload = obj.snapshot_state()
            header = {
                "step": int(step),
                "rank": int(rank),
                "world_size": int(world_size),
                "reduced": bool(reduced),
                "mesh_axes": dict(mesh_axes) if mesh_axes else None,
                "created_unix": time.time(),
                "library_version": __version__,
                "extra": dict(extra) if extra else None,
            }
            blob = pickle.dumps(
                {
                    "magic": MAGIC,
                    "schema_version": SCHEMA_VERSION,
                    "header": header,
                    "payload": payload,
                    # header is covered too: a bit-flipped `reduced`/`world_size`
                    # would silently change restore SEMANTICS, not just values
                    "checksums": _checksum_tree({"header": header, "payload": payload}),
                },
                protocol=4,
            )
            final = os.path.join(self.directory, self._filename(step, rank, world_size))
            atomic_write_bytes(final, blob)
            self._prune(rank)
            return final

    def _prune(self, rank: int) -> None:
        """Keep the newest ``self.keep`` steps of THIS rank's files (each
        rank prunes only what it wrote — safe on shared storage) and clear
        stale tmp files left by crashed writers."""
        mine: Dict[int, List[str]] = {}
        for (step, _world), files in self._scan().items():
            if rank in files:
                mine.setdefault(step, []).append(files[rank])
        for step in sorted(mine)[: -self.keep] if len(mine) > self.keep else []:
            for path in mine[step]:
                try:
                    os.unlink(path)
                except OSError:  # pragma: no cover - racing prune from another run
                    pass
        now = time.time()
        for name in os.listdir(self.directory):
            if ".snap.tmp." in name and name.startswith(self.tag + "."):
                path = os.path.join(self.directory, name)
                try:
                    if now - os.path.getmtime(path) > _TMP_TTL_S:
                        os.unlink(path)
                except OSError:  # pragma: no cover
                    pass

    # -- load -----------------------------------------------------------

    def load_file(self, path: str, verify: bool = True) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Read + verify one snapshot file → ``(header, payload)``.

        Raises :class:`SnapshotCorruptionError` (torn/bit-flipped file,
        checksum mismatch) or :class:`SnapshotSchemaError` (written by a
        newer schema), always naming the snapshot file.
        """
        try:
            with open(path, "rb") as f:
                record = pickle.load(f)
        except FileNotFoundError:
            raise SnapshotError(f"snapshot {path} does not exist")
        except Exception as err:
            raise SnapshotCorruptionError(
                f"snapshot {path} is unreadable ({type(err).__name__}: {err}) — torn write or corruption"
            )
        if not isinstance(record, dict) or record.get("magic") != MAGIC:
            raise SnapshotCorruptionError(f"snapshot {path} has no {MAGIC!r} magic header")
        version = record.get("schema_version")
        if not isinstance(version, int) or version > SCHEMA_VERSION:
            raise SnapshotSchemaError(
                f"snapshot {path} has schema version {version!r}; this build understands <= "
                f"{SCHEMA_VERSION} — upgrade metrics_tpu to restore it"
            )
        if verify:
            stored = record.get("checksums")
            computed = _checksum_tree({"header": record.get("header"), "payload": record.get("payload")})
            if stored != computed:
                bad = sorted(
                    set(stored or {}).symmetric_difference(computed)
                    | {k for k in (stored or {}) if k in computed and stored[k] != computed[k]}
                )
                raise SnapshotCorruptionError(
                    f"snapshot {path} failed checksum verification at leaf "
                    f"{bad[0] if bad else '<manifest>'} — corrupt state refused"
                )
        return record["header"], record["payload"]

    def latest_intact(self) -> Optional[Tuple[int, int]]:
        """Newest ``(step, world_size)`` whose snapshot group is complete and
        verifies, or None."""
        for (step, world), files in sorted(self._scan().items(), reverse=True):
            try:
                self._verify_group(step, world, files, keep=frozenset(), force_full=True)
            except SnapshotError:
                continue
            return step, world
        return None

    def _verify_group(
        self, step: int, world: int, files: Dict[int, str], keep: Any, force_full: bool = False
    ) -> Tuple[Dict[int, Dict[str, Any]], Dict[int, Dict[str, Any]]]:
        """Group intactness check → ``(headers, payloads)`` dicts keyed by
        old rank, with payloads retained only for the ``keep`` ranks
        (unassigned payloads are checksummed and dropped under 'full'
        verification, or not read at all under 'assigned' — restore memory
        is O(assigned share) either way; see ``group_verification`` in the
        constructor for the read-cost/consistency trade-off)."""
        missing = sorted(set(range(world)) - set(files))
        if missing:
            raise SnapshotError(
                f"snapshot step {step} incomplete: missing rank file(s) {missing} of world {world}"
            )
        full = force_full or self.group_verification == "full"
        headers: Dict[int, Dict[str, Any]] = {}
        payloads: Dict[int, Dict[str, Any]] = {}
        for r in range(world):
            # old rank 0's header always loads: it carries the reduced flag
            if full or r in keep or r == 0:
                header, payload = self.load_file(files[r])
                headers[r] = header
                if r in keep:
                    payloads[r] = payload
            elif os.path.getsize(files[r]) == 0:
                raise SnapshotCorruptionError(f"snapshot {files[r]} is empty — torn write")
        return headers, payloads

    def restore(self, obj: Any, rank: int = 0, world_size: int = 1) -> Dict[str, Any]:
        """Restore ``obj`` from the newest intact snapshot group.

        Corrupt or incomplete groups are skipped (loud warning + a
        ``snapshot_fallback`` event in ``metrics_tpu.health_report()``) in
        favor of the next older intact group; when no intact group remains,
        the newest group's error re-raises, naming the snapshot. Returns an
        info dict: ``{"step", "old_world", "world_size", "merged_ranks",
        "reduced", "fallbacks"}``.
        """
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} outside world of size {world_size}")
        from metrics_tpu.obs import trace as _obs_trace

        with _obs_trace.span("snapshot.restore", rank=int(rank)):
            return self._restore_newest(obj, rank, world_size)

    def _restore_newest(self, obj: Any, rank: int, world_size: int) -> Dict[str, Any]:
        first_err: Optional[SnapshotError] = None
        fallbacks = 0
        for (step, world), files in sorted(self._scan().items(), reverse=True):
            # keep=assigned covers reduced groups too: reduced implies
            # world==1, whose only payload (old rank 0) maps to new rank 0's
            # assignment; every other rank resets without reading a payload
            assigned = [o for o in range(world) if (o * world_size) // world == rank]
            try:
                headers, payloads = self._verify_group(step, world, files, keep=set(assigned))
            except SnapshotError as err:
                if first_err is None:
                    first_err = err
                fallbacks += 1
                warnings.warn(
                    f"SnapshotManager: skipping snapshot step {step} ({err}); "
                    "falling back to the next older snapshot",
                    UserWarning,
                )
                from metrics_tpu.resilience.health import record_degradation

                record_degradation("snapshot_fallback", str(err), step=step, directory=self.directory)
                continue
            info = self._restore_group(obj, step, world, headers, payloads, assigned, rank, world_size)
            info["fallbacks"] = fallbacks
            return info
        if first_err is not None:
            raise first_err
        raise SnapshotError(f"no {self.tag!r} snapshots found under {self.directory}")

    def _restore_group(
        self,
        obj: Any,
        step: int,
        old_world: int,
        headers: Dict[int, Dict[str, Any]],
        payloads: Dict[int, Dict[str, Any]],
        assigned: List[int],
        rank: int,
        world_size: int,
    ) -> Dict[str, Any]:
        reduced = bool(headers[0].get("reduced"))
        info = {
            "step": step,
            "old_world": old_world,
            "world_size": world_size,
            "reduced": reduced,
            "merged_ranks": [],
        }
        if reduced:
            # globally reduced state: rank 0 carries it, everyone else is the
            # reduction identity, so the next sync reproduces the global value
            if rank == 0:
                obj.load_snapshot_state(payloads[0])
                info["merged_ranks"] = [0]
            else:
                obj.reset()
            return info
        # `assigned` is the contiguous partition of old ranks over new ranks
        # (preserves rank order under later cat-style syncs): old rank o ->
        # new rank floor(o * world_size / old_world)
        info["merged_ranks"] = assigned
        # non-divisible worlds break the unweighted mean GLOBALLY, so every
        # rank must warn — including one whose own share is a single old
        # rank (its local merge is trivially exact, the synced value isn't).
        # Grown worlds are subsumed: old_world % world_size == old_world != 0
        if old_world % world_size != 0 and _has_mean_state(obj):
            # the unweighted-over-ranks mean the live sync computes survives
            # an elastic hop only for equal partitions: uneven shrink merges
            # unequal-weight partition means, and a GROWN world is worse —
            # share-less ranks reset to defaults, and there is no identity
            # element for an unweighted mean, so the next sync dilutes the
            # value. Loud, because the drift is otherwise silent
            warnings.warn(
                f"SnapshotManager: restoring 'mean'-reduced state from world {old_world} onto "
                f"world {world_size}: merged means are approximate (exact only when the new "
                "world size divides the saved one). Prefer sum+count states over 'mean' for "
                "elastic jobs.",
                UserWarning,
            )
            from metrics_tpu.resilience.health import record_degradation

            record_degradation(
                "snapshot_mean_approx",
                f"elastic restore {old_world}->{world_size} with 'mean'-reduced state",
                step=step,
            )
        if not assigned:
            obj.reset()  # a grown world: this new rank starts from defaults
        elif len(assigned) == 1 and old_world == world_size:
            obj.load_snapshot_state(payloads[assigned[0]])  # bit-identical path
        else:
            obj.load_snapshot_state(_merge_payloads(obj, [payloads[o] for o in assigned]))
        return info

"""Process-wide degradation registry + ``health_report()``.

PR 2 gave the framework three independent degradation channels: the
in-graph fault counters (``utilities/guard.py``), the retrying multihost
gather's local-only fallback (``parallel/sync.py::RetryingGather``), and
the bench driver's backend probes. Each surfaced through its own warning;
nothing aggregated them, so "is this job degraded, and how?" had no single
answer. This module is that answer:

- every degradation event — backend probe timeout/failure, forced-CPU
  escape hatch, gather local-only fallback, snapshot corruption fallback —
  lands in one bounded in-process :class:`HealthRegistry` via
  :func:`record_degradation`;
- :func:`health_report` renders the registry plus the backend bootstrap
  state (``utilities/backend.py``) plus, for any metrics passed in, their
  fault counters and overflow drop counts, as one plain dict.

The registry is deliberately host-side and stdlib-only: it must stay
usable precisely when the accelerator stack is wedged.
"""
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

# the witness layer is stdlib-only python (ast/threading), so this import
# keeps the works-while-wedged contract above
from metrics_tpu.analysis.lockwitness import named_lock

# Known degradation kinds (informative, not enforced — new subsystems may
# record new kinds without touching this module):
#   backend_probe_timeout  backend init probe exceeded its deadline
#   backend_probe_failed   backend init probe exited non-zero
#   forced_cpu             METRICS_TPU_FORCE_CPU / probe fallback re-pointed jax at CPU
#   gather_degraded        multihost gather fell back to local-only state
#   snapshot_fallback      a corrupt/incomplete snapshot was skipped for an older intact one
#   overload_shed          a ServeLoop ingest queue was full and a request was shed
#                          (metrics_tpu/serving — graceful overload degradation, counted
#                          so accepted + shed always reconciles with offered)
#   serve_update_error     a ServeLoop worker's update raised; the request was dropped
#   async_sync_error       an overlapped sync cycle's gather/reduce raised; readers keep
#                          the previous (staler) reduced view and the cadence retries
#   async_sync_stalled     an overlapped sync cycle overran its deadline; readers keep
#                          serving the previous view while staleness grows
#   serve_worker_died      a ServeLoop worker thread exited outside the stop handshake;
#                          its published state keeps serving but its queue share no
#                          longer drains (metrics_tpu/serving)
#   fleet_payload_rejected an aggregator refused a published view (checksum/schema
#                          failure or metric-config mismatch), naming host and leaf
#                          (metrics_tpu/fleet)
#   fleet_publish_error    a host's view push to an aggregator exhausted its
#                          retry/timeout budget; the host keeps serving, the
#                          destination's breaker opens (metrics_tpu/fleet)
#   fleet_host_stale       a host view aged past the staleness threshold — recorded on
#                          the aggregator (nothing received) and/or the publisher
#                          (nothing delivered); cleared by the next accepted view
#   fleet_publish_recovered a previously-stale publish channel delivered again (the
#                          recovery edge, so stale episodes are bounded in the log)
#   fleet_seq_regression   an aggregator answered 'duplicate' repeatedly while holding a
#                          seq strictly ABOVE the publisher's (host restarted after a
#                          backward clock step); the publisher jumped its sequence past
#                          it (held == ours is the benign idempotent-retry case: no jump)
#   serve_warmup_done      a ServeLoop's AOT warmup finished precompiling its matrix
#                          (serving/warmup.py) — INFORMATIONAL: a normal-operation
#                          milestone that never flips `degraded` (see
#                          INFORMATIONAL_EVENT_KINDS), recorded so "when did this host
#                          go zero-trace" is datable next to real degradations
#   serve_warmup_error     a ServeLoop's AOT warmup thread failed; serving continues on
#                          the normal tracing path (degraded cold-start latency only)
#   serve_aot_evicted      a warmed executable rejected its arguments at call time and
#                          was evicted from the shared table — that shape serves through
#                          the normal jit path for the rest of the process
#                          (serving/warmup.py; also counted as serve_aot_evicted_total)
#   drift_detected         a DriftMonitor's live traffic window crossed a drift
#                          threshold vs its blessed reference (obs/drift.py) — recorded
#                          ONCE per episode (hysteresis-gated: a flapping score cannot
#                          wheel this ring), naming the monitor and breaching scores
#   drift_recovered        the drift episode ended: every score back under threshold
#                          for `clear_after` consecutive checks (the recovery edge, so
#                          drift episodes are bounded in the log like fleet staleness)
#   drift_check_error      a drift check/observe raised on the serving cadence; the
#                          monitor keeps its previous scores and the cadence retries
#                          (episode-gated once per monitor — metrics_tpu/serving)
#   drift_baseline_loaded  a DriftMonitor attached a ReferenceWindow — INFORMATIONAL:
#                          a normal-operation milestone that never flips `degraded`,
#                          recorded so "when was this baseline blessed" is datable
#                          next to any later drift_detected
_MAX_EVENTS = 256

# event kinds that are operational milestones, not degradations: reported,
# counted, datable — but excluded from the `degraded` flag (the
# INFORMATIONAL_FAULT_CLASSES stance applied to registry events)
INFORMATIONAL_EVENT_KINDS = frozenset({"serve_warmup_done", "drift_baseline_loaded"})


class HealthRegistry:
    """Bounded, thread-safe event log of degradations in this process.

    Two stores with different retention: the bounded event RING (full
    messages + details, newest ``max_events`` — a flood of one kind, e.g.
    ``overload_shed`` under spike load, evicts older entries), and the
    per-kind TABLE that never evicts — occurrence count plus first/last
    wall-clock and last monotonic timestamps per kind — so a degradation
    that happened stays countable and datable however noisy the ring got
    since. Events carry both clocks: ``time_unix`` for correlation with
    external logs, ``time_mono`` for in-process interval arithmetic that
    must survive wall-clock steps (NTP slew, clock jumps)."""

    def __init__(self, max_events: int = _MAX_EVENTS) -> None:
        self._lock = named_lock("health.HealthRegistry._lock", threading.Lock(), hot=True)
        self._events: "deque[Dict[str, Any]]" = deque(maxlen=max_events)
        self._kinds: Dict[str, Dict[str, Any]] = {}
        # event listeners (obs/flightrec.py's degraded-edge trigger): called
        # per recorded event, OUTSIDE the lock, on the recording thread — a
        # raising listener is dropped from the record path, never the caller
        self._listeners: List[Any] = []

    def add_listener(self, fn: Any) -> None:
        """Register ``fn(event_dict)``, called per recorded event on the
        recording thread (after the ring/table update, outside the lock).
        Listeners must be cheap and must not re-enter :meth:`record` for
        the same trigger (the flight recorder guards its own re-entrancy)."""
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_listener(self, fn: Any) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)

    def record(self, kind: str, message: str, **details: Any) -> Dict[str, Any]:
        now_unix, now_mono = time.time(), time.monotonic()
        event: Dict[str, Any] = {
            "kind": kind,
            "message": message,
            "time_unix": now_unix,
            "time_mono": now_mono,
        }
        if details:
            event["details"] = details
        with self._lock:
            self._events.append(event)
            entry = self._kinds.get(kind)
            if entry is None:
                self._kinds[kind] = {
                    "count": 1,
                    "first_unix": now_unix,
                    "last_unix": now_unix,
                    "last_mono": now_mono,
                }
            else:
                entry["count"] += 1
                entry["last_unix"] = now_unix
                entry["last_mono"] = now_mono
        for fn in list(self._listeners):
            try:
                fn(event)
            except Exception:  # noqa: BLE001 — telemetry degrades, never the caller's seam
                pass
        return event

    def events(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            events = list(self._events)
        if kind is not None:
            events = [e for e in events if e["kind"] == kind]
        return events

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {kind: entry["count"] for kind, entry in self._kinds.items()}

    def kinds(self) -> Dict[str, Dict[str, Any]]:
        """The never-evicting per-kind table (count + first/last seen)."""
        with self._lock:
            return {kind: dict(entry) for kind, entry in self._kinds.items()}

    @property
    def degraded(self) -> bool:
        with self._lock:
            return bool(self._kinds)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._kinds.clear()


registry = HealthRegistry()


def record_degradation(kind: str, message: str, **details: Any) -> Dict[str, Any]:
    """Record one degradation event in the process-wide registry."""
    return registry.record(kind, message, **details)


_DEGRADED_KEYS = ("faults", "overflow_dropped")


def _metric_health(metric: Any) -> Dict[str, Any]:
    """Fault/overflow/staleness view of one ``Metric`` (host-side reads
    only). Staleness — the last-update step and wall-clock, plus the age in
    seconds — makes a *stalled* stream visible next to the fault counters:
    a metric whose faults are clean but whose ``staleness_s`` keeps growing
    is not being fed. Staleness alone does not flip the report's
    ``degraded`` flag (only the :data:`_DEGRADED_KEYS` do) — how stale is
    too stale is a deployment question, not a library one."""
    entry: Dict[str, Any] = {}
    faults = getattr(metric, "fault_counts", None)
    if faults:
        # function-level import: guard pulls in jax, and this module must
        # stay importable with the jax stack wedged — but reaching here
        # means the caller passed a constructed Metric, so jax is already up
        from metrics_tpu.utilities.guard import INFORMATIONAL_FAULT_CLASSES

        nonzero = {
            k: v for k, v in faults.items() if v and k not in INFORMATIONAL_FAULT_CLASSES
        }
        if nonzero:
            entry["faults"] = nonzero
        # informational classes (padding is normal serving operation):
        # reported — the pad volume is an interesting operational number —
        # but never `degraded`
        for name in INFORMATIONAL_FAULT_CLASSES:
            count = faults.get(name)
            if count:
                entry[name] = count
    dropped = getattr(metric, "dropped_count", None)
    if dropped:
        entry["overflow_dropped"] = dropped
    if getattr(metric, "sync_mode", "blocking") == "overlapped":
        # overlapped async sync (parallel/async_sync.py): how far the
        # double-buffered reduced view trails the live accumulator, in
        # update steps and wall-clock. Informational like staleness — an
        # operator decides how much lag is too much; only a scheduler
        # degradation event (async_sync_error/_stalled) flips `degraded`.
        lag = getattr(metric, "sync_lag", None)
        if lag is not None:
            entry["sync_mode"] = "overlapped"
            entry["sync_lag_steps"] = lag.get("sync_lag_steps")
            entry["sync_lag_s"] = lag.get("sync_lag_s")
            if lag.get("in_flight"):
                entry["sync_in_flight"] = True
    last = getattr(metric, "_last_update_unix", None)
    if last is not None:
        entry["last_update_unix"] = last
        entry["last_update_step"] = getattr(metric, "update_count", None)
        entry["staleness_s"] = max(0.0, time.time() - last)
    elif hasattr(metric, "_last_update_unix"):
        entry["never_updated"] = True
    return entry


def health_report(*metrics: Any) -> Dict[str, Any]:
    """One dict describing every known degradation in this process.

    ``metrics`` (optional) are ``Metric`` or ``MetricCollection`` instances
    whose fault counters / overflow drops should be folded into the report
    (they hold per-instance state the process-wide registry cannot see).
    The report is plain JSON-serializable data::

        {"backend": {...bootstrap state...},
         "events": [...degradation events, oldest first...],
         "event_counts": {kind: n},
         "event_kinds": {kind: {"count", "first_unix", "last_unix",
                                "last_mono"}},   # never evicts (ring does)
         "informational_event_kinds": [...],  # the milestone kinds, always
         "runtime": {"counters": {...}, "histograms": {...}},  # when any
         "metrics": {name: {"faults": {...}, "overflow_dropped": n,
                            "last_update_unix": t, "last_update_step": s,
                            "staleness_s": age}},
         "degraded": bool}

    ``event_counts``/``event_kinds`` list EVERY recorded kind — loud
    degradations and informational milestones side by side (the table is
    the one never-evicting record, so a milestone must be datable there
    too); ``informational_event_kinds`` names which kinds are milestones
    (:data:`INFORMATIONAL_EVENT_KINDS` — ``serve_warmup_done``,
    ``drift_baseline_loaded``), so a consumer can partition the table
    without importing this module. ``degraded`` is True when any
    NON-informational registry event OR any reported metric
    fault/overflow exists. Staleness (``last_update_*``/``staleness_s``,
    or ``never_updated``) is informational — a stalled stream is visible
    but does not flip the flag by itself.
    """
    from metrics_tpu.utilities.backend import backend_status

    report: Dict[str, Any] = {
        "backend": backend_status(),
        "events": registry.events(),
        "event_counts": registry.counts(),
        "event_kinds": registry.kinds(),
        "informational_event_kinds": sorted(INFORMATIONAL_EVENT_KINDS),
        "metrics": {},
    }
    # self-telemetry summary (obs/runtime_metrics.py), LIGHT form only:
    # counters plus histogram counts/sums — pure python, honoring this
    # module's works-while-wedged contract (quantiles are the exporters'
    # job: ServeLoop.scrape() / obs.prometheus_text)
    from metrics_tpu.obs.runtime_metrics import registry as _runtime_registry

    runtime = _runtime_registry.snapshot(quantiles=False)
    if runtime["counters"] or runtime["histograms"]:
        report["runtime"] = runtime
    seen: Dict[str, int] = {}
    for obj in metrics:
        # copy_state=False: this is a read-only fault-counter sweep — the
        # default copy would materialize per-member copies of group-aliased
        # ring states and flip the collection's aliasing flag
        members = (
            obj.items(keep_base=True, copy_state=False)
            if hasattr(obj, "items") and hasattr(obj, "_modules")
            else None
        )
        for name, metric in members if members is not None else [(type(obj).__name__, obj)]:
            entry = _metric_health(metric)
            if entry:
                # two bare instances of one class must not collide (the
                # second would silently overwrite the first's faults)
                seen[name] = seen.get(name, 0) + 1
                report["metrics"][name if seen[name] == 1 else f"{name}#{seen[name]}"] = entry
    report["degraded"] = bool(
        set(report["event_counts"]) - INFORMATIONAL_EVENT_KINDS
    ) or any(
        any(k in entry for k in _DEGRADED_KEYS) for entry in report["metrics"].values()
    )
    return report

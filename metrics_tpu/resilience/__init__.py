"""Resilience subsystem: crash-safe elastic snapshots + degradation health.

Two pillars (VERDICT r5 weak #4 / next-round #4):

- :mod:`metrics_tpu.resilience.snapshot` — ``SnapshotManager``: atomic,
  checksummed, schema-versioned snapshots of any ``Metric`` /
  ``MetricCollection`` state with rolling retention, corruption fallback,
  and elastic world-size restore (per-rank partials re-merged through each
  state's registered reduction, so a job preempted on 8 devices resumes on
  4 or 1 with value-parity ``compute()``).
- :mod:`metrics_tpu.resilience.health` — one process-wide registry where
  every degradation lands (backend probe timeouts, gather local-only
  fallbacks, snapshot corruption fallbacks) and ``health_report()``, the
  single pane of glass over those events plus any metric's fault counters.
"""
from metrics_tpu.resilience.health import HealthRegistry, health_report, record_degradation, registry
from metrics_tpu.resilience.snapshot import (
    SnapshotCorruptionError,
    SnapshotError,
    SnapshotManager,
    SnapshotSchemaError,
)

__all__ = [
    "HealthRegistry",
    "SnapshotCorruptionError",
    "SnapshotError",
    "SnapshotManager",
    "SnapshotSchemaError",
    "health_report",
    "record_degradation",
    "registry",
]

"""Regression module metrics (reference
``src/torchmetrics/regression/__init__.py``)."""
from metrics_tpu.regression.basic import (  # noqa: F401
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    SymmetricMeanAbsolutePercentageError,
    WeightedMeanAbsolutePercentageError,
)
from metrics_tpu.regression.cosine_similarity import CosineSimilarity  # noqa: F401
from metrics_tpu.regression.explained_variance import ExplainedVariance  # noqa: F401
from metrics_tpu.regression.pearson import PearsonCorrCoef  # noqa: F401
from metrics_tpu.regression.r2 import R2Score  # noqa: F401
from metrics_tpu.regression.spearman import SpearmanCorrCoef  # noqa: F401
from metrics_tpu.regression.tweedie_deviance import TweedieDevianceScore  # noqa: F401

"""Sum-state regression module metrics (reference
``src/torchmetrics/regression/{mse,mae,log_mse,mape,symmetric_mape,wmape}.py``).

All six share the same shape: two scalar ``sum`` states, fully jittable
update, one ``psum`` to sync.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.mae import _mean_absolute_error_compute, _mean_absolute_error_update
from metrics_tpu.functional.regression.mape import (
    _mean_absolute_percentage_error_compute,
    _mean_absolute_percentage_error_update,
)
from metrics_tpu.functional.regression.log_mse import (
    _mean_squared_log_error_compute,
    _mean_squared_log_error_update,
)
from metrics_tpu.functional.regression.mse import _mean_squared_error_compute, _mean_squared_error_update
from metrics_tpu.functional.regression.symmetric_mape import (
    _symmetric_mean_absolute_percentage_error_compute,
    _symmetric_mean_absolute_percentage_error_update,
)
from metrics_tpu.functional.regression.wmape import (
    _weighted_mean_absolute_percentage_error_compute,
    _weighted_mean_absolute_percentage_error_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class MeanSquaredError(Metric):
    """MSE / RMSE (reference ``regression/mse.py:22``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = MeanSquaredError()
        >>> round(float(metric(preds, target)), 4)
        0.375
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, squared: bool = True, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(squared, bool):
            raise ValueError(f"Expected argument `squared` to be a boolean but got {squared}")
        self.squared = squared
        self.num_outputs = num_outputs
        self.add_state("sum_squared_error", default=jnp.zeros(() if num_outputs == 1 else (num_outputs,)), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_error, n_obs = _mean_squared_error_update(preds, target, self.num_outputs)
        self.sum_squared_error += sum_squared_error
        self.total += n_obs

    def compute(self) -> Array:
        return _mean_squared_error_compute(self.sum_squared_error, self.total, squared=self.squared)


class MeanAbsoluteError(Metric):
    """MAE (reference ``regression/mae.py:22``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanAbsoluteError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = MeanAbsoluteError()
        >>> round(float(metric(preds, target)), 4)
        0.5
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
        self.sum_abs_error += sum_abs_error
        self.total += n_obs

    def compute(self) -> Array:
        return _mean_absolute_error_compute(self.sum_abs_error, self.total)


class MeanSquaredLogError(Metric):
    """MSLE (reference ``regression/log_mse.py:22``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredLogError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = MeanSquaredLogError()
        >>> round(float(metric(preds, target)), 4)
        0.128
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_squared_log_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_log_error, n_obs = _mean_squared_log_error_update(preds, target)
        self.sum_squared_log_error += sum_squared_log_error
        self.total += n_obs

    def compute(self) -> Array:
        return _mean_squared_log_error_compute(self.sum_squared_log_error, self.total)


class MeanAbsolutePercentageError(Metric):
    """MAPE (reference ``regression/mape.py:22``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanAbsolutePercentageError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = MeanAbsolutePercentageError()
        >>> round(float(metric(preds, target)), 4)
        0.3274
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_per_error, num_obs = _mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_per_error += sum_abs_per_error
        self.total += num_obs

    def compute(self) -> Array:
        return _mean_absolute_percentage_error_compute(self.sum_abs_per_error, self.total)


class SymmetricMeanAbsolutePercentageError(Metric):
    """SMAPE (reference ``regression/symmetric_mape.py:22``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SymmetricMeanAbsolutePercentageError
        >>> metric = SymmetricMeanAbsolutePercentageError()
        >>> round(float(metric(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))), 4)
        0.5788
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_per_error, num_obs = _symmetric_mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_per_error += sum_abs_per_error
        self.total += num_obs

    def compute(self) -> Array:
        return _symmetric_mean_absolute_percentage_error_compute(self.sum_abs_per_error, self.total)


class WeightedMeanAbsolutePercentageError(Metric):
    """WMAPE (reference ``regression/wmape.py:22``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import WeightedMeanAbsolutePercentageError
        >>> metric = WeightedMeanAbsolutePercentageError()
        >>> round(float(metric(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))), 4)
        0.16
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_scale", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_error, sum_scale = _weighted_mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_error += sum_abs_error
        self.sum_scale += sum_scale

    def compute(self) -> Array:
        return _weighted_mean_absolute_percentage_error_compute(self.sum_abs_error, self.sum_scale)

"""``TweedieDevianceScore`` module metric (reference
``src/torchmetrics/regression/tweedie_deviance.py``).
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.tweedie_deviance import (
    _tweedie_deviance_score_compute,
    _tweedie_deviance_score_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class TweedieDevianceScore(Metric):
    """Tweedie deviance (reference ``tweedie_deviance.py:24-104``).

    .. note::
        ``higher_is_better`` is **False** here; the reference leaves the
        flag unset (``None``). A deviance is a loss: lower is better (PARITY.md "Class behavior-flag
        divergences" — strictly more informative for ``MetricTracker.best_metric``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import TweedieDevianceScore
        >>> metric = TweedieDevianceScore()
        >>> round(float(metric(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))), 4)
        0.375
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, power: float = 0.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if 0 < power < 1:
            raise ValueError(f"Deviance Score is not defined for power={power}.")
        self.power = power
        self.add_state("sum_deviance_score", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("num_observations", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, targets: Array) -> None:
        sum_deviance_score, num_observations = _tweedie_deviance_score_update(preds, targets, self.power)
        self.sum_deviance_score = self.sum_deviance_score + sum_deviance_score
        self.num_observations = self.num_observations + num_observations

    def compute(self) -> Array:
        return _tweedie_deviance_score_compute(self.sum_deviance_score, self.num_observations)

"""``CosineSimilarity`` module metric (reference
``src/torchmetrics/regression/cosine_similarity.py``).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.cosine_similarity import (
    _cosine_similarity_compute,
    _cosine_similarity_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.ringbuffer import CatBuffer, cat_append, reject_valid_kwarg

Array = jax.Array


class CosineSimilarity(Metric):
    """Cosine similarity over accumulated rows (reference
    ``cosine_similarity.py:22-77``).

    Two accumulation modes:

    - default: raw preds/target rows accumulate in ``cat`` list states (the
      reference's pattern, ``cosine_similarity.py:40-41``).
    - ``capacity=N``: static-shape, fully jittable/shardable state. For
      ``reduction='sum'|'mean'`` the state is a **moment sum** — per-row
      similarities fold into two scalar ``sum`` states, which is EXACT for
      any number of samples (nothing is dropped; ``capacity`` only bounds
      the ``'none'``/``None`` per-row output, which uses a
      :class:`CatBuffer` of per-row similarities and drops past capacity
      with an observable ``dropped`` counter). In ``'none'`` mode compute
      returns the full ``(capacity,)`` buffer with **NaN** padding at
      unfilled slots — static shapes cannot carry the true row count, and
      NaN makes accidental reductions over padding loud. Use eager mode
      (no ``capacity``) for the reference's exact ``(N,)`` output.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CosineSimilarity
        >>> metric = CosineSimilarity(reduction='mean')
        >>> round(float(metric(jnp.asarray([[1.0, 2.0, 3.0]]), jnp.asarray([[2.0, 4.0, 6.0]]))), 4)
        1.0
        >>> streaming = CosineSimilarity(reduction='mean', capacity=8)
        >>> streaming.update(jnp.asarray([[1.0, 2.0, 3.0]]), jnp.asarray([[2.0, 4.0, 6.0]]))
        >>> round(float(streaming.compute()), 4)
        1.0
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(
        self, reduction: Optional[str] = "sum", capacity: Optional[int] = None, **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.capacity = capacity
        if capacity is not None:
            if reduction in ("sum", "mean"):
                self.add_state("sum_sim", default=jnp.asarray(0.0), dist_reduce_fx="sum")
                self.add_state("n_total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
            else:
                self.add_state(
                    "sims", default=CatBuffer.zeros(capacity, (), jnp.float32), dist_reduce_fx="cat"
                )
        else:
            # rows are (d,) embeddings with data-dependent d — ragged,
            # so template=None by declaration
            self.add_state("preds", default=[], dist_reduce_fx="cat", template=None)
            self.add_state("target", default=[], dist_reduce_fx="cat", template=None)

    def update(self, preds: Array, target: Array, valid: Optional[Array] = None) -> None:
        """``valid`` (bool ``(N,)``) is accepted in capacity mode only — the
        ragged-SPMD-batch contract shared with the CatBuffer metrics."""
        preds, target = _cosine_similarity_update(preds, target)
        if self.capacity is not None:
            sims = _cosine_similarity_compute(preds, target, "none")
            if valid is not None:
                # zero-padded invalid rows give 0/0 = NaN similarities;
                # select them out BEFORE weighting (NaN * 0 is NaN, so a
                # multiplicative mask would poison the sums)
                sims = jnp.where(jnp.asarray(valid, bool), sims, 0.0)
            if self.reduction in ("sum", "mean"):
                if valid is None:
                    self.sum_sim += sims.sum()
                    self.n_total += jnp.asarray(sims.shape[0], jnp.float32)
                else:
                    self.sum_sim += sims.sum()
                    self.n_total += jnp.asarray(valid, jnp.float32).sum()
            else:
                self.sims = cat_append(self.sims, sims, valid)
            return
        reject_valid_kwarg(valid)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        if self.capacity is not None:
            if self.reduction == "sum":
                return self.sum_sim
            if self.reduction == "mean":
                return self.sum_sim / self.n_total
            # 'none': the static-shape contract is uniform across eager,
            # auto-jit and functionalize — the full (capacity,) buffer with
            # NaN padding at unfilled slots. NaN is unambiguous (a genuine
            # cosine similarity is never NaN here) and makes accidental
            # reductions over padding loud. Exact (N,) row output = eager
            # mode (no capacity); the raw rows remain reachable via
            # `metric._state['sims'].values()`.
            return jnp.where(self.sims.mask, self.sims.data, jnp.nan)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _cosine_similarity_compute(preds, target, self.reduction)

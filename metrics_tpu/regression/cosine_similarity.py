"""``CosineSimilarity`` module metric (reference
``src/torchmetrics/regression/cosine_similarity.py``).
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.regression.cosine_similarity import (
    _cosine_similarity_compute,
    _cosine_similarity_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class CosineSimilarity(Metric):
    """Cosine similarity over accumulated rows (reference
    ``cosine_similarity.py:22-77``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CosineSimilarity
        >>> metric = CosineSimilarity(reduction='mean')
        >>> round(float(metric(jnp.asarray([[1.0, 2.0, 3.0]]), jnp.asarray([[2.0, 4.0, 6.0]]))), 4)
        1.0
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, reduction: Optional[str] = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _cosine_similarity_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _cosine_similarity_compute(preds, target, self.reduction)

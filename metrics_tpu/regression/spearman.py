"""``SpearmanCorrCoef`` module metric (reference
``src/torchmetrics/regression/spearman.py:25``).
"""
from typing import Any

import jax

from metrics_tpu.functional.regression.spearman import _spearman_corrcoef_compute, _spearman_corrcoef_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class SpearmanCorrCoef(Metric):
    """Spearman rank correlation over accumulated predictions
    (reference ``spearman.py:25-84``); cat list states, ranking at compute."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _spearman_corrcoef_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spearman_corrcoef_compute(preds, target)

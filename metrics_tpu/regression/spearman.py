"""``SpearmanCorrCoef`` module metric (reference
``src/torchmetrics/regression/spearman.py:25``).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.spearman import (
    _spearman_corrcoef_compute,
    _spearman_corrcoef_update,
    _spearman_masked,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.ringbuffer import CatBuffer, cat_append

Array = jax.Array


class SpearmanCorrCoef(Metric):
    """Spearman rank correlation over accumulated predictions
    (reference ``spearman.py:25-84``).

    Two accumulation modes (same design as :class:`~metrics_tpu.AUROC`):

    - default: cat list states, ranking at compute (eager).
    - ``capacity=N``: fixed-size :class:`CatBuffer` ring states — update,
      compute (masked tie-averaged ranking), and cross-device sync are all
      static-shape and fully jittable / ``functionalize``-able. Samples
      past capacity are dropped.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SpearmanCorrCoef
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = SpearmanCorrCoef()
        >>> round(float(metric(preds, target)), 4)
        1.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, capacity: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.capacity = capacity
        if capacity is not None:
            self.add_state("preds", default=CatBuffer.zeros(capacity, (), jnp.float32), dist_reduce_fx="cat")
            self.add_state("target", default=CatBuffer.zeros(capacity, (), jnp.float32), dist_reduce_fx="cat")
        else:
            tpl = jnp.zeros((0,), jnp.float32)
            self.add_state("preds", default=[], dist_reduce_fx="cat", template=tpl)
            self.add_state("target", default=[], dist_reduce_fx="cat", template=tpl)

    def update(self, preds: Array, target: Array, valid: Optional[Array] = None) -> None:
        if self.capacity is not None:
            preds = jnp.asarray(preds, jnp.float32)
            target = jnp.asarray(target, jnp.float32)
            if preds.shape != target.shape:
                raise ValueError(
                    f"Expected `preds` and `target` of the same shape, got {preds.shape} vs {target.shape}"
                )
            preds = preds.squeeze()
            target = target.squeeze()
            if preds.ndim > 1:
                raise ValueError("Expected both predictions and target to be 1 dimensional tensors.")
            self.preds = cat_append(self.preds, jnp.atleast_1d(preds), valid)
            self.target = cat_append(self.target, jnp.atleast_1d(target), valid)
            return
        if valid is not None:
            raise ValueError("`valid` masks are only supported in capacity (static-shape) mode")
        preds, target = _spearman_corrcoef_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        if self.capacity is not None:
            return _spearman_masked(self.preds.data, self.target.data, self.preds.mask)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spearman_corrcoef_compute(preds, target)

"""``PearsonCorrCoef`` module metric (reference
``src/torchmetrics/regression/pearson.py:66``).
"""
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.pearson import _pearson_corrcoef_compute, _pearson_corrcoef_update
from metrics_tpu.metric import Metric

Array = jax.Array


def _final_aggregation(
    means_x: Array,
    means_y: Array,
    vars_x: Array,
    vars_y: Array,
    corrs_xy: Array,
    nbs: Array,
) -> Tuple[Array, Array, Array, Array]:
    """Chan-style pairwise merge of per-device moment statistics
    (reference ``regression/pearson.py:23-64``). The loop is over the device
    count — a small static bound, unrolled at trace time."""
    mx1, my1, vx1, vy1, cxy1, n1 = means_x[0], means_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0]
    for i in range(1, means_x.shape[0]):
        mx2, my2, vx2, vy2, cxy2, n2 = means_x[i], means_y[i], vars_x[i], vars_y[i], corrs_xy[i], nbs[i]
        nb = n1 + n2
        mean_x = (n1 * mx1 + n2 * mx2) / nb
        mean_y = (n1 * my1 + n2 * my2) / nb

        element_x1 = (n1 + 1) * mean_x - n1 * mx1
        vx1 = vx1 + (element_x1 - mx1) * (element_x1 - mean_x) - (element_x1 - mean_x) ** 2
        element_x2 = (n2 + 1) * mean_x - n2 * mx2
        vx2 = vx2 + (element_x2 - mx2) * (element_x2 - mean_x) - (element_x2 - mean_x) ** 2
        var_x = vx1 + vx2

        element_y1 = (n1 + 1) * mean_y - n1 * my1
        vy1 = vy1 + (element_y1 - my1) * (element_y1 - mean_y) - (element_y1 - mean_y) ** 2
        element_y2 = (n2 + 1) * mean_y - n2 * my2
        vy2 = vy2 + (element_y2 - my2) * (element_y2 - mean_y) - (element_y2 - mean_y) ** 2
        var_y = vy1 + vy2

        cxy1 = cxy1 + (element_x1 - mx1) * (element_y1 - mean_y) - (element_x1 - mean_x) * (element_y1 - mean_y)
        cxy2 = cxy2 + (element_x2 - mx2) * (element_y2 - mean_y) - (element_x2 - mean_x) * (element_y2 - mean_y)
        corr_xy = cxy1 + cxy2

        mx1, my1, vx1, vy1, cxy1, n1 = mean_x, mean_y, var_x, var_y, corr_xy, nb
    return var_x, var_y, corr_xy, nb


class PearsonCorrCoef(Metric):
    """Pearson correlation with streaming moment states
    (reference ``pearson.py:66-150``). States use ``dist_reduce_fx=None`` —
    sync stacks the per-device moments and ``compute`` merges them with the
    pairwise aggregation above.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PearsonCorrCoef
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> metric = PearsonCorrCoef()
        >>> round(float(metric(preds, target)), 4)
        0.9849
    """

    is_differentiable = True
    higher_is_better = None
    full_state_update = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        for name in ("mean_x", "mean_y", "var_x", "var_y", "corr_xy", "n_total"):
            self.add_state(name, default=jnp.asarray(0.0), dist_reduce_fx=None)

    def update(self, preds: Array, target: Array) -> None:
        """Reference ``pearson.py:118-131``."""
        self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total = _pearson_corrcoef_update(
            preds, target, self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
        )

    def compute(self) -> Array:
        """Reference ``pearson.py:133-150``."""
        if jnp.asarray(self.mean_x).ndim > 0 and jnp.asarray(self.mean_x).size > 1:
            var_x, var_y, corr_xy, n_total = _final_aggregation(
                self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
            )
        else:
            var_x, var_y, corr_xy, n_total = self.var_x, self.var_y, self.corr_xy, self.n_total
        return _pearson_corrcoef_compute(var_x, var_y, corr_xy, n_total)

"""``RetrievalMetric`` base class (reference
``src/torchmetrics/retrieval/base.py:27``).

Ragged per-query grouping is inherently host-side (the reference's
``get_group_indexes`` dict loop, ``utilities/data.py:210``); here grouping is
a single vectorized sort-and-split over the concatenated state — one
``argsort`` + ``unique`` on host, then the per-query kernel runs on-device
per group. Compute happens once per epoch, so the Python loop over queries is
off the hot path (the hot path — update — is an append).
"""
from abc import ABC, abstractmethod
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.utilities.checks import _check_retrieval_inputs
from metrics_tpu.utilities.data import dim_zero_cat, get_group_indexes

Array = jax.Array


class RetrievalMetric(Metric, ABC):
    """Group predictions by query id and average a per-query metric
    (reference ``retrieval/base.py:27-146``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    # list states + data-dependent grouping → eager execution
    jittable_update = False
    jittable_compute = False

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False

        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index

        # dist_reduce_fx=None: sync gathers the union of all ranks' samples
        # without reduction (reference ``base.py:93-95``)
        self.add_state("indexes", default=[], dist_reduce_fx=None)
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        """Reference ``base.py:98-109``."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target, ignore_index=self.ignore_index
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """Reference ``base.py:110-139``."""
        indexes = np.asarray(dim_zero_cat(self.indexes))
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)

        res: List[Array] = []
        groups = get_group_indexes(indexes)
        for group in groups:
            mini_preds = preds[group]
            mini_target = target[group]
            if not int(jnp.sum(mini_target)):
                if self.empty_target_action == "error":
                    raise ValueError("`compute` method was provided with a query with no positive target.")
                if self.empty_target_action == "pos":
                    res.append(jnp.asarray(1.0))
                elif self.empty_target_action == "neg":
                    res.append(jnp.asarray(0.0))
            else:
                res.append(self._metric(mini_preds, mini_target))
        return jnp.stack(res).mean() if res else jnp.asarray(0.0)

    @abstractmethod
    def _metric(self, preds: Array, target: Array) -> Array:
        """Per-query metric (reference ``base.py:141-146``)."""

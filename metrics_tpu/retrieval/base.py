"""``RetrievalMetric`` base class (reference
``src/torchmetrics/retrieval/base.py:27``).

The reference computes per query in a Python loop over ``get_group_indexes``
(``retrieval/base.py:110-139``, ``utilities/data.py:210``) — one device
dispatch per query. Here compute is vectorized: queries are grouped by ONE
device packed-radix sort (``ops/bucketed_rank.py`` — no host ``argsort``
round-trip), bucketed by padded power-of-two length, and each bucket runs as
ONE ``vmap``-ped masked-row kernel on device — O(log max_docs) dispatches
total regardless of query count (SURVEY.md §7 hard part #2).
"""
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.ops import _envtools
from metrics_tpu.ops import ascending_order, stable_key_order
from metrics_tpu.utilities.checks import _check_retrieval_inputs
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array

# Eager (host-grouped) compute above this many accumulated rows warns once
# per class, steering static workloads to the compiled `capacity=` mode
# (VERDICT r5 #8: the host-grouped default undersells the compiled path —
# 2.76x vs the reference dict loop where the compiled grouped compute is a
# single fused sort+scatter program). Overridable per process via the
# METRICS_TPU_EAGER_WARN_ROWS env var (read at each compute, so operators
# can tune a running deployment's noise floor without code changes).
_HOST_GROUPED_WARN_N = 50_000
_host_grouped_warned: set = set()


def _parse_warn_rows(raw: str) -> Optional[int]:
    try:
        value = int(raw)
        if value < 0:
            raise ValueError("negative")
        return value
    except ValueError:
        _env_warn_once(
            ("METRICS_TPU_EAGER_WARN_ROWS", raw),
            f"METRICS_TPU_EAGER_WARN_ROWS={raw!r} is not a non-negative integer; "
            f"using the default of {_HOST_GROUPED_WARN_N}",
        )
        return None  # -> module default at the read site


_env_warn_once = _envtools.WarnOnce()
_ENV_WARN_ROWS = _envtools.EnvParse("METRICS_TPU_EAGER_WARN_ROWS", _parse_warn_rows, None)


def _eager_warn_rows() -> int:
    """The effective warn threshold: ``METRICS_TPU_EAGER_WARN_ROWS`` when
    set and parseable (the shared ``ops/_envtools`` contract: call-time
    resolution, memoized parse, malformed values warn once and fall back —
    a bad env var must never break compute), else the module default."""
    value = _ENV_WARN_ROWS()
    return _HOST_GROUPED_WARN_N if value is None else value


def _group_layout(indexes: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sort order + per-query (start, count) over the concatenated state.

    The big O(n log n) work — the stable sort by query id — runs on device
    through the packed-radix kernel (same permutation as
    ``np.argsort(kind='stable')``); only the tiny (num_queries,)
    starts/counts layout arrays come back to host for the bucket packing.
    """
    idx_np = np.asarray(indexes)
    if idx_np.dtype.itemsize > 4 and idx_np.size and (
        idx_np.max() > np.iinfo(np.int32).max or idx_np.min() < np.iinfo(np.int32).min
    ):
        # ids beyond int32 would truncate on device (x64 disabled) — keep
        # the exact host layout for this pathological case
        order = np.argsort(idx_np, kind="stable")
        _, starts, counts = np.unique(idx_np[order], return_index=True, return_counts=True)
        return order, starts, counts

    if idx_np.size == 0:  # no rows -> no groups (np.unique layout)
        return np.zeros(0, np.int64), np.zeros(0, np.int64), np.zeros(0, np.int64)

    idx = jnp.asarray(indexes)
    order = ascending_order(idx)
    sorted_idx = idx[order]
    boundary = jnp.concatenate([jnp.ones((1,), bool), sorted_idx[1:] != sorted_idx[:-1]])
    starts = np.asarray(jnp.nonzero(boundary)[0])
    counts = np.diff(np.append(starts, idx.shape[0]))
    return np.asarray(order), starts, counts


def _bucket_rows(
    values: Tuple[np.ndarray, ...], starts: np.ndarray, counts: np.ndarray, sel: np.ndarray, length: int
):
    """Pack the selected queries' ragged docs into padded (Q, L) blocks."""
    c = counts[sel]
    offs = np.arange(int(c.sum())) - np.repeat(np.cumsum(c) - c, c)
    src = np.repeat(starts[sel], c) + offs
    row_ids = np.repeat(np.arange(len(sel)), c)
    mask = np.zeros((len(sel), length), bool)
    mask[row_ids, offs] = True
    out = []
    for v in values:
        block = np.zeros((len(sel), length), v.dtype)
        block[row_ids, offs] = v[src]
        out.append(block)
    return (*out, mask)


class RetrievalMetric(Metric, ABC):
    """Group predictions by query id and average a per-query metric
    (reference ``retrieval/base.py:27-146``).

    Two accumulation modes:

    - default: unbounded ``cat`` list states + the bucketed-vmap eager
      compute below (the reference's contract, any query-id values);
    - ``capacity=N``: :class:`CatBuffer` ring states and a fully jittable
      static-shape compute — sort-by-query + one ``(num_queries,
      max_docs_per_query)`` scatter + the same masked row kernels — so
      ``functionalize(RetrievalMAP(capacity=N, num_queries=Q))`` lives
      inside compiled steps and under ``shard_map``, like the curve
      metrics. Requires query ids in ``[0, num_queries)``; docs beyond
      ``max_docs_per_query`` for one query are dropped from compute;
      ``empty_target_action='error'`` is unsupported (cannot raise under
      jit).

    **Which mode should I use?** Passing ``capacity=`` (with its required
    ``num_queries=`` bound) auto-selects the compiled grouped compute —
    there is no extra switch. Prefer it whenever your workload is static
    (bounded rows, query ids in a known range): compute is one fused
    sort+scatter XLA program instead of host grouping + per-bucket
    dispatches, it works inside jitted train steps, and it syncs with the
    fused single-collective path. Keep the eager default for exploratory /
    unbounded workloads (arbitrary query-id values, no row bound, exact
    unbounded semantics, ``empty_target_action='error'``). Above
    ``_HOST_GROUPED_WARN_N`` accumulated rows (50k by default; override
    per process with the ``METRICS_TPU_EAGER_WARN_ROWS`` env var) the
    eager compute warns once per class to make this trade-off visible
    (silence by switching modes, raising the threshold, or
    ``warnings.filterwarnings``).
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    # list states + data-dependent grouping → eager execution (capacity
    # mode flips these to True per instance)
    jittable_update = False
    jittable_compute = False

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        capacity: Optional[int] = None,
        num_queries: Optional[int] = None,
        max_docs_per_query: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False

        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index

        self.capacity = capacity
        if capacity is not None:
            from metrics_tpu.utilities.ringbuffer import CatBuffer

            if not (isinstance(num_queries, int) and num_queries > 0):
                raise ValueError("capacity mode requires `num_queries` (a static bound on query ids)")
            if empty_target_action == "error":
                raise ValueError("`empty_target_action='error'` is not supported in capacity (compiled) mode")
            self.num_queries = num_queries
            # default L = capacity is the only always-correct bound, but the
            # compute materializes (num_queries, L) matrices — pass a tight
            # max_docs_per_query for large capacities or the scatter layout
            # costs Q*capacity elements regardless of actual fill
            self.max_docs_per_query = max_docs_per_query if max_docs_per_query is not None else capacity
            self.jittable_update = True
            self.jittable_compute = True
            self.add_state("indexes", default=CatBuffer.zeros(capacity, (), jnp.int32), dist_reduce_fx="cat")
            self.add_state("preds", default=CatBuffer.zeros(capacity, (), jnp.float32), dist_reduce_fx="cat")
            self.add_state("target", default=CatBuffer.zeros(capacity, (), jnp.float32), dist_reduce_fx="cat")
        else:
            # dist_reduce_fx=None: sync gathers the union of all ranks'
            # samples without reduction (reference ``base.py:93-95``)
            self.add_state("indexes", default=[], dist_reduce_fx=None, template=jnp.zeros((0,), jnp.int32))
            self.add_state("preds", default=[], dist_reduce_fx=None, template=jnp.zeros((0,), jnp.float32))
            self.add_state("target", default=[], dist_reduce_fx=None, template=jnp.zeros((0,), jnp.float32))

    def update(self, preds: Array, target: Array, indexes: Array, valid: Optional[Array] = None) -> None:
        """Reference ``base.py:98-109``."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        if self.capacity is not None:
            self._update_capacity(preds, target, indexes, valid)
            return
        from metrics_tpu.utilities.ringbuffer import reject_valid_kwarg

        reject_valid_kwarg(valid)
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target, ignore_index=self.ignore_index
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def _update_capacity(self, preds: Array, target: Array, indexes: Array, valid: Optional[Array]) -> None:
        """Trace-safe append: shape/dtype checks only; ``ignore_index``
        filtering becomes part of the validity mask instead of a
        dynamic-shape boolean filter."""
        from metrics_tpu.utilities.ringbuffer import cat_append

        indexes = jnp.asarray(indexes).reshape(-1)
        preds = jnp.asarray(preds, jnp.float32).reshape(-1)
        target = jnp.asarray(target).reshape(-1)
        if not (indexes.shape == preds.shape == target.shape):
            raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
        if not jnp.issubdtype(indexes.dtype, jnp.integer):
            raise ValueError("`indexes` must be a tensor of long integers")
        keep = jnp.ones(indexes.shape, bool) if valid is None else jnp.asarray(valid, bool).reshape(-1)
        if self.ignore_index is not None:
            keep = keep & (target != self.ignore_index)
        # out-of-contract ids drop instead of wasting ring slots (negative
        # ids would otherwise WRAP in the compute scatter — see below)
        keep = keep & (indexes >= 0) & (indexes < self.num_queries)
        self.indexes = cat_append(self.indexes, indexes.astype(jnp.int32), keep)
        self.preds = cat_append(self.preds, preds, keep)
        self.target = cat_append(self.target, target.astype(jnp.float32), keep)

    def compute(self) -> Array:
        """Vectorized equivalent of reference ``base.py:110-139``."""
        if self.capacity is not None:
            return self._compute_capacity()
        indexes = np.asarray(dim_zero_cat(self.indexes))
        preds = np.asarray(dim_zero_cat(self.preds))
        target = np.asarray(dim_zero_cat(self.target))
        if indexes.size >= _eager_warn_rows() and type(self).__name__ not in _host_grouped_warned:
            _host_grouped_warned.add(type(self).__name__)
            from metrics_tpu.utilities.prints import rank_zero_warn

            rank_zero_warn(
                f"{type(self).__name__}: computing over {indexes.size} accumulated rows on the "
                "host-grouped eager path. For static workloads, `capacity=` + `num_queries=` "
                "auto-selects the compiled grouped compute (one fused sort+scatter XLA program, "
                "usable inside jitted steps) — see the RetrievalMetric docstring. "
                "This warns once per class.",
                UserWarning,
            )
        values = self._per_query_values(indexes, preds, target)
        return values.mean() if values.size else jnp.asarray(0.0)

    def _grouped_capacity_matrices(self) -> Tuple[Array, Array, Array]:
        """The static-shape grouped layout: sort rows by query id (invalid
        rows to a sentinel), derive each row's rank within its query from
        the sorted array itself (``i - searchsorted(idx, idx_i)``), and
        scatter into dense ``(Q, L)`` score/target/mask matrices. Fully
        jittable: shapes depend only on ``capacity``, ``num_queries`` and
        ``max_docs_per_query``. Shared by the scalar and curve computes."""
        q, l = self.num_queries, self.max_docs_per_query
        idx_buf, pred_buf, tgt_buf = self.indexes, self.preds, self.target
        n = idx_buf.capacity
        valid = idx_buf.mask
        # sentinel also guards ids outside [0, q): scatter mode='drop' only
        # drops out-of-bounds-HIGH indices — a negative id would wrap to
        # query q-1 and corrupt it (update() already filters these; states
        # merged/restored from elsewhere get the same protection here)
        idx = jnp.where(valid & (idx_buf.data >= 0) & (idx_buf.data < q), idx_buf.data, q)
        # counting-sort form: ids are bounded by construction, so the stable
        # grouping sort is one packed value-sort pass (ops/bucketed_rank.py)
        order = stable_key_order(idx, q + 1)
        idx_s = idx[order]
        p_s = pred_buf.data[order]
        t_s = tgt_buf.data[order]
        pos = jnp.arange(n) - jnp.searchsorted(idx_s, idx_s, side="left")
        # rows with idx == q (invalid) or pos >= l scatter out of bounds
        pmat = jnp.zeros((q, l), p_s.dtype).at[idx_s, pos].set(p_s, mode="drop")
        tmat = jnp.zeros((q, l), t_s.dtype).at[idx_s, pos].set(t_s, mode="drop")
        mask = jnp.zeros((q, l), bool).at[idx_s, pos].set(True, mode="drop")
        return pmat, tmat, mask

    def _compute_capacity(self) -> Array:
        """Vmapped masked row kernel over the grouped layout — the compiled
        form of the eager per-query mean."""
        pmat, tmat, mask = self._grouped_capacity_matrices()

        values = jax.vmap(self._row_metric)(pmat, tmat, mask)
        pos_counts = jnp.sum((tmat > 0) & mask, axis=1)
        neg_counts = jnp.sum(mask, axis=1) - pos_counts
        present = jnp.any(mask, axis=1)
        empty = self._query_is_empty(pos_counts, neg_counts)
        fill = 1.0 if self.empty_target_action == "pos" else 0.0
        values = jnp.where(empty | ~present, fill, values)  # also clears NaNs
        include = present if self.empty_target_action in ("pos", "neg") else present & ~empty
        return jnp.sum(values * include) / jnp.maximum(jnp.sum(include), 1)

    def _query_is_empty(self, pos_counts: np.ndarray, neg_counts: np.ndarray) -> np.ndarray:
        """Which queries hit the degenerate case (no positives by default;
        FallOut overrides to no negatives, reference ``fall_out.py:80-103``)."""
        return pos_counts == 0

    def _empty_message(self) -> str:
        return "`compute` method was provided with a query with no positive target."

    def _per_query_values(
        self,
        indexes: np.ndarray,
        preds: np.ndarray,
        target: np.ndarray,
        kernel: Optional[Callable] = None,
        kernel_key: Any = None,
        out_shape: Tuple[int, ...] = (),
    ) -> Array:
        """Per-query results — scalar by default, ``out_shape``-shaped for
        vector-valued kernels (e.g. precision/recall curves) — from a
        bucketed vmap of the masked row kernel, with the empty-target action
        applied host-side ("pos" fills ones, "neg" zeros, "skip" drops the
        query, "error" raises)."""
        if indexes.size == 0:
            return jnp.zeros((0,) + out_shape)
        order, starts, counts = _group_layout(indexes)
        p, t = preds[order], target[order]
        pos_counts = np.add.reduceat((t > 0).astype(np.int64), starts)
        neg_counts = counts - pos_counts
        empty = self._query_is_empty(pos_counts, neg_counts)

        if empty.any() and self.empty_target_action == "error":
            raise ValueError(self._empty_message())

        num_queries = len(counts)
        values = np.zeros((num_queries,) + out_shape, np.float32)
        if self.empty_target_action == "pos":
            values[empty] = 1.0
        # padded power-of-two length per query (vectorized: one array op)
        lengths = np.where(counts > 1, 1 << np.ceil(np.log2(np.maximum(counts, 1))).astype(np.int64), 1)
        todo = ~empty
        for length in np.unique(lengths[todo]):
            sel = np.where(todo & (lengths == length))[0]
            pb, tb, mb = _bucket_rows((p, t), starts, counts, sel, int(length))
            jitted = self._bucket_kernel(int(length), kernel, kernel_key)
            values[sel] = np.asarray(jitted(jnp.asarray(pb), jnp.asarray(tb), jnp.asarray(mb)))
        if self.empty_target_action == "skip":
            values = values[todo]
        return jnp.asarray(values)

    def _bucket_kernel(self, length: int, kernel: Optional[Callable] = None, kernel_key: Any = None) -> Callable:
        """Jitted vmap of a masked row kernel, cached per (padded length,
        caller key) so repeated computes never re-trace."""
        cache: Dict[Any, Callable] = self.__dict__.setdefault("_bucket_kernels", {})
        key = (length, kernel_key)
        if key not in cache:
            cache[key] = jax.jit(jax.vmap(kernel if kernel is not None else self._row_metric))
        return cache[key]

    @abstractmethod
    def _row_metric(self, preds: Array, target: Array, mask: Array) -> Array:
        """Masked per-query kernel over one padded ``(L,)`` row — jittable,
        vmapped over a bucket of queries (vectorized form of reference
        ``base.py:141-146``)."""

    def _metric(self, preds: Array, target: Array) -> Array:
        """Per-query metric on concrete arrays (reference ``base.py:141-146``) —
        kept for API parity; compute uses the vectorized row kernels."""
        mask = jnp.ones(preds.shape[-1], bool)
        return self._row_metric(jnp.asarray(preds), jnp.asarray(target), mask)

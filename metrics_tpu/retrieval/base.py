"""``RetrievalMetric`` base class (reference
``src/torchmetrics/retrieval/base.py:27``).

The reference computes per query in a Python loop over ``get_group_indexes``
(``retrieval/base.py:110-139``, ``utilities/data.py:210``) — one device
dispatch per query. Here compute is vectorized: queries are grouped by one
host ``argsort``+``unique``, bucketed by padded power-of-two length, and each
bucket runs as ONE ``vmap``-ped masked-row kernel on device — O(log max_docs)
dispatches total regardless of query count (SURVEY.md §7 hard part #2).
"""
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.utilities.checks import _check_retrieval_inputs
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


def _group_layout(indexes: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sort order + per-query (start, count) over the concatenated state."""
    order = np.argsort(indexes, kind="stable")
    _, starts, counts = np.unique(indexes[order], return_index=True, return_counts=True)
    return order, starts, counts


def _bucket_rows(
    values: Tuple[np.ndarray, ...], starts: np.ndarray, counts: np.ndarray, sel: np.ndarray, length: int
):
    """Pack the selected queries' ragged docs into padded (Q, L) blocks."""
    c = counts[sel]
    offs = np.arange(int(c.sum())) - np.repeat(np.cumsum(c) - c, c)
    src = np.repeat(starts[sel], c) + offs
    row_ids = np.repeat(np.arange(len(sel)), c)
    mask = np.zeros((len(sel), length), bool)
    mask[row_ids, offs] = True
    out = []
    for v in values:
        block = np.zeros((len(sel), length), v.dtype)
        block[row_ids, offs] = v[src]
        out.append(block)
    return (*out, mask)


class RetrievalMetric(Metric, ABC):
    """Group predictions by query id and average a per-query metric
    (reference ``retrieval/base.py:27-146``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    # list states + data-dependent grouping → eager execution
    jittable_update = False
    jittable_compute = False

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.allow_non_binary_target = False

        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index

        # dist_reduce_fx=None: sync gathers the union of all ranks' samples
        # without reduction (reference ``base.py:93-95``)
        self.add_state("indexes", default=[], dist_reduce_fx=None)
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        """Reference ``base.py:98-109``."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target, ignore_index=self.ignore_index
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """Vectorized equivalent of reference ``base.py:110-139``."""
        indexes = np.asarray(dim_zero_cat(self.indexes))
        preds = np.asarray(dim_zero_cat(self.preds))
        target = np.asarray(dim_zero_cat(self.target))
        values = self._per_query_values(indexes, preds, target)
        return values.mean() if values.size else jnp.asarray(0.0)

    def _query_is_empty(self, pos_counts: np.ndarray, neg_counts: np.ndarray) -> np.ndarray:
        """Which queries hit the degenerate case (no positives by default;
        FallOut overrides to no negatives, reference ``fall_out.py:80-103``)."""
        return pos_counts == 0

    def _empty_message(self) -> str:
        return "`compute` method was provided with a query with no positive target."

    def _per_query_values(
        self,
        indexes: np.ndarray,
        preds: np.ndarray,
        target: np.ndarray,
        kernel: Optional[Callable] = None,
        kernel_key: Any = None,
        out_shape: Tuple[int, ...] = (),
    ) -> Array:
        """Per-query results — scalar by default, ``out_shape``-shaped for
        vector-valued kernels (e.g. precision/recall curves) — from a
        bucketed vmap of the masked row kernel, with the empty-target action
        applied host-side ("pos" fills ones, "neg" zeros, "skip" drops the
        query, "error" raises)."""
        if indexes.size == 0:
            return jnp.zeros((0,) + out_shape)
        order, starts, counts = _group_layout(indexes)
        p, t = preds[order], target[order]
        pos_counts = np.add.reduceat((t > 0).astype(np.int64), starts)
        neg_counts = counts - pos_counts
        empty = self._query_is_empty(pos_counts, neg_counts)

        if empty.any() and self.empty_target_action == "error":
            raise ValueError(self._empty_message())

        num_queries = len(counts)
        values = np.zeros((num_queries,) + out_shape, np.float32)
        if self.empty_target_action == "pos":
            values[empty] = 1.0
        # padded power-of-two length per query (vectorized: one array op)
        lengths = np.where(counts > 1, 1 << np.ceil(np.log2(np.maximum(counts, 1))).astype(np.int64), 1)
        todo = ~empty
        for length in np.unique(lengths[todo]):
            sel = np.where(todo & (lengths == length))[0]
            pb, tb, mb = _bucket_rows((p, t), starts, counts, sel, int(length))
            jitted = self._bucket_kernel(int(length), kernel, kernel_key)
            values[sel] = np.asarray(jitted(jnp.asarray(pb), jnp.asarray(tb), jnp.asarray(mb)))
        if self.empty_target_action == "skip":
            values = values[todo]
        return jnp.asarray(values)

    def _bucket_kernel(self, length: int, kernel: Optional[Callable] = None, kernel_key: Any = None) -> Callable:
        """Jitted vmap of a masked row kernel, cached per (padded length,
        caller key) so repeated computes never re-trace."""
        cache: Dict[Any, Callable] = self.__dict__.setdefault("_bucket_kernels", {})
        key = (length, kernel_key)
        if key not in cache:
            cache[key] = jax.jit(jax.vmap(kernel if kernel is not None else self._row_metric))
        return cache[key]

    @abstractmethod
    def _row_metric(self, preds: Array, target: Array, mask: Array) -> Array:
        """Masked per-query kernel over one padded ``(L,)`` row — jittable,
        vmapped over a bucket of queries (vectorized form of reference
        ``base.py:141-146``)."""

    def _metric(self, preds: Array, target: Array) -> Array:
        """Per-query metric on concrete arrays (reference ``base.py:141-146``) —
        kept for API parity; compute uses the vectorized row kernels."""
        mask = jnp.ones(preds.shape[-1], bool)
        return self._row_metric(jnp.asarray(preds), jnp.asarray(target), mask)

"""Retrieval module metrics (reference
``src/torchmetrics/retrieval/{average_precision,reciprocal_rank,precision,
recall,fall_out,ndcg,hit_rate,r_precision,precision_recall_curve}.py``).
"""
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.retrieval.kernels import (
    _masked_average_precision,
    _masked_fall_out,
    _masked_hit_rate,
    _masked_normalized_dcg,
    _masked_precision,
    _masked_precision_recall_curve,
    _masked_r_precision,
    _masked_recall,
    _masked_reciprocal_rank,
)
from metrics_tpu.retrieval.base import RetrievalMetric
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class RetrievalMAP(RetrievalMetric):
    """Mean average precision (reference ``retrieval/average_precision.py:24``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalMAP
        >>> metric = RetrievalMAP()
        >>> metric.update(jnp.asarray([0.8, 0.4, 0.9, 0.2]), jnp.asarray([1, 0, 0, 1]),
        ...               indexes=jnp.asarray([0, 0, 1, 1]))
        >>> round(float(metric.compute()), 4)
        0.75
    """

    def _row_metric(self, preds: Array, target: Array, mask: Array) -> Array:
        return _masked_average_precision(preds, target, mask)


class RetrievalMRR(RetrievalMetric):
    """Mean reciprocal rank (reference ``retrieval/reciprocal_rank.py:24``)."""

    def _row_metric(self, preds: Array, target: Array, mask: Array) -> Array:
        return _masked_reciprocal_rank(preds, target, mask)


class RetrievalPrecision(RetrievalMetric):
    """Mean precision@k (reference ``retrieval/precision.py:24``)."""

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        adaptive_k: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (k is not None) and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.k = k
        self.adaptive_k = adaptive_k

    def _row_metric(self, preds: Array, target: Array, mask: Array) -> Array:
        return _masked_precision(preds, target, mask, k=self.k, adaptive_k=self.adaptive_k)


class RetrievalRecall(RetrievalMetric):
    """Mean recall@k (reference ``retrieval/recall.py:24``)."""

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (k is not None) and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        self.k = k

    def _row_metric(self, preds: Array, target: Array, mask: Array) -> Array:
        return _masked_recall(preds, target, mask, k=self.k)


class RetrievalFallOut(RetrievalMetric):
    """Mean fall-out@k; empty-target logic inverted — a query with no
    *negative* target is the degenerate case (reference ``retrieval/fall_out.py:24-103``)."""

    higher_is_better = False

    def __init__(
        self,
        empty_target_action: str = "pos",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (k is not None) and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        self.k = k

    def _query_is_empty(self, pos_counts: np.ndarray, neg_counts: np.ndarray) -> np.ndarray:
        """Reference ``fall_out.py:80-103`` — empty-target test is on negatives."""
        return neg_counts == 0

    def _empty_message(self) -> str:
        return "`compute` method was provided with a query with no negative target."

    def _row_metric(self, preds: Array, target: Array, mask: Array) -> Array:
        return _masked_fall_out(preds, target, mask, k=self.k)


class RetrievalNormalizedDCG(RetrievalMetric):
    """Mean nDCG@k; non-binary relevance allowed (reference ``retrieval/ndcg.py:24``)."""

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (k is not None) and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        self.k = k
        self.allow_non_binary_target = True

    def _row_metric(self, preds: Array, target: Array, mask: Array) -> Array:
        return _masked_normalized_dcg(preds, target, mask, k=self.k)


class RetrievalHitRate(RetrievalMetric):
    """Mean hit-rate@k (reference ``retrieval/hit_rate.py:24``)."""

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (k is not None) and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        self.k = k

    def _row_metric(self, preds: Array, target: Array, mask: Array) -> Array:
        return _masked_hit_rate(preds, target, mask, k=self.k)


class RetrievalRPrecision(RetrievalMetric):
    """Mean r-precision (reference ``retrieval/r_precision.py:24``)."""

    def _row_metric(self, preds: Array, target: Array, mask: Array) -> Array:
        return _masked_r_precision(preds, target, mask)


def _retrieval_recall_at_fixed_precision(
    precision: Array, recall: Array, top_k: Array, min_precision: float
) -> Tuple[Array, Array]:
    """Lexicographic best (recall, k) subject to precision floor
    (reference ``retrieval/precision_recall_curve.py:35-58``) — pure jnp so
    the capacity mode's jitted compute can run it; identical on concrete
    arrays."""
    precision = jnp.asarray(precision)
    recall = jnp.asarray(recall)
    top_k = jnp.asarray(top_k)
    n = top_k.shape[0]
    meets = precision >= min_precision
    any_meets = jnp.any(meets)
    r_star = jnp.max(jnp.where(meets, recall, -jnp.inf))
    # reference tie-break: max() over (recall, k) tuples → largest k
    best_k = jnp.max(jnp.where(meets & (recall == r_star), top_k, 0))
    max_recall = jnp.where(any_meets, r_star, 0.0).astype(jnp.float32)
    # no candidate, or best recall is 0 → k = len(top_k) (reference ``:54-56``)
    best_k = jnp.where(any_meets & (r_star > 0), best_k, n).astype(top_k.dtype)
    return max_recall, best_k


class RetrievalPrecisionRecallCurve(RetrievalMetric):
    """Query-averaged precision/recall curve over k
    (reference ``retrieval/precision_recall_curve.py:61-186``).

    ``capacity=`` mode (round 5): the same :class:`CatBuffer` ring states
    and compiled grouped layout as the scalar retrieval metrics, with the
    masked curve kernel vmapped per query — fully jittable. ``max_k``
    defaults to ``max_docs_per_query`` there (the static bound), not the
    data-dependent max group size the eager mode infers."""

    def __init__(
        self,
        max_k: Optional[int] = None,
        adaptive_k: bool = False,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (max_k is not None) and not (isinstance(max_k, int) and max_k > 0):
            raise ValueError("`max_k` has to be a positive integer or None")
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.max_k = max_k
        self.adaptive_k = adaptive_k

    def _row_metric(self, preds: Array, target: Array, mask: Array) -> Array:  # pragma: no cover - unused
        raise NotImplementedError

    def _compute_capacity(self) -> Tuple[Array, Array, Array]:
        """Compiled grouped curves: vmap the masked curve kernel over the
        dense (Q, L) layout, then the base class's include/fill semantics
        broadcast over the (2, max_k) curve values."""
        max_k = self.max_k if self.max_k is not None else self.max_docs_per_query
        pmat, tmat, mask = self._grouped_capacity_matrices()
        curves = jax.vmap(
            lambda pp, tt, mm: jnp.stack(
                _masked_precision_recall_curve(pp, tt, mm, max_k, self.adaptive_k)
            )
        )(pmat, tmat, mask)  # (Q, 2, max_k)
        pos_counts = jnp.sum((tmat > 0) & mask, axis=1)
        neg_counts = jnp.sum(mask, axis=1) - pos_counts
        present = jnp.any(mask, axis=1)
        empty = self._query_is_empty(pos_counts, neg_counts)
        fill = 1.0 if self.empty_target_action == "pos" else 0.0
        curves = jnp.where((empty | ~present)[:, None, None], fill, curves)
        include = present if self.empty_target_action in ("pos", "neg") else present & ~empty
        denom = jnp.maximum(jnp.sum(include), 1)
        mean = jnp.sum(curves * include[:, None, None].astype(curves.dtype), axis=0) / denom
        top_k = jnp.arange(1, max_k + 1, dtype=jnp.int32)
        return mean[0], mean[1], top_k

    def compute(self) -> Tuple[Array, Array, Array]:
        """Vectorized form of reference ``precision_recall_curve.py:157-186``:
        per-query (2, max_k) curves from the shared bucketed helper, then
        average over (non-skipped) queries."""
        if self.capacity is not None:
            return self._compute_capacity()
        indexes = np.asarray(dim_zero_cat(self.indexes))
        preds = np.asarray(dim_zero_cat(self.preds))
        target = np.asarray(dim_zero_cat(self.target))

        max_k = self.max_k
        if max_k is None:
            max_k = int(np.unique(indexes, return_counts=True)[1].max()) if indexes.size else 1

        def curve_kernel(pp: Array, tt: Array, mm: Array) -> Array:
            return jnp.stack(_masked_precision_recall_curve(pp, tt, mm, max_k, self.adaptive_k))

        values = self._per_query_values(
            indexes,
            preds,
            target,
            kernel=curve_kernel,
            kernel_key=("pr_curve", max_k, self.adaptive_k),
            out_shape=(2, max_k),
        )
        top_k = jnp.arange(1, max_k + 1, dtype=jnp.int32)
        if values.shape[0] == 0:
            return jnp.zeros(max_k), jnp.zeros(max_k), top_k
        return values[:, 0].mean(axis=0), values[:, 1].mean(axis=0), top_k


class RetrievalRecallAtFixedPrecision(RetrievalPrecisionRecallCurve):
    """Best recall@k subject to a precision floor
    (reference ``precision_recall_curve.py:189-252``)."""

    def __init__(
        self,
        min_precision: float = 0.0,
        max_k: Optional[int] = None,
        adaptive_k: bool = False,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            max_k=max_k,
            adaptive_k=adaptive_k,
            empty_target_action=empty_target_action,
            ignore_index=ignore_index,
            **kwargs,
        )
        if not (isinstance(min_precision, float) and 0.0 <= min_precision <= 1.0):
            raise ValueError("`min_precision` has to be a positive float between 0 and 1")
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:
        precision, recall, top_k = super().compute()
        return _retrieval_recall_at_fixed_precision(precision, recall, top_k, self.min_precision)

"""``Dice`` module metric (reference
``src/torchmetrics/classification/dice.py``, 167 LoC).
"""
from typing import Any, Optional

import jax

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.dice import _dice_compute

Array = jax.Array


class Dice(StatScores):
    """Dice = 2*TP / (2*TP + FP + FN) (reference ``dice.py:26-167``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Dice
        >>> preds = jnp.asarray([[0.75, 0.05, 0.05, 0.15], [0.1, 0.15, 0.7, 0.05],
        ...                      [0.3, 0.4, 0.2, 0.1], [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.asarray([0, 1, 3, 2])
        >>> metric = Dice(num_classes=4, average='micro')
        >>> round(float(metric(preds, target)), 4)
        0.25
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        zero_division: int = 0,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: Optional[str] = "micro",
        mdmc_average: Optional[str] = "global",
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")

        super().__init__(
            reduce="macro" if average in ("weighted", "none", None) else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            **kwargs,
        )
        self.average = average
        self.zero_division = zero_division

    def compute(self) -> Array:
        """Reference ``dice.py:160-167``."""
        tp, fp, tn, fn = self._get_final_stats()
        return _dice_compute(tp, fp, fn, self.average, self.mdmc_reduce, self.zero_division)

"""``AUROC`` module metric (reference
``src/torchmetrics/classification/auroc.py:27``).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.auroc import (
    _auroc_compute,
    _auroc_update,
    _binary_auroc_masked,
    _multiclass_auroc_masked,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.enums import AverageMethod, DataType
from metrics_tpu.utilities.ringbuffer import init_score_ring_states, reject_valid_kwarg, score_ring_update

Array = jax.Array


class AUROC(Metric):
    """Area under the ROC curve (reference ``auroc.py:27-195``).

    Two accumulation modes:

    - default: raw preds/target accumulate in ``cat`` list states (the
      reference's all_gather-heavy pattern, SURVEY.md §2.5); compute runs
      eagerly on the concatenation.
    - ``capacity=N``: a fixed-size :class:`CatBuffer` ring state — update,
      compute, and cross-device sync are all static-shape and fully
      jittable (compute is the tie-averaged rank statistic, identical to
      the trapezoidal ROC area). This is the form that lives inside a
      compiled training step / ``functionalize``. Samples past capacity
      are dropped.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AUROC
        >>> preds = jnp.asarray([0.2, 0.8, 0.6, 0.4])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> metric = AUROC()
        >>> round(float(metric(preds, target)), 4)
        1.0
    """

    _snapshot_attrs = ("mode",)  # data-inferred at update (resilience snapshots)
    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.average = average
        self.max_fpr = max_fpr
        self.capacity = capacity

        allowed_average = (AverageMethod.MICRO, AverageMethod.MACRO, AverageMethod.WEIGHTED, AverageMethod.NONE, None, "none")
        if average not in allowed_average:
            raise ValueError(
                f"Argument `average` expected to be one of the following: {allowed_average} but got {average}"
            )
        if max_fpr is not None and (not isinstance(max_fpr, float) or not 0 < max_fpr <= 1):
            raise ValueError(f"`max_fpr` should be a float in range (0, 1], got: {max_fpr}")

        if capacity is not None:
            # static-shape mode: the data mode is fixed at construction
            # (binary unless num_classes declares one-vs-rest multiclass)
            if max_fpr is not None:
                raise ValueError("`max_fpr` is not supported together with `capacity` (static-shape) mode")
            if average == AverageMethod.MICRO:
                raise ValueError("`average='micro'` is not supported together with `capacity` mode")
            self.mode = init_score_ring_states(self, capacity, num_classes, pos_label)
        else:
            self.mode: Optional[DataType] = None
            self.add_state("preds", default=[], dist_reduce_fx="cat", template=jnp.zeros((0,), jnp.float32))
            self.add_state("target", default=[], dist_reduce_fx="cat", template=jnp.zeros((0,), jnp.int32))

    def update(self, preds: Array, target: Array, valid: Optional[Array] = None) -> None:
        """Reference ``auroc.py:160-175``.

        ``valid`` is accepted in capacity mode only: a per-row bool mask so
        sharded SPMD updates can contribute ragged sample counts from
        equal-shaped blocks (e.g. a final partial batch per device).
        """
        if self.capacity is not None:
            score_ring_update(self, preds, target, valid, "AUROC")
            return
        reject_valid_kwarg(valid)
        preds, target, mode = _auroc_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)
        if self.mode and self.mode != mode:
            raise ValueError(
                "The mode of data (binary, multi-label, multi-class) should be constant, but changed"
                f" between batches from {self.mode} to {mode}"
            )
        self.mode = mode

    def compute(self) -> Array:
        """Reference ``auroc.py:177-195``."""
        if not self.mode:
            raise RuntimeError("You have to have determined mode.")
        if self.capacity is not None:
            if self.mode == DataType.MULTICLASS:
                return _multiclass_auroc_masked(
                    self.preds.data, self.target.data, self.preds.mask, self.num_classes, self.average
                )
            return _binary_auroc_masked(self.preds.data, self.target.data, self.preds.mask)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _auroc_compute(
            preds, target, self.mode, self.num_classes, self.pos_label, self.average, self.max_fpr
        )

"""``AUC`` module metric (reference
``src/torchmetrics/classification/auc.py:24``).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.auc import _auc_compute, _auc_compute_masked, _auc_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.ringbuffer import CatBuffer, cat_append, reject_valid_kwarg

Array = jax.Array


class AUC(Metric):
    """Area under any (x, y) curve (reference ``auc.py:24-78``).

    Two accumulation modes:

    - default: x/y accumulate in ``cat`` list states; compute runs the
      dense trapezoid on the concatenation.
    - ``capacity=N``: fixed-size :class:`CatBuffer` ring states — update,
      compute and sync are static-shape and fully jittable (the masked
      trapezoid kernel). Points past capacity are dropped (observable via
      the ``dropped`` counter / ``on_overflow``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AUC
        >>> metric = AUC(reorder=True)
        >>> round(float(metric(jnp.asarray([0.0, 0.5, 1.0]), jnp.asarray([0.0, 0.5, 1.0]))), 4)
        0.5
        >>> ring = AUC(reorder=True, capacity=8)  # static-shape, jittable
        >>> ring.update(jnp.asarray([0.0, 0.5, 1.0]), jnp.asarray([0.0, 0.5, 1.0]))
        >>> round(float(ring.compute()), 4)
        0.5
    """

    is_differentiable = False
    higher_is_better: bool = None
    full_state_update = False

    def __init__(self, reorder: bool = False, capacity: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.reorder = reorder
        self.capacity = capacity
        if capacity is not None:
            self.add_state("x", default=CatBuffer.zeros(capacity, (), jnp.float32), dist_reduce_fx="cat")
            self.add_state("y", default=CatBuffer.zeros(capacity, (), jnp.float32), dist_reduce_fx="cat")
        else:
            tpl = jnp.zeros((0,), jnp.float32)
            self.add_state("x", default=[], dist_reduce_fx="cat", template=tpl)
            self.add_state("y", default=[], dist_reduce_fx="cat", template=tpl)

    def update(self, x: Array, y: Array, valid: Optional[Array] = None) -> None:
        """``valid`` (bool ``(N,)``) is accepted in capacity mode only — the
        ragged-SPMD-batch contract shared with the other CatBuffer metrics."""
        x, y = _auc_update(jnp.asarray(x), jnp.asarray(y))
        if self.capacity is not None:
            self.x = cat_append(self.x, x, valid)
            self.y = cat_append(self.y, y, valid)
            return
        reject_valid_kwarg(valid)
        self.x.append(x)
        self.y.append(y)

    def compute(self) -> Array:
        if self.capacity is not None:
            return _auc_compute_masked(self.x.data, self.y.data, self.x.mask, reorder=self.reorder)
        x = dim_zero_cat(self.x)
        y = dim_zero_cat(self.y)
        return _auc_compute(x, y, reorder=self.reorder)

"""``Precision`` / ``Recall`` module metrics (reference
``src/torchmetrics/classification/precision_recall.py:23,162``).
"""
from typing import Any, Optional

import jax

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.precision_recall import _precision_compute, _recall_compute

Array = jax.Array


class Precision(StatScores):
    """Precision = TP / (TP + FP) (reference ``precision_recall.py:23-158``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Precision
        >>> preds = jnp.asarray([[0.75, 0.05, 0.05, 0.15], [0.1, 0.15, 0.7, 0.05],
        ...                      [0.3, 0.4, 0.2, 0.1], [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.asarray([0, 1, 3, 2])
        >>> metric = Precision(num_classes=4, average='macro')
        >>> round(float(metric(preds, target)), 4)
        0.25
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: Optional[str] = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")

        super().__init__(
            reduce="macro" if average in ("weighted", "none", None) else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            **kwargs,
        )
        self.average = average

    def compute(self) -> Array:
        """Reference ``precision_recall.py:151-158``."""
        tp, fp, tn, fn = self._get_final_stats()
        return _precision_compute(tp, fp, fn, self.average, self.mdmc_reduce)


class Recall(StatScores):
    """Recall = TP / (TP + FN) (reference ``precision_recall.py:162-297``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Recall
        >>> preds = jnp.asarray([[0.75, 0.05, 0.05, 0.15], [0.1, 0.15, 0.7, 0.05],
        ...                      [0.3, 0.4, 0.2, 0.1], [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.asarray([0, 1, 3, 2])
        >>> metric = Recall(num_classes=4, average='macro')
        >>> round(float(metric(preds, target)), 4)
        0.25
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: Optional[str] = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")

        super().__init__(
            reduce="macro" if average in ("weighted", "none", None) else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            **kwargs,
        )
        self.average = average

    def compute(self) -> Array:
        """Reference ``precision_recall.py:290-297``."""
        tp, fp, tn, fn = self._get_final_stats()
        return _recall_compute(tp, fp, fn, self.average, self.mdmc_reduce)

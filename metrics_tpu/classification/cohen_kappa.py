"""``CohenKappa`` module metric (reference
``src/torchmetrics/classification/cohen_kappa.py``, 105 LoC).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.cohen_kappa import _cohen_kappa_compute, _cohen_kappa_update
from metrics_tpu.metric import Metric

Array = jax.Array


class CohenKappa(Metric):
    """Cohen's kappa over an accumulated confusion matrix
    (reference ``cohen_kappa.py:24-105``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CohenKappa
        >>> preds = jnp.asarray([[0.75, 0.05, 0.05, 0.15], [0.1, 0.15, 0.7, 0.05],
        ...                      [0.3, 0.4, 0.2, 0.1], [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.asarray([0, 1, 3, 2])
        >>> metric = CohenKappa(num_classes=4)
        >>> round(float(metric(preds, target)), 4)
        0.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: int,
        weights: Optional[str] = None,
        threshold: float = 0.5,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.weights = weights
        self.threshold = threshold

        allowed_weights = ("linear", "quadratic", "none", None)
        if weights not in allowed_weights:
            raise ValueError(f"Argument weights needs to one of the following: {allowed_weights}")
        self.weights = None if weights == "none" else weights

        self.add_state("confmat", default=jnp.zeros((num_classes, num_classes), jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        confmat = _cohen_kappa_update(preds, target, self.num_classes, self.threshold)
        self.confmat += confmat

    def compute(self) -> Array:
        return _cohen_kappa_compute(self.confmat, self.weights)

"""``KLDivergence`` module metric (reference
``src/torchmetrics/classification/kl_divergence.py``, 105 LoC).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.kl_divergence import _kld_compute, _kld_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class KLDivergence(Metric):
    """KL(P || Q) (reference ``kl_divergence.py:24-105``).

    State is a scalar sum for mean/sum reductions and a ``cat`` list for
    ``reduction='none'`` (reference ``:77-82``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import KLDivergence
        >>> metric = KLDivergence()
        >>> p = jnp.asarray([[0.5, 0.5]])
        >>> q = jnp.asarray([[0.25, 0.75]])
        >>> round(float(metric(p, q)), 4)
        0.1438
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, log_prob: bool = False, reduction: Optional[str] = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(log_prob, bool):
            raise TypeError(f"Expected argument `log_prob` to be bool but got {log_prob}")
        allowed_reduction = ("mean", "sum", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.log_prob = log_prob
        self.reduction = reduction

        if self.reduction in ("mean", "sum"):
            self.add_state("measures", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        else:
            self.add_state("measures", default=[], dist_reduce_fx="cat")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def update(self, p: Array, q: Array) -> None:
        measures, total = _kld_update(p, q, self.log_prob)
        if self.reduction is None or self.reduction == "none":
            self.measures.append(measures)
        else:
            self.measures = measures.sum() + self.measures
        self.total = total + self.total

    def compute(self) -> Array:
        measures = dim_zero_cat(self.measures) if self.reduction in ("none", None) else self.measures
        return _kld_compute(measures, self.total, self.reduction)

"""``KLDivergence`` module metric (reference
``src/torchmetrics/classification/kl_divergence.py``, 105 LoC).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.kl_divergence import _kld_compute, _kld_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.ringbuffer import CatBuffer, cat_append, reject_valid_kwarg

Array = jax.Array


class KLDivergence(Metric):
    """KL(P || Q) (reference ``kl_divergence.py:24-105``).

    State is a scalar sum for mean/sum reductions and a ``cat`` list for
    ``reduction='none'`` (reference ``:77-82``). ``capacity=N`` gives the
    ``'none'`` output a static-shape :class:`CatBuffer` ring instead —
    jittable/shardable, ``(capacity,)`` output with NaN padding at unfilled
    slots (the same contract as ``CosineSimilarity(reduction='none',
    capacity=...)``); mean/sum reductions are already scalar sums and
    ignore ``capacity``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import KLDivergence
        >>> metric = KLDivergence()
        >>> p = jnp.asarray([[0.5, 0.5]])
        >>> q = jnp.asarray([[0.25, 0.75]])
        >>> round(float(metric(p, q)), 4)
        0.1438
        >>> ring = KLDivergence(reduction='none', capacity=4)  # jittable rows
        >>> ring.update(p, q)
        >>> [round(float(v), 4) for v in ring.compute()[:1]]
        [0.1438]
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(
        self,
        log_prob: bool = False,
        reduction: Optional[str] = "mean",
        capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(log_prob, bool):
            raise TypeError(f"Expected argument `log_prob` to be bool but got {log_prob}")
        allowed_reduction = ("mean", "sum", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.log_prob = log_prob
        self.reduction = reduction
        self.capacity = capacity

        if self.reduction in ("mean", "sum"):
            self.add_state("measures", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        elif capacity is not None:
            self.add_state(
                "measures", default=CatBuffer.zeros(capacity, (), jnp.float32), dist_reduce_fx="cat"
            )
        else:
            self.add_state(
                "measures", default=[], dist_reduce_fx="cat", template=jnp.zeros((0,), jnp.float32)
            )
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def update(self, p: Array, q: Array, valid: Optional[Array] = None) -> None:
        """``valid`` (bool ``(N,)``) masks rows — in the ``'none'``+capacity
        ring and in the mean/sum scalar folds (the shared ragged-SPMD-batch
        contract)."""
        measures, total = _kld_update(p, q, self.log_prob)
        if self.reduction is None or self.reduction == "none":
            if self.capacity is not None:
                if valid is not None:
                    # rows scatter out of the ring via the append mask, but
                    # total must count only valid rows
                    total = jnp.sum(jnp.asarray(valid, jnp.int32))
                self.measures = cat_append(self.measures, measures, valid)
            else:
                reject_valid_kwarg(valid)
                self.measures.append(measures)
        else:
            if valid is not None:
                # select, don't multiply: zero-padded invalid rows can carry
                # NaN measures and NaN * 0 is NaN
                measures = jnp.where(jnp.asarray(valid, bool), measures, 0.0)
                total = jnp.sum(jnp.asarray(valid, jnp.int32))
            self.measures = measures.sum() + self.measures
        self.total = total + self.total

    def compute(self) -> Array:
        if self.reduction in ("none", None) and self.capacity is not None:
            return jnp.where(self.measures.mask, self.measures.data, jnp.nan)
        measures = dim_zero_cat(self.measures) if self.reduction in ("none", None) else self.measures
        return _kld_compute(measures, self.total, self.reduction)

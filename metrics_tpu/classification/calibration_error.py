"""``CalibrationError`` module metric (reference
``src/torchmetrics/classification/calibration_error.py``, 107 LoC).
"""
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.calibration_error import (
    _ce_bin_update,
    _ce_compute,
    _ce_compute_from_bins,
    _ce_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.ringbuffer import reject_valid_kwarg

Array = jax.Array


class CalibrationError(Metric):
    """Top-label calibration error (reference ``calibration_error.py:24-107``).

    Two accumulation modes:

    - default: confidences/accuracies accumulate in ``cat`` list states
      (the reference's pattern, ``calibration_error.py:49-50``); binning
      happens at compute.
    - ``binned=True``: static ``(n_bins,)`` count/confidence/accuracy SUM
      counters updated in-graph. Because ``_ce_compute`` only ever consumes
      per-bin sums, this is **exactly** equal to the cat-list result (same
      ``searchsorted`` binning) while being constant-memory, fully
      jittable/functionalizable, and shardable — the formulation this
      framework prefers on TPU (SURVEY.md §7 "binned/streaming
      formulations"). Unlike the CatBuffer capacity modes there is no
      sample cap and nothing is ever dropped.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CalibrationError
        >>> metric = CalibrationError(n_bins=3)
        >>> conf = jnp.asarray([0.9, 0.6, 0.3, 0.8])
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> round(float(metric(conf, target)), 4)
        0.35
        >>> binned = CalibrationError(n_bins=3, binned=True)
        >>> binned.update(conf, target)
        >>> round(float(binned.compute()), 4)  # identical to the list mode
        0.35
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    DISTANCES = {"l1", "l2", "max"}

    def __init__(self, n_bins: int = 15, norm: str = "l1", binned: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if norm not in self.DISTANCES:
            raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")
        if not isinstance(n_bins, int) or n_bins <= 0:
            raise ValueError(f"Expected argument `n_bins` to be a int larger than 0 but got {n_bins}")
        self.n_bins = n_bins
        self.norm = norm
        self.binned = bool(binned)
        self.bin_boundaries = jnp.linspace(0, 1, n_bins + 1, dtype=jnp.float32)
        if self.binned:
            zeros = jnp.zeros((n_bins,), jnp.float32)
            self.add_state("bin_count", default=zeros, dist_reduce_fx="sum")
            self.add_state("bin_conf", default=zeros, dist_reduce_fx="sum")
            self.add_state("bin_acc", default=zeros, dist_reduce_fx="sum")
        else:
            tpl = jnp.zeros((0,), jnp.float32)
            self.add_state("confidences", default=[], dist_reduce_fx="cat", template=tpl)
            self.add_state("accuracies", default=[], dist_reduce_fx="cat", template=tpl)

    def update(self, preds: Array, target: Array, valid: Optional[Array] = None) -> None:
        """``valid`` (bool ``(N,)``) is accepted in binned mode only — the
        ragged-SPMD-batch contract shared with the CatBuffer metrics."""
        confidences, accuracies = _ce_update(preds, target)
        if self.binned:
            count, conf, acc = _ce_bin_update(confidences, accuracies, self.n_bins, valid)
            self.bin_count += count
            self.bin_conf += conf
            self.bin_acc += acc
            return
        reject_valid_kwarg(valid)
        self.confidences.append(confidences)
        self.accuracies.append(accuracies)

    def compute(self) -> Array:
        if self.binned:
            return _ce_compute_from_bins(self.bin_count, self.bin_conf, self.bin_acc, norm=self.norm)
        confidences = dim_zero_cat(self.confidences)
        accuracies = dim_zero_cat(self.accuracies)
        return _ce_compute(confidences, accuracies, self.bin_boundaries, norm=self.norm)

"""``CalibrationError`` module metric (reference
``src/torchmetrics/classification/calibration_error.py``, 107 LoC).
"""
from typing import Any, List

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.calibration_error import _ce_compute, _ce_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class CalibrationError(Metric):
    """Top-label calibration error (reference ``calibration_error.py:24-107``).

    Confidences/accuracies accumulate in ``cat`` list states; binning happens
    at compute (exact parity with the reference). For a constant-memory
    in-graph variant, bin at update time instead (the counts are sum states) —
    see ``BinnedPrecisionRecallCurve`` for the pattern.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CalibrationError
        >>> metric = CalibrationError(n_bins=3)
        >>> conf = jnp.asarray([0.9, 0.6, 0.3, 0.8])
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> round(float(metric(conf, target)), 4)
        0.35
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    DISTANCES = {"l1", "l2", "max"}

    def __init__(self, n_bins: int = 15, norm: str = "l1", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if norm not in self.DISTANCES:
            raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")
        if not isinstance(n_bins, int) or n_bins <= 0:
            raise ValueError(f"Expected argument `n_bins` to be a int larger than 0 but got {n_bins}")
        self.n_bins = n_bins
        self.norm = norm
        self.bin_boundaries = jnp.linspace(0, 1, n_bins + 1, dtype=jnp.float32)
        self.add_state("confidences", default=[], dist_reduce_fx="cat")
        self.add_state("accuracies", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        confidences, accuracies = _ce_update(preds, target)
        self.confidences.append(confidences)
        self.accuracies.append(accuracies)

    def compute(self) -> Array:
        confidences = dim_zero_cat(self.confidences)
        accuracies = dim_zero_cat(self.accuracies)
        return _ce_compute(confidences, accuracies, self.bin_boundaries, norm=self.norm)

"""``FBetaScore`` / ``F1Score`` module metrics (reference
``src/torchmetrics/classification/f_beta.py``, 275 LoC).
"""
from typing import Any, Optional

import jax

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.f_beta import _fbeta_compute

Array = jax.Array


class FBetaScore(StatScores):
    """F-beta score (reference ``f_beta.py:24-147``).

    .. note::
        ``higher_is_better`` is **True** here; the reference leaves the
        flag unset (``None``). An F-score: higher is better (PARITY.md "Class behavior-flag
        divergences" — strictly more informative for ``MetricTracker.best_metric``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import FBetaScore
        >>> preds = jnp.asarray([[0.75, 0.05, 0.05, 0.15], [0.1, 0.15, 0.7, 0.05],
        ...                      [0.3, 0.4, 0.2, 0.1], [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.asarray([0, 1, 3, 2])
        >>> metric = FBetaScore(num_classes=4, beta=0.5, average='macro')
        >>> round(float(metric(preds, target)), 4)
        0.25
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        beta: float = 1.0,
        threshold: float = 0.5,
        average: Optional[str] = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        self.beta = beta
        allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")

        super().__init__(
            reduce="macro" if average in ("weighted", "none", None) else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            **kwargs,
        )
        self.average = average

    def compute(self) -> Array:
        """Reference ``f_beta.py:140-147``."""
        tp, fp, tn, fn = self._get_final_stats()
        return _fbeta_compute(tp, fp, tn, fn, self.beta, self.ignore_index, self.average, self.mdmc_reduce)


class F1Score(FBetaScore):
    """F1 = F-beta with beta=1 (reference ``f_beta.py:150-275``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import F1Score
        >>> preds = jnp.asarray([[0.75, 0.05, 0.05, 0.15], [0.1, 0.15, 0.7, 0.05],
        ...                      [0.3, 0.4, 0.2, 0.1], [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.asarray([0, 1, 3, 2])
        >>> metric = F1Score(num_classes=4, average='macro')
        >>> round(float(metric(preds, target)), 4)
        0.25
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: Optional[str] = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes,
            beta=1.0,
            threshold=threshold,
            average=average,
            mdmc_average=mdmc_average,
            ignore_index=ignore_index,
            top_k=top_k,
            multiclass=multiclass,
            **kwargs,
        )

"""``AveragePrecision`` module metric (reference
``src/torchmetrics/classification/avg_precision.py``, 136 LoC).
"""
from typing import Any, List, Optional, Union

import jax

import jax.numpy as jnp

from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute,
    _average_precision_update,
    _binary_average_precision_masked,
    _multiclass_average_precision_masked,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.enums import DataType
from metrics_tpu.utilities.ringbuffer import init_score_ring_states, reject_valid_kwarg, score_ring_update

Array = jax.Array


class AveragePrecision(Metric):
    """Average precision over accumulated predictions
    (reference ``avg_precision.py:24-136``).

    .. note::
        ``higher_is_better`` is **True** here; the reference leaves the
        flag unset (``None``). A precision-family score: higher is better (PARITY.md "Class behavior-flag
        divergences" — strictly more informative for ``MetricTracker.best_metric``).

    Two accumulation modes (same design as :class:`~metrics_tpu.AUROC`):

    - default: cat list states, step-integral of the PR curve at compute.
    - ``capacity=N``: fixed-size :class:`CatBuffer` ring states — update,
      compute (masked tie-grouped AP), and cross-device sync are all
      static-shape and fully jittable / ``functionalize``-able.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AveragePrecision
        >>> preds = jnp.asarray([0.2, 0.8, 0.6, 0.4])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> metric = AveragePrecision()
        >>> round(float(metric(preds, target)), 4)
        1.0
    """

    _snapshot_attrs = ("num_classes", "pos_label", "mode")  # data-inferred at update (resilience snapshots)
    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        allowed_average = ("micro", "macro", "weighted", "none", None)
        if average not in allowed_average:
            raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
        self.average = average
        self.capacity = capacity
        if capacity is not None:
            if average == "micro":
                raise ValueError("`average='micro'` is not supported together with `capacity` mode")
            self.mode = init_score_ring_states(self, capacity, num_classes, pos_label)
        else:
            self.add_state("preds", default=[], dist_reduce_fx="cat", template=jnp.zeros((0,), jnp.float32))
            self.add_state("target", default=[], dist_reduce_fx="cat", template=jnp.zeros((0,), jnp.int32))

    def update(self, preds: Array, target: Array, valid: Optional[Array] = None) -> None:
        if self.capacity is not None:
            score_ring_update(self, preds, target, valid, "AveragePrecision")
            return
        reject_valid_kwarg(valid)
        preds, target, num_classes, pos_label = _average_precision_update(
            preds, target, self.num_classes, self.pos_label, self.average
        )
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def compute(self) -> Union[Array, List[Array]]:
        if self.capacity is not None:
            if self.mode == DataType.MULTICLASS:
                return _multiclass_average_precision_masked(
                    self.preds.data, self.target.data, self.preds.mask, self.num_classes, self.average
                )
            return _binary_average_precision_masked(self.preds.data, self.target.data, self.preds.mask)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _average_precision_compute(preds, target, self.num_classes, self.pos_label, self.average)

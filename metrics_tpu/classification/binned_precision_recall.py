"""Binned (constant-memory) precision-recall metrics (reference
``src/torchmetrics/classification/binned_precision_recall.py``, 300 LoC).

This is the TPU-preferred formulation of the curve metrics (SURVEY.md §7):
static ``(C, T)`` TP/FP/FN counters, fully jittable update and compute —
unlike the exact cat-state curves, these run inside compiled training steps
and sync with one ``psum``.

TPU-first change vs the reference: the reference loops over thresholds one at
a time "to conserve memory" (``binned_precision_recall.py:152-157``, an eager
CUDA concern); here the comparison is vectorized over a broadcast threshold
axis — one fused XLA reduction, no loop.
"""
from typing import Any, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute_with_precision_recall,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import METRIC_EPS, to_onehot

Array = jax.Array


def _recall_at_precision(
    precision: Array,
    recall: Array,
    thresholds: Array,
    min_precision: float,
) -> Tuple[Array, Array]:
    """Highest recall (tie-broken by precision, then threshold) subject to a
    precision floor (reference ``binned_precision_recall.py:24-43``) —
    vectorized lexicographic max instead of the reference's Python generator."""
    n = thresholds.shape[0]
    prec = precision[:n]
    rec = recall[:n]
    mask = prec >= min_precision
    r_max = jnp.max(jnp.where(mask, rec, -jnp.inf))
    mask2 = mask & (rec == r_max)
    p_max = jnp.max(jnp.where(mask2, prec, -jnp.inf))
    mask3 = mask2 & (prec == p_max)
    t_best = jnp.max(jnp.where(mask3, thresholds, -jnp.inf))

    any_valid = jnp.any(mask)
    max_recall = jnp.where(any_valid, r_max, 0.0).astype(recall.dtype)
    best_threshold = jnp.where(any_valid, t_best, 0.0)
    best_threshold = jnp.where(max_recall == 0.0, jnp.asarray(1e6, thresholds.dtype), best_threshold)
    return max_recall, best_threshold.astype(thresholds.dtype)


class BinnedPrecisionRecallCurve(Metric):
    """Constant-memory PR curve over fixed thresholds
    (reference ``binned_precision_recall.py:45-180``).

    Example (binary case):
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0, 0.1, 0.8, 0.4])
        >>> target = jnp.array([0, 1, 1, 0])
        >>> pr_curve = BinnedPrecisionRecallCurve(num_classes=1, thresholds=5)
        >>> precision, recall, thresholds = pr_curve(pred, target)
        >>> import numpy as np
        >>> np.asarray(precision).round(2)
        array([0.5, 0.5, 1. , 1. , 1. , 1. ], dtype=float32)
        >>> np.asarray(recall).round(2)
        array([1. , 0.5, 0.5, 0.5, 0. , 0. ], dtype=float32)
    """

    is_differentiable = False
    higher_is_better: Optional[bool] = None
    full_state_update = False

    def __init__(
        self,
        num_classes: int,
        thresholds: Union[int, Array, List[float]] = 100,
        use_pallas: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        # the hand-tiled VMEM kernel (ops/binned_counters.py) avoids the
        # (N, C, T) HBM intermediate. `use_pallas=None` defers to the kernel
        # dispatch layer (`ops/dispatch.py`: pallas on TPU, XLA elsewhere,
        # `METRICS_TPU_KERNEL_BACKEND` overrides); the explicit bool stays
        # honored as a per-instance force (True runs the interpreter off-TPU)
        self.use_pallas = use_pallas
        if isinstance(thresholds, int):
            self.num_thresholds = thresholds
            self.thresholds = jnp.linspace(0, 1.0, thresholds)
        elif thresholds is not None:
            if not isinstance(thresholds, (list, jax.Array)):
                raise ValueError("Expected argument `thresholds` to either be an integer, list of floats or a tensor")
            self.thresholds = jnp.asarray(thresholds)
            self.num_thresholds = self.thresholds.size

        for name in ("TPs", "FPs", "FNs"):
            self.add_state(
                name=name,
                default=jnp.zeros((num_classes, self.num_thresholds), dtype=jnp.float32),
                dist_reduce_fx="sum",
            )

    def update(self, preds: Array, target: Array) -> None:
        """Vectorized threshold counting (reference ``binned_precision_recall.py:139-157``)."""
        preds = jnp.asarray(preds)
        target = jnp.asarray(target)
        if preds.ndim == target.ndim == 1:
            preds = preds.reshape(-1, 1)
            target = target.reshape(-1, 1)
        if preds.ndim == target.ndim + 1:
            target = to_onehot(target, num_classes=self.num_classes)

        from metrics_tpu.ops import binned_counter_update

        if self.use_pallas is None:
            backend = None  # one switch for every caller: ops/dispatch.py
        elif self.use_pallas:
            backend = "pallas" if jax.default_backend() == "tpu" else "pallas-interpret"
        else:
            backend = "xla"
        tps, fps, fns = binned_counter_update(
            preds, (target == 1).astype(jnp.float32), self.thresholds, backend=backend
        )
        self.TPs += tps
        self.FPs += fps
        self.FNs += fns

    def compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        """Reference ``binned_precision_recall.py:159-172``."""
        precisions = (self.TPs + METRIC_EPS) / (self.TPs + self.FPs + METRIC_EPS)
        recalls = self.TPs / (self.TPs + self.FNs + METRIC_EPS)
        precisions = jnp.concatenate([precisions, jnp.ones((self.num_classes, 1), precisions.dtype)], axis=1)
        recalls = jnp.concatenate([recalls, jnp.zeros((self.num_classes, 1), recalls.dtype)], axis=1)
        if self.num_classes == 1:
            return precisions[0, :], recalls[0, :], self.thresholds
        return list(precisions), list(recalls), [self.thresholds for _ in range(self.num_classes)]


class BinnedAveragePrecision(BinnedPrecisionRecallCurve):
    """Constant-memory average precision
    (reference ``binned_precision_recall.py:183-233``).

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0, 1, 2, 3], jnp.float32)
        >>> target = jnp.array([0, 1, 1, 1])
        >>> print(f"{BinnedAveragePrecision(num_classes=1, thresholds=10)(pred, target):.4f}")
        1.0000
    """

    def compute(self) -> Union[List[Array], Array]:
        precisions, recalls, _ = super().compute()
        return _average_precision_compute_with_precision_recall(precisions, recalls, self.num_classes, average=None)


class BinnedRecallAtFixedPrecision(BinnedPrecisionRecallCurve):
    """Highest recall at a minimum precision
    (reference ``binned_precision_recall.py:236-300``).

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0, 0.2, 0.5, 0.8])
        >>> target = jnp.array([0, 1, 1, 0])
        >>> m = BinnedRecallAtFixedPrecision(num_classes=1, thresholds=10, min_precision=0.5)
        >>> recall, threshold = m(pred, target)
        >>> print(f"{recall:.4f} {threshold:.4f}")
        1.0000 0.1111
    """

    def __init__(
        self,
        num_classes: int,
        min_precision: float,
        thresholds: Union[int, Array, List[float]] = 100,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes=num_classes, thresholds=thresholds, **kwargs)
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:
        precisions, recalls, thresholds = super().compute()
        if self.num_classes == 1:
            return _recall_at_precision(precisions, recalls, thresholds, self.min_precision)

        recalls_at_p = []
        thresholds_at_p = []
        for i in range(self.num_classes):
            r, t = _recall_at_precision(precisions[i], recalls[i], thresholds[i], self.min_precision)
            recalls_at_p.append(r)
            thresholds_at_p.append(t)
        return jnp.stack(recalls_at_p), jnp.stack(thresholds_at_p)

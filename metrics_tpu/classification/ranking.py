"""Multilabel ranking module metrics (reference
``src/torchmetrics/classification/ranking.py``, 195 LoC).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.ranking import (
    _coverage_error_compute,
    _coverage_error_update,
    _label_ranking_average_precision_compute,
    _label_ranking_average_precision_update,
    _label_ranking_loss_compute,
    _label_ranking_loss_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class _RankingBase(Metric):
    is_differentiable = False
    higher_is_better: Optional[bool] = None
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("score", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")
        self.add_state("sample_weight", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self._weighted = False

    def _accumulate(self, score: Array, total: int, sample_weight: Optional[Array]) -> None:
        self.score = score + self.score
        self.total = total + self.total
        if sample_weight is not None:
            self._weighted = True
            self.sample_weight = sample_weight + self.sample_weight

    def _final(self, compute_fn) -> Array:
        sw = self.sample_weight if self._weighted else None
        return compute_fn(self.score, self.total, sw)


class CoverageError(_RankingBase):
    """How far down the ranking to go to cover all true labels
    (reference ``ranking.py:24-77``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CoverageError
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.1, 0.9, 0.3]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0]])
        >>> metric = CoverageError()
        >>> round(float(metric(preds, target)), 4)
        1.5
    """

    higher_is_better = False

    def update(self, preds: Array, target: Array, sample_weight: Optional[Array] = None) -> None:
        score, total, sw = _coverage_error_update(preds, target, sample_weight)
        self._accumulate(score, total, sw)

    def compute(self) -> Array:
        return self._final(_coverage_error_compute)


class LabelRankingAveragePrecision(_RankingBase):
    """Average fraction of correctly-ordered relevant labels
    (reference ``ranking.py:80-135``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import LabelRankingAveragePrecision
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.1, 0.9, 0.3]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0]])
        >>> metric = LabelRankingAveragePrecision()
        >>> round(float(metric(preds, target)), 4)
        1.0
    """

    higher_is_better = True

    def update(self, preds: Array, target: Array, sample_weight: Optional[Array] = None) -> None:
        score, total, sw = _label_ranking_average_precision_update(preds, target, sample_weight)
        self._accumulate(score, total, sw)

    def compute(self) -> Array:
        return self._final(_label_ranking_average_precision_compute)


class LabelRankingLoss(_RankingBase):
    """Average number of incorrectly-ordered label pairs
    (reference ``ranking.py:138-195``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import LabelRankingLoss
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.1, 0.9, 0.3]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0]])
        >>> metric = LabelRankingLoss()
        >>> round(float(metric(preds, target)), 4)
        0.0
    """

    higher_is_better = False

    def update(self, preds: Array, target: Array, sample_weight: Optional[Array] = None) -> None:
        score, total, sw = _label_ranking_loss_update(preds, target, sample_weight)
        self._accumulate(score, total, sw)

    def compute(self) -> Array:
        return self._final(_label_ranking_loss_compute)

"""``JaccardIndex`` module metric (reference
``src/torchmetrics/classification/jaccard.py``, 113 LoC).
"""
from typing import Any, Optional

import jax

from metrics_tpu.classification.confusion_matrix import ConfusionMatrix
from metrics_tpu.functional.classification.jaccard import _jaccard_from_confmat

Array = jax.Array


class JaccardIndex(ConfusionMatrix):
    """Jaccard index (IoU) over an accumulated confusion matrix
    (reference ``jaccard.py:24-113``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import JaccardIndex
        >>> preds = jnp.asarray([[0.75, 0.05, 0.05, 0.15], [0.1, 0.15, 0.7, 0.05],
        ...                      [0.3, 0.4, 0.2, 0.1], [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.asarray([0, 1, 3, 2])
        >>> metric = JaccardIndex(num_classes=4)
        >>> round(float(metric(preds, target)), 4)
        0.25
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: int,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        absent_score: float = 0.0,
        threshold: float = 0.5,
        multilabel: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes,
            normalize=None,
            threshold=threshold,
            multilabel=multilabel,
            **kwargs,
        )
        self.average = average
        self.ignore_index = ignore_index
        self.absent_score = absent_score

    def compute(self) -> Array:
        """Reference ``jaccard.py:106-113``."""
        return _jaccard_from_confmat(
            self.confmat, self.num_classes, self.average, self.ignore_index, self.absent_score
        )

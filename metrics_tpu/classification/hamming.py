"""``HammingDistance`` module metric (reference
``src/torchmetrics/classification/hamming.py``, 93 LoC).
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.hamming import _hamming_distance_compute, _hamming_distance_update
from metrics_tpu.metric import Metric

Array = jax.Array


class HammingDistance(Metric):
    """Average Hamming loss (reference ``hamming.py:24-93``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import HammingDistance
        >>> preds = jnp.asarray([[0.75, 0.05, 0.05, 0.15], [0.1, 0.15, 0.7, 0.05],
        ...                      [0.3, 0.4, 0.2, 0.1], [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.asarray([0, 1, 3, 2])
        >>> metric = HammingDistance()
        >>> round(float(metric(preds, target)), 4)
        0.375
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(self, threshold: float = 0.5, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.threshold = threshold
        self.add_state("correct", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        correct, total = _hamming_distance_update(preds, target, self.threshold)
        self.correct += correct
        self.total += total

    def compute(self) -> Array:
        return _hamming_distance_compute(self.correct, self.total)

"""``PrecisionRecallCurve`` module metric (reference
``src/torchmetrics/classification/precision_recall_curve.py:28``).

Exact-curve form: raw preds/target accumulate in ``cat`` list states and the
curve is computed eagerly on the gathered concatenation (the reference's
all_gather-heavy path, SURVEY.md §2.5). Inside compiled code prefer
``BinnedPrecisionRecallCurve``.
"""
from typing import Any, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_masked,
    _multiclass_precision_recall_curve_masked,
    _precision_recall_curve_compute,
    _precision_recall_curve_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.enums import DataType
from metrics_tpu.utilities.ringbuffer import init_score_ring_states, reject_valid_kwarg, score_ring_update

Array = jax.Array


class PrecisionRecallCurve(Metric):
    """Exact precision-recall pairs per threshold
    (reference ``precision_recall_curve.py:28-144``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PrecisionRecallCurve
        >>> preds = jnp.asarray([0.2, 0.8, 0.6, 0.4])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> metric = PrecisionRecallCurve()
        >>> precision, recall, thresholds = metric(preds, target)
        >>> print(precision)
        [1. 1. 1.]
        >>> print(recall)
        [1.  0.5 0. ]
    """

    _snapshot_attrs = ("num_classes", "pos_label", "mode")  # data-inferred at update (resilience snapshots)
    is_differentiable = False
    higher_is_better: Optional[bool] = None
    full_state_update = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.capacity = capacity
        if capacity is not None:
            self.mode = init_score_ring_states(self, capacity, num_classes, pos_label)
        else:
            self.add_state("preds", default=[], dist_reduce_fx="cat", template=jnp.zeros((0,), jnp.float32))
            self.add_state("target", default=[], dist_reduce_fx="cat", template=jnp.zeros((0,), jnp.int32))

    def update(self, preds: Array, target: Array, valid: Optional[Array] = None) -> None:
        """Reference ``precision_recall_curve.py:119-133``."""
        if self.capacity is not None:
            score_ring_update(self, preds, target, valid, "PrecisionRecallCurve")
            return
        reject_valid_kwarg(valid)
        preds, target, num_classes, pos_label = _precision_recall_curve_update(
            preds, target, self.num_classes, self.pos_label
        )
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        """Reference ``precision_recall_curve.py:135-144``."""
        if self.capacity is not None:
            if self.mode == DataType.MULTICLASS:
                return _multiclass_precision_recall_curve_masked(
                    self.preds.data, self.target.data, self.preds.mask, self.num_classes
                )
            return _binary_precision_recall_curve_masked(self.preds.data, self.target.data, self.preds.mask)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _precision_recall_curve_compute(preds, target, self.num_classes, self.pos_label)

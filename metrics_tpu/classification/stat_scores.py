"""``StatScores`` module metric (reference
``src/torchmetrics/classification/stat_scores.py:24``).
"""
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import _stat_scores_compute, _stat_scores_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class StatScores(Metric):
    """Accumulate tp/fp/tn/fn counts (reference ``classification/stat_scores.py:24-235``).

    State layout follows the reference: fixed-shape ``sum``-reduced counters
    for global reductions (``()`` for micro, ``(C,)`` for macro — the
    TPU-friendly static form), and ``cat`` lists when per-sample statistics
    must be kept (``reduce='samples'`` / ``mdmc_reduce='samplewise'``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import StatScores
        >>> preds = jnp.asarray([[0.75, 0.05, 0.05, 0.15], [0.1, 0.15, 0.7, 0.05],
        ...                      [0.3, 0.4, 0.2, 0.1], [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.asarray([0, 1, 3, 2])
        >>> metric = StatScores(reduce='micro')
        >>> print(metric(preds, target))
        [1 3 9 3 4]
    """

    is_differentiable = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    @property
    def _valid_mask_always(self) -> bool:
        """Whether THIS instance's update consumes `valid` row masks (the
        traced row-drop/padding contract — utilities/guard.py::
        _consumes_valid_mask, ops/padding.py). A property, not a class
        flag: per-sample reductions keep one output row per input row and
        negative ``ignore_index`` drops rows by concrete indexing, so those
        configs refuse masks and must fall back to the eager drop path."""
        if self.reduce == "samples" or self.mdmc_reduce == "samplewise":
            return False
        if self.ignore_index is not None and self.ignore_index < 0:
            return False
        return True

    def __init__(
        self,
        threshold: float = 0.5,
        top_k: Optional[int] = None,
        reduce: str = "micro",
        num_classes: Optional[int] = None,
        ignore_index: Optional[int] = None,
        mdmc_reduce: Optional[str] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.reduce = reduce
        self.mdmc_reduce = mdmc_reduce
        self.num_classes = num_classes
        self.threshold = threshold
        self.multiclass = multiclass
        self.ignore_index = ignore_index
        self.top_k = top_k

        if reduce not in ["micro", "macro", "samples"]:
            raise ValueError(f"The `reduce` {reduce} is not valid.")
        if mdmc_reduce not in [None, "samplewise", "global"]:
            raise ValueError(f"The `mdmc_reduce` {mdmc_reduce} is not valid.")
        if reduce == "macro" and (not num_classes or num_classes < 1):
            raise ValueError("When you set `reduce` as 'macro', you have to provide the number of classes.")
        if num_classes and ignore_index is not None and (not ignore_index < num_classes or num_classes == 1):
            raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

        if mdmc_reduce != "samplewise" and reduce != "samples":
            shape = () if reduce == "micro" else (num_classes,)
            for s in ("tp", "fp", "tn", "fn"):
                self.add_state(s, default=jnp.zeros(shape, dtype=jnp.int32), dist_reduce_fx="sum")
        else:
            for s in ("tp", "fp", "tn", "fn"):
                self.add_state(s, default=[], dist_reduce_fx="cat", template=jnp.zeros((0,), jnp.int32))

    def update(self, preds: Array, target: Array, valid: Optional[Array] = None) -> None:
        """Accumulate stat scores for a batch (reference ``stat_scores.py:170-192``).

        ``valid`` is an optional bool ``(N,)`` row mask: masked rows
        contribute to no counter — the in-graph row-drop path
        (``on_invalid='drop'``) and the padding ladder
        (``pad_batches=True``) both ride it."""
        tp, fp, tn, fn = _stat_scores_update(
            preds,
            target,
            reduce=self.reduce,
            mdmc_reduce=self.mdmc_reduce,
            threshold=self.threshold,
            num_classes=self.num_classes,
            top_k=self.top_k,
            multiclass=self.multiclass,
            ignore_index=self.ignore_index,
            valid=valid,
        )
        if self.reduce != "samples" and self.mdmc_reduce != "samplewise":
            self.tp += tp
            self.fp += fp
            self.tn += tn
            self.fn += fn
        else:
            self.tp.append(tp)
            self.fp.append(fp)
            self.tn.append(tn)
            self.fn.append(fn)

    def _get_final_stats(self) -> Tuple[Array, Array, Array, Array]:
        """Concat list states / pass through tensors (reference ``stat_scores.py:215-222``)."""
        tp = dim_zero_cat(self.tp) if isinstance(self.tp, list) else self.tp
        fp = dim_zero_cat(self.fp) if isinstance(self.fp, list) else self.fp
        tn = dim_zero_cat(self.tn) if isinstance(self.tn, list) else self.tn
        fn = dim_zero_cat(self.fn) if isinstance(self.fn, list) else self.fn
        return tp, fp, tn, fn

    def compute(self) -> Array:
        """Final [tp, fp, tn, fn, support] stack (reference ``stat_scores.py:224-235``)."""
        tp, fp, tn, fn = self._get_final_stats()
        return _stat_scores_compute(tp, fp, tn, fn)

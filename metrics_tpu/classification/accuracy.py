"""``Accuracy`` module metric (reference
``src/torchmetrics/classification/accuracy.py:31``).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.accuracy import (
    _accuracy_compute,
    _accuracy_update,
    _check_subset_validity,
    _mode,
    _subset_accuracy_compute,
    _subset_accuracy_update,
)
from metrics_tpu.utilities.enums import AverageMethod, DataType

Array = jax.Array


class Accuracy(StatScores):
    """Accuracy over any classification input type
    (reference ``classification/accuracy.py:31-330``).

    The input mode (binary / multiclass / multilabel / mdmc) is resolved from
    static shape+dtype info, so it is fixed at trace time and the whole update
    compiles to one XLA graph.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> preds = jnp.asarray([[0.75, 0.05, 0.05, 0.15], [0.1, 0.15, 0.7, 0.05],
        ...                      [0.3, 0.4, 0.2, 0.1], [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.asarray([0, 1, 3, 2])
        >>> metric = Accuracy(num_classes=4)
        >>> round(float(metric(preds, target)), 4)
        0.25
    """

    _snapshot_attrs = ("mode", "subset_accuracy")  # data-inferred at update (resilience snapshots)
    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    @property
    def _valid_mask_always(self) -> bool:
        # exact-match subset accuracy has no masked counting rule; while the
        # flag is (still) set the update would reject `valid`, so the guard/
        # ladder must treat this config as mask-refusing
        if self.subset_accuracy:
            return False
        return super()._valid_mask_always

    def __init__(
        self,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        average: Optional[str] = "micro",
        # None, not "global": multidim inputs must raise until the caller
        # picks a reduction — the reference's class/functional defaults
        # genuinely differ here (classification/accuracy.py:168 vs
        # functional/classification/accuracy.py) and the error is part of
        # the class contract
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        subset_accuracy: bool = False,
        **kwargs: Any,
    ) -> None:
        allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")

        _reduce_options = (AverageMethod.WEIGHTED, AverageMethod.NONE, None)
        if "reduce" not in kwargs:
            kwargs["reduce"] = "macro" if average in _reduce_options else average
        if "mdmc_reduce" not in kwargs:
            kwargs["mdmc_reduce"] = mdmc_average

        super().__init__(
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            **kwargs,
        )

        if top_k is not None and (not isinstance(top_k, int) or top_k <= 0):
            raise ValueError(f"The `top_k` should be an integer larger than 0, got {top_k}")

        self.average = average
        self.threshold = threshold
        self.top_k = top_k
        self.subset_accuracy = subset_accuracy
        self.mode: Optional[DataType] = None
        self.multiclass = multiclass
        self.ignore_index = ignore_index

        if self.subset_accuracy:
            self.add_state("correct", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")
            self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array, valid: Optional[Array] = None) -> None:
        """Reference ``accuracy.py:209-263``.

        ``valid`` is an optional bool ``(N,)`` row mask (masked rows
        contribute nothing — the traced drop/padding path); exact-match
        ``subset_accuracy`` has no masked counting rule and rejects it."""
        mode = _mode(preds, target, self.threshold, self.top_k, self.num_classes, self.multiclass, self.ignore_index)

        if not self.mode:
            self.mode = mode
        elif self.mode != mode:
            raise ValueError(f"You can not use {mode} inputs with {self.mode} inputs.")

        if self.subset_accuracy and not _check_subset_validity(self.mode):
            self.subset_accuracy = False

        if self.subset_accuracy:
            if valid is not None:
                raise ValueError("`valid` row masks are not supported with `subset_accuracy`")
            correct, total = _subset_accuracy_update(
                preds, target, threshold=self.threshold, top_k=self.top_k, ignore_index=self.ignore_index
            )
            self.correct += correct
            self.total += total
        else:
            tp, fp, tn, fn = _accuracy_update(
                preds,
                target,
                reduce=self.reduce,
                mdmc_reduce=self.mdmc_reduce,
                threshold=self.threshold,
                num_classes=self.num_classes,
                top_k=self.top_k,
                multiclass=self.multiclass,
                ignore_index=self.ignore_index,
                mode=self.mode,
                valid=valid,
            )
            if self.reduce != "samples" and self.mdmc_reduce != "samplewise":
                self.tp += tp
                self.fp += fp
                self.tn += tn
                self.fn += fn
            else:
                self.tp.append(tp)
                self.fp.append(fp)
                self.tn.append(tn)
                self.fn.append(fn)

    def compute(self) -> Array:
        """Reference ``accuracy.py:265-273``."""
        if not self.mode:
            raise RuntimeError("You have to have determined mode.")
        if self.subset_accuracy:
            return _subset_accuracy_compute(self.correct, self.total)
        tp, fp, tn, fn = self._get_final_stats()
        return _accuracy_compute(tp, fp, tn, fn, self.average, self.mdmc_reduce, self.mode)

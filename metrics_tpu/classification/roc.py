"""``ROC`` module metric (reference
``src/torchmetrics/classification/roc.py:26``).
"""
from typing import Any, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.roc import (
    _binary_roc_masked,
    _multiclass_roc_masked,
    _roc_compute,
    _roc_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.enums import DataType
from metrics_tpu.utilities.ringbuffer import init_score_ring_states, reject_valid_kwarg, score_ring_update

Array = jax.Array


class ROC(Metric):
    """Receiver operating characteristic (reference ``roc.py:26-143``).

    ``capacity=N`` switches to :class:`CatBuffer` ring states with a fully
    jittable masked compute returning terminal-padded ``(cap + 1,)`` arrays
    (stacked ``(C, cap + 1)`` one-vs-rest for multiclass) — trapezoidal
    integration over the padded curve equals the exact eager curve.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ROC
        >>> preds = jnp.asarray([0.2, 0.8, 0.6, 0.4])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> metric = ROC()
        >>> fpr, tpr, thresholds = metric(preds, target)
        >>> print(fpr)
        [0.  0.  0.  0.5 1. ]
        >>> print(tpr)
        [0.  0.5 1.  1.  1. ]
    """

    _snapshot_attrs = ("num_classes", "pos_label", "mode")  # data-inferred at update (resilience snapshots)
    is_differentiable = False
    higher_is_better: Optional[bool] = None
    full_state_update = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.capacity = capacity
        if capacity is not None:
            self.mode = init_score_ring_states(self, capacity, num_classes, pos_label)
        else:
            self.add_state("preds", default=[], dist_reduce_fx="cat", template=jnp.zeros((0,), jnp.float32))
            self.add_state("target", default=[], dist_reduce_fx="cat", template=jnp.zeros((0,), jnp.int32))

    def update(self, preds: Array, target: Array, valid: Optional[Array] = None) -> None:
        if self.capacity is not None:
            score_ring_update(self, preds, target, valid, "ROC")
            return
        reject_valid_kwarg(valid)
        preds, target, num_classes, pos_label = _roc_update(preds, target, self.num_classes, self.pos_label)
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        if self.capacity is not None:
            if self.mode == DataType.MULTICLASS:
                return _multiclass_roc_masked(self.preds.data, self.target.data, self.preds.mask, self.num_classes)
            return _binary_roc_masked(self.preds.data, self.target.data, self.preds.mask)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _roc_compute(preds, target, self.num_classes, self.pos_label)

"""``MatthewsCorrCoef`` module metric (reference
``src/torchmetrics/classification/matthews_corrcoef.py``, 95 LoC).
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.matthews_corrcoef import (
    _matthews_corrcoef_compute,
    _matthews_corrcoef_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class MatthewsCorrCoef(Metric):
    """Matthews correlation coefficient over an accumulated confusion matrix
    (reference ``matthews_corrcoef.py:24-95``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MatthewsCorrCoef
        >>> preds = jnp.asarray([[0.75, 0.05, 0.05, 0.15], [0.1, 0.15, 0.7, 0.05],
        ...                      [0.3, 0.4, 0.2, 0.1], [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.asarray([0, 1, 3, 2])
        >>> metric = MatthewsCorrCoef(num_classes=4)
        >>> round(float(metric(preds, target)), 4)
        0.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, num_classes: int, threshold: float = 0.5, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.threshold = threshold
        self.add_state("confmat", default=jnp.zeros((num_classes, num_classes), jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        confmat = _matthews_corrcoef_update(preds, target, self.num_classes, self.threshold)
        self.confmat += confmat

    def compute(self) -> Array:
        return _matthews_corrcoef_compute(self.confmat)

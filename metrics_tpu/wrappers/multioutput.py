"""``MultioutputWrapper`` (reference
``src/torchmetrics/wrappers/multioutput.py:24-145``).
"""
from copy import deepcopy
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import apply_to_collection

Array = jax.Array

_ARRAY_TYPES = (jax.Array, np.ndarray)


def _get_nan_indices(*tensors: Array) -> Array:
    """Rows containing NaN in any input (reference ``multioutput.py:14-21``)."""
    if len(tensors) == 0:
        raise ValueError("Must pass at least one tensor as argument")
    sentinel = tensors[0]
    nan_idxs = jnp.zeros(len(sentinel), dtype=bool)
    for tensor in tensors:
        permuted_tensor = jnp.asarray(tensor).reshape(len(sentinel), -1)
        nan_idxs = nan_idxs | jnp.any(jnp.isnan(permuted_tensor), axis=1)
    return nan_idxs


class MultioutputWrapper(Metric):
    """One metric clone per output column (reference ``multioutput.py:24-145``).

    NaN-row removal is data-dependent (dynamic shapes) and therefore runs
    eagerly, like every wrapper.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError, MultioutputWrapper
        >>> metric = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
        >>> metric.update(jnp.asarray([[1.0, 2.0]]), jnp.asarray([[1.0, 4.0]]))
        >>> [round(float(v), 2) for v in metric.compute()]
        [0.0, 4.0]
    """

    is_differentiable = False
    jittable_update = False
    jittable_compute = False

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
    ) -> None:
        super().__init__()
        self.metrics = [deepcopy(base_metric) for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs
        # NaN-row removal is a dynamic-shape filter; without it the body is a
        # pure column-split delegate and functionalize() can trace it
        self._wrapper_trace_safe = not remove_nans

    def _get_args_kwargs_by_output(self, *args: Array, **kwargs: Array) -> List[Tuple[list, dict]]:
        """Reference ``multioutput.py:98-117``."""
        args_kwargs_by_output = []
        for i in range(len(self.metrics)):
            def select(x, _i=i):
                return jnp.take(jnp.asarray(x), jnp.array([_i]), axis=self.output_dim)

            selected_args = list(apply_to_collection(args, _ARRAY_TYPES, select))
            selected_kwargs = apply_to_collection(kwargs, _ARRAY_TYPES, select)
            if self.remove_nans:
                args_kwargs = tuple(selected_args) + tuple(selected_kwargs.values())
                nan_idxs = _get_nan_indices(*args_kwargs)
                selected_args = [jnp.asarray(arg)[~nan_idxs] for arg in selected_args]
                selected_kwargs = {k: jnp.asarray(v)[~nan_idxs] for k, v in selected_kwargs.items()}
            if self.squeeze_outputs:
                selected_args = [arg.squeeze(self.output_dim) for arg in selected_args]
                selected_kwargs = {k: v.squeeze(self.output_dim) for k, v in selected_kwargs.items()}
            args_kwargs_by_output.append((selected_args, selected_kwargs))
        return args_kwargs_by_output

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Reference ``multioutput.py:119-123``."""
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs):
            metric.update(*selected_args, **selected_kwargs)

    def compute(self) -> List[Array]:
        """Reference ``multioutput.py:125-127``."""
        return [m.compute() for m in self.metrics]

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Reference ``multioutput.py:129-141``."""
        results = []
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs):
            results.append(metric(*selected_args, **selected_kwargs))
        if results[0] is None:
            return None
        return results

    def reset(self) -> None:
        """Reference ``multioutput.py:143-145``."""
        for metric in self.metrics:
            metric.reset()
        super().reset()

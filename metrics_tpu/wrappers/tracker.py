"""``MetricTracker`` (reference ``src/torchmetrics/wrappers/tracker.py:26-213``)."""
import warnings
from copy import deepcopy
from typing import Any, Dict, List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.collections import MetricCollection
from metrics_tpu.metric import Metric

Array = jax.Array


class MetricTracker:
    """Track a metric (or collection) over time steps
    (reference ``tracker.py:26-213``); a plain list of copies instead of the
    reference's ``ModuleList``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError, MetricTracker
        >>> tracker = MetricTracker(MeanSquaredError(), maximize=False)
        >>> for preds, target in [([1.0], [2.0]), ([1.0], [1.5])]:
        ...     tracker.increment()
        ...     tracker.update(jnp.asarray(preds), jnp.asarray(target))
        >>> round(float(tracker.best_metric()), 4)
        0.25
    """

    def __init__(self, metric: Union[Metric, MetricCollection], maximize: Union[bool, List[bool]] = True) -> None:
        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(
                f"Metric arg need to be an instance of a metrics_tpu `Metric` or `MetricCollection` but got {metric}"
            )
        self._base_metric = metric
        if not isinstance(maximize, (bool, list)):
            raise ValueError("Argument `maximize` should either be a single bool or list of bool")
        if isinstance(maximize, list) and isinstance(metric, MetricCollection) and len(maximize) != len(metric):
            raise ValueError("The len of argument `maximize` should match the length of the metric collection")
        self.maximize = maximize
        self._metrics: List[Union[Metric, MetricCollection]] = []
        self._increment_called = False

    @property
    def n_steps(self) -> int:
        """Reference ``tracker.py:112-115``."""
        return len(self._metrics)

    def increment(self) -> None:
        """Reference ``tracker.py:117-120``."""
        self._increment_called = True
        self._metrics.append(deepcopy(self._base_metric))

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self._check_for_increment("forward")
        return self._metrics[-1](*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._check_for_increment("update")
        self._metrics[-1].update(*args, **kwargs)

    def compute(self) -> Any:
        self._check_for_increment("compute")
        return self._metrics[-1].compute()

    def compute_all(self) -> Union[Array, Dict[str, Array]]:
        """Reference ``tracker.py:137-144``."""
        self._check_for_increment("compute_all")
        res = [metric.compute() for metric in self._metrics]
        if isinstance(self._base_metric, MetricCollection):
            keys = res[0].keys()
            return {k: jnp.stack([jnp.asarray(r[k]) for r in res], axis=0) for k in keys}
        return jnp.stack([jnp.asarray(r) for r in res], axis=0)

    def reset(self) -> None:
        self._metrics[-1].reset()

    def reset_all(self) -> None:
        for metric in self._metrics:
            metric.reset()

    def best_metric(
        self, return_step: bool = False
    ) -> Union[None, float, Tuple[int, float], Dict[str, Any], Tuple[Dict[str, Any], Dict[str, Any]]]:
        """Reference ``tracker.py:160-208``."""
        if isinstance(self._base_metric, Metric):
            fn = jnp.argmax if self.maximize else jnp.argmin
            try:
                all_res = self.compute_all()
                idx = int(fn(all_res))
                best = float(all_res[idx])
                if return_step:
                    return idx, best
                return best
            except (ValueError, TypeError) as error:
                warnings.warn(
                    f"Encountered the following error when trying to get the best metric: {error}"
                    "this is probably due to the 'best' not being defined for this metric."
                    "Returning `None` instead.",
                    UserWarning,
                )
                if return_step:
                    return None, None
                return None

        res = self.compute_all()
        maximize = self.maximize if isinstance(self.maximize, list) else len(res) * [self.maximize]
        idx, best = {}, {}
        for i, (k, v) in enumerate(res.items()):
            try:
                fn = jnp.argmax if maximize[i] else jnp.argmin
                best_i = int(fn(v))
                idx[k], best[k] = best_i, float(v[best_i])
            except (ValueError, TypeError) as error:
                warnings.warn(
                    f"Encountered the following error when trying to get the best metric for metric {k}:"
                    f"{error} this is probably due to the 'best' not being defined for this metric."
                    "Returning `None` instead.",
                    UserWarning,
                )
                idx[k], best[k] = None, None
        if return_step:
            return idx, best
        return best

    def _check_for_increment(self, method: str) -> None:
        """Reference ``tracker.py:210-213``."""
        if not self._increment_called:
            raise ValueError(f"`{method}` cannot be called before `.increment()` has been called")

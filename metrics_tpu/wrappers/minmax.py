"""``MinMaxMetric`` (reference ``src/torchmetrics/wrappers/minmax.py:23-110``)."""
from typing import Any, Dict, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric

Array = jax.Array


class MinMaxMetric(Metric):
    """Track the running min/max of a wrapped metric's compute value
    (reference ``minmax.py:23-110``; min/max are plain attributes updated at
    compute time, not registered states — matching ``minmax.py:54-88``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError, MinMaxMetric
        >>> metric = MinMaxMetric(MeanSquaredError())
        >>> metric.update(jnp.asarray([1.0]), jnp.asarray([2.0]))
        >>> {k: round(float(v), 4) for k, v in sorted(metric.compute().items())}
        {'max': 1.0, 'min': 1.0, 'raw': 1.0}
    """

    jittable_update = False
    jittable_compute = False
    full_state_update = True

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `metrics_tpu.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        self.min_val = jnp.asarray(jnp.inf)
        self.max_val = jnp.asarray(-jnp.inf)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._base_metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        val = self._base_metric.compute()
        if not self._is_suitable_val(val):
            raise RuntimeError(
                f"Returned value from base metric should be a scalar (int, float or tensor of size 1, but got {val}"
            )
        val = jnp.asarray(val)
        self.max_val = jnp.maximum(self.max_val, val)
        self.min_val = jnp.minimum(self.min_val, val)
        return {"raw": val, "max": self.max_val, "min": self.min_val}

    def reset(self) -> None:
        """Reference ``minmax.py:91-94``."""
        super().reset()
        self._base_metric.reset()
        self.min_val = jnp.asarray(jnp.inf)
        self.max_val = jnp.asarray(-jnp.inf)

    @staticmethod
    def _is_suitable_val(val: Union[int, float, Array]) -> bool:
        """Reference ``minmax.py:97-103``."""
        if isinstance(val, (int, float)):
            return True
        if isinstance(val, (jax.Array,)) or hasattr(val, "size"):
            return val.size == 1
        return False

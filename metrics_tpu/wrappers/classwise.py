"""``ClasswiseWrapper`` (reference
``src/torchmetrics/wrappers/classwise.py:8-73``).
"""
from typing import Any, Dict, List, Optional

import jax

from metrics_tpu.metric import Metric

Array = jax.Array


class ClasswiseWrapper(Metric):
    """Unroll a per-class result tensor into a labeled dict
    (reference ``classwise.py:8-73``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy, ClasswiseWrapper
        >>> metric = ClasswiseWrapper(Accuracy(num_classes=3, average=None), labels=["cat", "dog", "bird"])
        >>> preds = jnp.asarray([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1], [0.1, 0.1, 0.8]])
        >>> target = jnp.asarray([0, 1, 1])
        >>> metric.update(preds, target)
        >>> {k: round(float(v), 2) for k, v in sorted(metric.compute().items())}
        {'accuracy_bird': 0.0, 'accuracy_cat': 1.0, 'accuracy_dog': 0.5}
    """

    jittable_update = False
    jittable_compute = False
    # pure delegate body: functionalize() can swap child state and trace it
    _wrapper_trace_safe = True

    def __init__(self, metric: Metric, labels: Optional[List[str]] = None) -> None:
        super().__init__()
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected argument `metric` to be an instance of `metrics_tpu.Metric` but got {metric}")
        if labels is not None and not (isinstance(labels, list) and all(isinstance(lab, str) for lab in labels)):
            raise ValueError(f"Expected argument `labels` to either be `None` or a list of strings but got {labels}")
        self.metric = metric
        self.labels = labels

    def _convert(self, x: Array) -> Dict[str, Any]:
        name = self.metric.__class__.__name__.lower()
        if self.labels is None:
            return {f"{name}_{i}": val for i, val in enumerate(x)}
        return {f"{name}_{lab}": val for lab, val in zip(self.labels, x)}

    def update(self, *args: Any, **kwargs: Any) -> None:
        self.metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        return self._convert(self.metric.compute())

    def reset(self) -> None:
        self.metric.reset()
        super().reset()

"""``BootStrapper`` wrapper (reference
``src/torchmetrics/wrappers/bootstrapping.py:49-155``).

Sampling runs on the host RNG (numpy) — resample indices are data-independent,
so only the gather itself touches the device.
"""
from copy import deepcopy
from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import apply_to_collection

Array = jax.Array


def _bootstrap_sampler(size: int, sampling_strategy: str = "poisson") -> np.ndarray:
    """Resample-with-replacement indices (reference ``bootstrapping.py:26-46``)."""
    if sampling_strategy == "poisson":
        n = np.random.poisson(1.0, size)
        return np.repeat(np.arange(size), n)
    if sampling_strategy == "multinomial":
        return np.random.randint(0, size, size)
    raise ValueError("Unknown sampling strategy")


class BootStrapper(Metric):
    """Confidence intervals via bootstrapped metric copies
    (reference ``bootstrapping.py:49-155``).

    Example:
        >>> import numpy as np
        >>> from metrics_tpu import Accuracy, BootStrapper
        >>> np.random.seed(123)
        >>> bootstrap = BootStrapper(Accuracy(), num_bootstraps=20)
        >>> bootstrap.update(np.random.randint(0, 5, 20), np.random.randint(0, 5, 20))
        >>> sorted(bootstrap.compute())
        ['mean', 'std']
    """

    jittable_update = False
    jittable_compute = False

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Array]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of metrics_tpu.Metric but received {base_metric}"
            )
        self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]
        self.num_bootstraps = num_bootstraps
        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw

        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling}"
                f" but received {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Resample every input along dim 0 per copy (reference ``:120-137``)."""
        args_sizes = apply_to_collection(args, (jax.Array, np.ndarray), len)
        kwargs_sizes = list(apply_to_collection(kwargs, (jax.Array, np.ndarray), len).values())
        if len(args_sizes) > 0:
            size = args_sizes[0]
        elif len(kwargs_sizes) > 0:
            size = kwargs_sizes[0]
        else:
            raise ValueError("None of the input contained tensors, so could not determine the sampling size")
        for idx in range(self.num_bootstraps):
            sample_idx = _bootstrap_sampler(size, sampling_strategy=self.sampling_strategy)
            new_args = apply_to_collection(args, (jax.Array, np.ndarray), lambda x: jnp.asarray(x)[sample_idx])
            new_kwargs = apply_to_collection(kwargs, (jax.Array, np.ndarray), lambda x: jnp.asarray(x)[sample_idx])
            self.metrics[idx].update(*new_args, **new_kwargs)

    def compute(self) -> Dict[str, Array]:
        """Reference ``bootstrapping.py:139-155``."""
        computed_vals = jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], axis=0)
        output_dict = {}
        if self.mean:
            output_dict["mean"] = computed_vals.mean(axis=0)
        if self.std:
            output_dict["std"] = computed_vals.std(axis=0, ddof=1)
        if self.quantile is not None:
            output_dict["quantile"] = jnp.quantile(computed_vals, self.quantile)
        if self.raw:
            output_dict["raw"] = computed_vals
        return output_dict

    def reset(self) -> None:
        for m in self.metrics:
            m.reset()
        super().reset()

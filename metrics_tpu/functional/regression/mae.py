"""MAE kernel (reference ``src/torchmetrics/functional/regression/mae.py``)."""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.compute import _to_float

Array = jax.Array


def _mean_absolute_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    """Reference ``mae.py:22-35``."""
    preds = _to_float(preds)
    target = _to_float(target)
    _check_same_shape(preds, target)
    sum_abs_error = jnp.sum(jnp.abs(preds - target))
    n_obs = target.size
    return sum_abs_error, n_obs


def _mean_absolute_error_compute(sum_abs_error: Array, n_obs: Array) -> Array:
    """Reference ``mae.py:38-52``."""
    return sum_abs_error / n_obs


def mean_absolute_error(preds: Array, target: Array) -> Array:
    """Mean absolute error (reference ``mae.py:55-75``).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([0., 1, 2, 3])
        >>> y = jnp.array([0., 1, 2, 1])
        >>> mean_absolute_error(x, y)
        Array(0.5, dtype=float32)
    """
    sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
    return _mean_absolute_error_compute(sum_abs_error, n_obs)

"""WMAPE kernel (reference ``src/torchmetrics/functional/regression/wmape.py``)."""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _weighted_mean_absolute_percentage_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``wmape.py:22-37``."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    sum_abs_error = jnp.sum(jnp.abs((preds - target).reshape(-1)))
    sum_scale = jnp.sum(jnp.abs(target.reshape(-1)))
    return sum_abs_error, sum_scale


def _weighted_mean_absolute_percentage_error_compute(
    sum_abs_error: Array, sum_scale: Array, epsilon: float = 1.17e-06
) -> Array:
    """Reference ``wmape.py:40-52``."""
    return sum_abs_error / jnp.clip(sum_scale, epsilon, None)


def weighted_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """WMAPE (reference ``wmape.py:55-85``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1., 10, 1e6])
        >>> preds = jnp.array([0.9, 15, 1.2e6])
        >>> print(f"{weighted_mean_absolute_percentage_error(preds, target):.4f}")
        0.2000
    """
    sum_abs_error, sum_scale = _weighted_mean_absolute_percentage_error_update(preds, target)
    return _weighted_mean_absolute_percentage_error_compute(sum_abs_error, sum_scale)

"""Cosine similarity kernels (reference
``src/torchmetrics/functional/regression/cosine_similarity.py``).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.compute import _to_float

Array = jax.Array


def _cosine_similarity_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``cosine_similarity.py:22-37``."""
    preds = _to_float(preds)
    target = _to_float(target)
    _check_same_shape(preds, target)
    return preds, target


def _cosine_similarity_compute(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    """Reference ``cosine_similarity.py:40-66``."""
    dot_product = (preds * target).sum(axis=-1)
    preds_norm = jnp.linalg.norm(preds, axis=-1)
    target_norm = jnp.linalg.norm(target, axis=-1)
    similarity = dot_product / (preds_norm * target_norm)
    reduction_mapping = {
        "sum": jnp.sum,
        "mean": jnp.mean,
        "none": lambda x: x,
        None: lambda x: x,
    }
    return reduction_mapping[reduction](similarity)


def cosine_similarity(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    """Cosine similarity (reference ``cosine_similarity.py:69-103``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([[0., 1], [1, 1]])
        >>> preds = jnp.array([[0., 1], [0, 1]])
        >>> print(f"{cosine_similarity(preds, target, 'mean'):.4f}")
        0.8536
    """
    preds, target = _cosine_similarity_update(preds, target)
    return _cosine_similarity_compute(preds, target, reduction)

"""MSE kernel (reference ``src/torchmetrics/functional/regression/mse.py``)."""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _mean_squared_error_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, int]:
    """Reference ``mse.py:22-40``."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    if num_outputs == 1:
        preds = preds.reshape(-1)
        target = target.reshape(-1)
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=0)
    n_obs = target.shape[0]
    return sum_squared_error, n_obs


def _mean_squared_error_compute(sum_squared_error: Array, n_obs: Array, squared: bool = True) -> Array:
    """Reference ``mse.py:43-60``."""
    mse = sum_squared_error / n_obs
    return mse if squared else jnp.sqrt(mse)


def mean_squared_error(preds: Array, target: Array, squared: bool = True, num_outputs: int = 1) -> Array:
    """Mean squared error (reference ``mse.py:63-90``).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([0., 1, 2, 3])
        >>> y = jnp.array([0., 1, 2, 2])
        >>> mean_squared_error(x, y)
        Array(0.25, dtype=float32)
    """
    sum_squared_error, n_obs = _mean_squared_error_update(preds, target, num_outputs)
    return _mean_squared_error_compute(sum_squared_error, n_obs, squared=squared)

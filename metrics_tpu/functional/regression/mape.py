"""MAPE kernel (reference ``src/torchmetrics/functional/regression/mape.py``)."""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = 1.17e-06
) -> Tuple[Array, int]:
    """Reference ``mape.py:22-43``."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    abs_per_error = jnp.abs(preds - target) / jnp.clip(jnp.abs(target), epsilon, None)
    sum_abs_per_error = jnp.sum(abs_per_error)
    return sum_abs_per_error, target.size


def _mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs: Array) -> Array:
    """Reference ``mape.py:46-61``."""
    return sum_abs_per_error / num_obs


def mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """MAPE (reference ``mape.py:64-94``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1., 10, 1e6])
        >>> preds = jnp.array([0.9, 15, 1.2e6])
        >>> mean_absolute_percentage_error(preds, target).round(4)
        Array(0.2667, dtype=float32)
    """
    sum_abs_per_error, num_obs = _mean_absolute_percentage_error_update(preds, target)
    return _mean_absolute_percentage_error_compute(sum_abs_per_error, num_obs)

"""Tweedie deviance kernels (reference
``src/torchmetrics/functional/regression/tweedie_deviance.py``, 140 LoC).

Value-domain validation (strictly-positive preds/targets per power) is
data-dependent; it runs only on concrete arrays — inside jit the math
proceeds unchecked, matching the static-shape contract.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape, _is_concrete
from metrics_tpu.utilities.compute import _safe_xlogy

Array = jax.Array


def _tweedie_deviance_score_update(preds: Array, targets: Array, power: float = 0.0) -> Tuple[Array, Array]:
    """Reference ``tweedie_deviance.py:24-85``."""
    preds = jnp.asarray(preds)
    targets = jnp.asarray(targets)
    _check_same_shape(preds, targets)

    if 0 < power < 1:
        raise ValueError(f"Deviance Score is not defined for power={power}.")

    concrete = _is_concrete(preds, targets)
    if power == 0:
        deviance_score = (targets - preds) ** 2
    elif power == 1:
        if concrete and (bool((preds <= 0).any()) or bool((targets < 0).any())):
            raise ValueError(
                f"For power={power}, 'preds' has to be strictly positive and 'targets' cannot be negative."
            )
        deviance_score = 2 * (_safe_xlogy(targets, targets / preds) + preds - targets)
    elif power == 2:
        if concrete and (bool((preds <= 0).any()) or bool((targets <= 0).any())):
            raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")
        deviance_score = 2 * (jnp.log(preds / targets) + (targets / preds) - 1)
    else:
        if concrete:
            if power < 0 and bool((preds <= 0).any()):
                raise ValueError(f"For power={power}, 'preds' has to be strictly positive.")
            if 1 < power < 2 and (bool((preds <= 0).any()) or bool((targets < 0).any())):
                raise ValueError(
                    f"For power={power}, 'targets' has to be strictly positive and 'preds' cannot be negative."
                )
            if power > 2 and (bool((preds <= 0).any()) or bool((targets <= 0).any())):
                raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")

        term_1 = jnp.power(jnp.clip(targets, 0, None), 2 - power) / ((1 - power) * (2 - power))
        term_2 = targets * jnp.power(preds, 1 - power) / (1 - power)
        term_3 = jnp.power(preds, 2 - power) / (2 - power)
        deviance_score = 2 * (term_1 - term_2 + term_3)

    sum_deviance_score = jnp.sum(deviance_score)
    num_observations = jnp.asarray(deviance_score.size)
    return sum_deviance_score, num_observations


def _tweedie_deviance_score_compute(sum_deviance_score: Array, num_observations: Array) -> Array:
    """Reference ``tweedie_deviance.py:88-103``."""
    return sum_deviance_score / num_observations


def tweedie_deviance_score(preds: Array, targets: Array, power: float = 0.0) -> Array:
    """Tweedie deviance score (reference ``tweedie_deviance.py:106-140``).

    Example:
        >>> import jax.numpy as jnp
        >>> targets = jnp.array([1.0, 2.0, 3.0, 4.0])
        >>> preds = jnp.array([4.0, 3.0, 2.0, 1.0])
        >>> tweedie_deviance_score(preds, targets, power=2).round(4)
        Array(1.2083, dtype=float32)
    """
    sum_deviance_score, num_observations = _tweedie_deviance_score_update(preds, targets, power)
    return _tweedie_deviance_score_compute(sum_deviance_score, num_observations)

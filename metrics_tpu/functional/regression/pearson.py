"""Pearson correlation kernels (reference
``src/torchmetrics/functional/regression/pearson.py``, 103 LoC).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _pearson_corrcoef_update(
    preds: Array,
    target: Array,
    mean_x: Array,
    mean_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    n_prior: Array,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Streaming-moment update (reference ``pearson.py:20-60``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    preds = preds.squeeze()
    target = target.squeeze()
    if preds.ndim > 1 or target.ndim > 1:
        raise ValueError("Expected both predictions and target to be 1 dimensional tensors.")

    n_obs = preds.size
    mx_new = (n_prior * mean_x + preds.mean() * n_obs) / (n_prior + n_obs)
    my_new = (n_prior * mean_y + target.mean() * n_obs) / (n_prior + n_obs)
    n_prior = n_prior + n_obs
    var_x = var_x + ((preds - mx_new) * (preds - mean_x)).sum()
    var_y = var_y + ((target - my_new) * (target - mean_y)).sum()
    corr_xy = corr_xy + ((preds - mx_new) * (target - mean_y)).sum()

    return mx_new, my_new, var_x, var_y, corr_xy, n_prior


def _pearson_corrcoef_compute(var_x: Array, var_y: Array, corr_xy: Array, nb: Array) -> Array:
    """Reference ``pearson.py:63-81``."""
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    corr_xy = corr_xy / (nb - 1)
    corrcoef = (corr_xy / jnp.sqrt(var_x * var_y)).squeeze()
    return jnp.clip(corrcoef, -1.0, 1.0)


def pearson_corrcoef(preds: Array, target: Array) -> Array:
    """Pearson correlation coefficient (reference ``pearson.py:84-103``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3., -0.5, 2, 7])
        >>> preds = jnp.array([2.5, 0.0, 2, 8])
        >>> pearson_corrcoef(preds, target).round(4)
        Array(0.9849, dtype=float32)
    """
    zero = jnp.zeros((), jnp.result_type(preds, jnp.float32))
    _, _, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, zero, zero, zero, zero, zero, zero
    )
    return _pearson_corrcoef_compute(var_x, var_y, corr_xy, nb)

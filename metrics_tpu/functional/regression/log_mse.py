"""MSLE kernel (reference ``src/torchmetrics/functional/regression/log_mse.py``)."""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _mean_squared_log_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    """Reference ``log_mse.py:22-36``."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    sum_squared_log_error = jnp.sum((jnp.log1p(preds) - jnp.log1p(target)) ** 2)
    return sum_squared_log_error, target.size


def _mean_squared_log_error_compute(sum_squared_log_error: Array, n_obs: Array) -> Array:
    """Reference ``log_mse.py:39-53``."""
    return sum_squared_log_error / n_obs


def mean_squared_log_error(preds: Array, target: Array) -> Array:
    """Mean squared log error (reference ``log_mse.py:56-79``).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([0., 1, 2, 3])
        >>> y = jnp.array([0., 1, 2, 2])
        >>> mean_squared_log_error(x, y).round(4)
        Array(0.0207, dtype=float32)
    """
    sum_squared_log_error, n_obs = _mean_squared_log_error_update(preds, target)
    return _mean_squared_log_error_compute(sum_squared_log_error, n_obs)

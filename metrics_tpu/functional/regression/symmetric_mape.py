"""SMAPE kernel (reference
``src/torchmetrics/functional/regression/symmetric_mape.py``)."""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _symmetric_mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = 1.17e-06
) -> Tuple[Array, int]:
    """Reference ``symmetric_mape.py:22-44``."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    abs_per_error = 2 * jnp.abs(preds - target) / jnp.clip(jnp.abs(target) + jnp.abs(preds), epsilon, None)
    sum_abs_per_error = jnp.sum(abs_per_error)
    return sum_abs_per_error, target.size


def _symmetric_mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs: Array) -> Array:
    """Reference ``symmetric_mape.py:47-62``."""
    return sum_abs_per_error / num_obs


def symmetric_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """SMAPE (reference ``symmetric_mape.py:65-92``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1., 10, 1e6])
        >>> preds = jnp.array([0.9, 15, 1.2e6])
        >>> print(f"{symmetric_mean_absolute_percentage_error(preds, target):.4f}")
        0.2290
    """
    sum_abs_per_error, num_obs = _symmetric_mean_absolute_percentage_error_update(preds, target)
    return _symmetric_mean_absolute_percentage_error_compute(sum_abs_per_error, num_obs)

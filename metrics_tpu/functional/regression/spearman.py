"""Spearman correlation kernels (reference
``src/torchmetrics/functional/regression/spearman.py``, 131 LoC).

TPU-first: the reference ranks with a Python loop over repeated values
(``spearman.py:35-52``); here mean-rank-of-ties is computed in one shot as
``rank_i = (#{x_j < x_i} + #{x_j <= x_i} + 1) / 2`` via sort + binary search —
static shapes, fully jittable, O(N log N).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _rank_data(data: Array, mask: Optional[Array] = None) -> Array:
    """1-based ranks with ties assigned the mean of their rank span
    (reference ``spearman.py:35-52``): ``rank_i = (#{< x_i} + 1 + #{<= x_i})/2``
    via sort + two binary searches — O(N log N), no N x N broadcast.

    With ``mask``, only True rows participate (the static-shape ring-buffer
    form): invalid rows sort to +inf, and the ``<=`` count is capped at the
    valid count so legitimate ``+inf`` data values don't absorb the
    sentinel ties. Rank values at invalid rows are meaningless and must be
    masked out by the caller.
    """
    data = jnp.asarray(data)
    if mask is None:
        sorted_data = jnp.sort(data)
        lt = jnp.searchsorted(sorted_data, data, side="left")
        le = jnp.searchsorted(sorted_data, data, side="right")
    else:
        sorted_data = jnp.sort(jnp.where(mask, data, jnp.inf))
        lt = jnp.searchsorted(sorted_data, data, side="left")
        le = jnp.minimum(
            jnp.searchsorted(sorted_data, data, side="right"), mask.sum().astype(jnp.int32)
        )
    return (lt + 1 + le).astype(jnp.result_type(data, jnp.float32)) / 2.0


def _spearman_corrcoef_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``spearman.py:55-76``."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_same_shape(preds, target)
    preds = preds.squeeze()
    target = target.squeeze()
    if preds.ndim > 1 or target.ndim > 1:
        raise ValueError("Expected both predictions and target to be 1 dimensional tensors.")
    return preds, target


def _spearman_masked(preds: Array, target: Array, mask: Array, eps: float = 1e-6) -> Array:
    """Spearman correlation of the masked rows of a :class:`CatBuffer` pair —
    the static-shape, jittable form of :func:`_spearman_corrcoef_compute`.

    An empty buffer (no valid rows) yields NaN: under jit nothing can raise
    on a traced count, so the undefined case is made explicit instead of
    leaking through a 0/0 chain.
    """
    return _spearman_corrcoef_compute(
        jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32), eps, mask=jnp.asarray(mask, bool)
    )


def _spearman_corrcoef_compute(
    preds: Array, target: Array, eps: float = 1e-6, mask: Optional[Array] = None
) -> Array:
    """Reference ``spearman.py:79-105``; one weighted implementation serves
    both the eager path (``mask=None`` — unit weights) and the ring-buffer
    path, so tie policy / eps / clip can never drift between the modes."""
    rp = _rank_data(preds, mask)
    rt = _rank_data(target, mask)
    w = jnp.ones_like(rp) if mask is None else mask.astype(rp.dtype)
    n = w.sum()
    n_safe = jnp.maximum(n, 1.0)

    mean_p = (rp * w).sum() / n_safe
    mean_t = (rt * w).sum() / n_safe
    dp = (rp - mean_p) * w
    dt = (rt - mean_t) * w

    cov = (dp * dt).sum() / n_safe
    std_p = jnp.sqrt((dp * dp).sum() / n_safe)
    std_t = jnp.sqrt((dt * dt).sum() / n_safe)

    corrcoef = jnp.clip(cov / (std_p * std_t + eps), -1.0, 1.0)
    if mask is None:
        return corrcoef
    return jnp.where(n > 0, corrcoef, jnp.nan)


def spearman_corrcoef(preds: Array, target: Array) -> Array:
    """Spearman rank correlation (reference ``spearman.py:108-131``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3., -0.5, 2, 7])
        >>> preds = jnp.array([2.5, 0.0, 2, 8])
        >>> print(f"{spearman_corrcoef(preds, target):.4f}")
        1.0000
    """
    preds, target = _spearman_corrcoef_update(preds, target)
    return _spearman_corrcoef_compute(preds, target)

"""Spearman correlation kernels (reference
``src/torchmetrics/functional/regression/spearman.py``, 131 LoC).

TPU-first: the reference ranks with a Python loop over repeated values
(``spearman.py:35-52``); here mean-rank-of-ties is computed in one shot as
``rank_i = (#{x_j < x_i} + #{x_j <= x_i} + 1) / 2`` via sort + binary search —
static shapes, fully jittable, O(N log N).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _rank_data(data: Array) -> Array:
    """1-based ranks with ties assigned the mean of their rank span
    (reference ``spearman.py:35-52``): ``rank_i = (#{< x_i} + 1 + #{<= x_i})/2``
    via sort + two binary searches — O(N log N), no N x N broadcast."""
    data = jnp.asarray(data)
    sorted_data = jnp.sort(data)
    lt = jnp.searchsorted(sorted_data, data, side="left")
    le = jnp.searchsorted(sorted_data, data, side="right")
    return (lt + 1 + le).astype(jnp.result_type(data, jnp.float32)) / 2.0


def _spearman_corrcoef_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``spearman.py:55-76``."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_same_shape(preds, target)
    preds = preds.squeeze()
    target = target.squeeze()
    if preds.ndim > 1 or target.ndim > 1:
        raise ValueError("Expected both predictions and target to be 1 dimensional tensors.")
    return preds, target


def _spearman_corrcoef_compute(preds: Array, target: Array, eps: float = 1e-6) -> Array:
    """Reference ``spearman.py:79-105``."""
    preds = _rank_data(preds)
    target = _rank_data(target)

    preds_diff = preds - preds.mean()
    target_diff = target - target.mean()

    cov = (preds_diff * target_diff).mean()
    preds_std = jnp.sqrt((preds_diff * preds_diff).mean())
    target_std = jnp.sqrt((target_diff * target_diff).mean())

    corrcoef = cov / (preds_std * target_std + eps)
    return jnp.clip(corrcoef, -1.0, 1.0)


def spearman_corrcoef(preds: Array, target: Array) -> Array:
    """Spearman rank correlation (reference ``spearman.py:108-131``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3., -0.5, 2, 7])
        >>> preds = jnp.array([2.5, 0.0, 2, 8])
        >>> print(f"{spearman_corrcoef(preds, target):.4f}")
        1.0000
    """
    preds, target = _spearman_corrcoef_update(preds, target)
    return _spearman_corrcoef_compute(preds, target)

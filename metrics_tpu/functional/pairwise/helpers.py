"""Pairwise helpers (reference
``src/torchmetrics/functional/pairwise/helpers.py``)."""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _check_input(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Tuple[Array, Array, bool]:
    """Reference ``pairwise/helpers.py:20-43``."""
    x = jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"Expected argument `x` to be a 2D tensor of shape `[N, d]` but got {x.shape}")

    if y is not None:
        y = jnp.asarray(y)
        if y.ndim != 2 or y.shape[1] != x.shape[1]:
            raise ValueError(
                "Expected argument `y` to be a 2D tensor of shape `[M, d]` where"
                " `d` should be same as the last dimension of `x`"
            )
        zero_diagonal = False if zero_diagonal is None else zero_diagonal
    else:
        y = x
        zero_diagonal = True if zero_diagonal is None else zero_diagonal
    return x, y, zero_diagonal


def _reduce_distance_matrix(distmat: Array, reduction: Optional[str] = None) -> Array:
    """Reference ``pairwise/helpers.py:46-60``."""
    if reduction == "mean":
        return distmat.mean(axis=-1)
    if reduction == "sum":
        return distmat.sum(axis=-1)
    if reduction in (None, "none"):
        return distmat
    raise ValueError(f"Expected reduction to be one of `['mean', 'sum', None]` but got {reduction}")

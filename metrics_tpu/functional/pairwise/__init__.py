"""Pairwise functional metrics (reference
``src/torchmetrics/functional/pairwise/__init__.py``).

All four distances are single MXU matmuls plus elementwise math — the
TPU-optimal formulation (the manhattan distance is the only O(N*M*d)
broadcast).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix
from metrics_tpu.utilities.compute import _safe_matmul

Array = jax.Array


def pairwise_cosine_similarity(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise cosine similarity (reference ``pairwise/cosine.py``).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([[2., 3], [3, 5], [5, 8]])
        >>> y = jnp.array([[1., 0], [2, 1]])
        >>> pairwise_cosine_similarity(x, y).round(4)
        Array([[0.5547, 0.8682],
               [0.5145, 0.8437],
               [0.5301, 0.8533]], dtype=float32)
    """
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    norm_x = x / jnp.linalg.norm(x, ord=2, axis=1, keepdims=True)
    norm_y = y / jnp.linalg.norm(y, ord=2, axis=1, keepdims=True)
    distance = _safe_matmul(norm_x, norm_y.T)
    if zero_diagonal:
        distance = distance * (1 - jnp.eye(distance.shape[0], distance.shape[1], dtype=distance.dtype))
    return _reduce_distance_matrix(distance, reduction)


def pairwise_euclidean_distance(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise euclidean distance (reference ``pairwise/euclidean.py``).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([[2., 3], [3, 5], [5, 8]])
        >>> y = jnp.array([[1., 0], [2, 1]])
        >>> pairwise_euclidean_distance(x, y).round(4)
        Array([[3.1623, 2.    ],
               [5.3852, 4.1231],
               [8.9443, 7.6158]], dtype=float32)
    """
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x_norm = (x * x).sum(axis=1, keepdims=True)
    y_norm = (y * y).sum(axis=1)
    distance = x_norm + y_norm - 2 * _safe_matmul(x, y.T)
    if zero_diagonal:
        distance = distance * (1 - jnp.eye(distance.shape[0], distance.shape[1], dtype=distance.dtype))
    return _reduce_distance_matrix(jnp.sqrt(jnp.clip(distance, 0, None)), reduction)


def pairwise_linear_similarity(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise linear similarity ``x @ y^T`` (reference ``pairwise/linear.py``).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([[2., 3], [3, 5], [5, 8]])
        >>> y = jnp.array([[1., 0], [2, 1]])
        >>> pairwise_linear_similarity(x, y)
        Array([[ 2.,  7.],
               [ 3., 11.],
               [ 5., 18.]], dtype=float32)
    """
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = _safe_matmul(x, y.T)
    if zero_diagonal:
        distance = distance * (1 - jnp.eye(distance.shape[0], distance.shape[1], dtype=distance.dtype))
    return _reduce_distance_matrix(distance, reduction)


def pairwise_manhattan_distance(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise manhattan distance (reference ``pairwise/manhattan.py``).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([[2., 3], [3, 5], [5, 8]])
        >>> y = jnp.array([[1., 0], [2, 1]])
        >>> pairwise_manhattan_distance(x, y)
        Array([[ 4.,  2.],
               [ 7.,  5.],
               [12., 10.]], dtype=float32)
    """
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = jnp.abs(x[:, None] - y[None, :]).sum(axis=-1)
    if zero_diagonal:
        distance = distance * (1 - jnp.eye(distance.shape[0], distance.shape[1], dtype=distance.dtype))
    return _reduce_distance_matrix(distance, reduction)

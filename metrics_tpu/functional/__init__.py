"""Functional metrics API (reference
``src/torchmetrics/functional/__init__.py``)."""
from metrics_tpu.functional.classification import (  # noqa: F401
    accuracy,
    cohen_kappa,
    confusion_matrix,
    dice,
    f1_score,
    fbeta_score,
    hamming_distance,
    jaccard_index,
    matthews_corrcoef,
    precision,
    precision_recall,
    recall,
    specificity,
    stat_scores,
)

"""Image kernel helpers (reference
``src/torchmetrics/functional/image/helper.py``, 122 LoC).

Depthwise gaussian/uniform filtering is expressed as
``lax.conv_general_dilated`` with ``feature_group_count=C`` — a native MXU
convolution on TPU.
"""
from typing import Sequence

import jax
import jax.numpy as jnp

Array = jax.Array


def _gaussian(kernel_size: int, sigma: float, dtype) -> Array:
    """1-d gaussian window (reference ``helper.py:11-27``)."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1.0, dtype=dtype)
    gauss = jnp.exp(-((dist / sigma) ** 2) / 2)
    return (gauss / gauss.sum())[None, :]  # (1, kernel_size)


def _gaussian_kernel_2d(channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype) -> Array:
    """Depthwise 2-d gaussian kernel ``(C, 1, kh, kw)`` (reference ``helper.py:30-60``)."""
    kernel_x = _gaussian(kernel_size[0], sigma[0], dtype)
    kernel_y = _gaussian(kernel_size[1], sigma[1], dtype)
    kernel = kernel_x.T @ kernel_y  # (kh, kw)
    return jnp.broadcast_to(kernel, (channel, 1, kernel_size[0], kernel_size[1]))


def _gaussian_kernel_3d(channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype) -> Array:
    """Depthwise 3-d gaussian kernel (reference ``helper.py:63-83``)."""
    kernel_x = _gaussian(kernel_size[0], sigma[0], dtype)
    kernel_y = _gaussian(kernel_size[1], sigma[1], dtype)
    kernel_z = _gaussian(kernel_size[2], sigma[2], dtype)
    kernel_xy = kernel_x.T @ kernel_y  # (kh, kw)
    kernel = kernel_xy[:, :, None] * kernel_z.reshape(1, 1, -1)
    return jnp.broadcast_to(kernel, (channel, 1, *kernel.shape))


def _uniform_kernel(channel: int, kernel_size: Sequence[int], dtype) -> Array:
    """Depthwise uniform (box) kernel."""
    kernel = jnp.ones(tuple(kernel_size), dtype) / jnp.prod(jnp.asarray(kernel_size, dtype))
    return jnp.broadcast_to(kernel, (channel, 1, *kernel_size))


def _depthwise_conv(x: Array, kernel: Array) -> Array:
    """Valid-mode depthwise convolution over NCHW / NCDHW inputs.

    Runs at ``Precision.HIGHEST``: quality metrics (SSIM/UQI) are reported to
    ~4 decimal places, and the TPU default bf16 conv accumulation introduces
    ~1e-3 error in the filtered moments — visible in the final score.
    """
    channel = x.shape[1]
    spatial = x.ndim - 2
    dn = ("NCHW", "OIHW", "NCHW") if spatial == 2 else ("NCDHW", "OIDHW", "NCDHW")
    return jax.lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1,) * spatial,
        padding="VALID",
        dimension_numbers=dn,
        feature_group_count=channel,
        precision=jax.lax.Precision.HIGHEST,
    )


def _reflect_pad(x: Array, pads: Sequence[int]) -> Array:
    """Reflect-pad the trailing spatial dims of an NC... tensor."""
    pad_width = [(0, 0), (0, 0)] + [(p, p) for p in pads]
    return jnp.pad(x, pad_width, mode="reflect")

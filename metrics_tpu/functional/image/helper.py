"""Image kernel helpers (reference
``src/torchmetrics/functional/image/helper.py``, 122 LoC).

Depthwise gaussian/uniform filtering runs as separable per-dimension
passes, each a banded-matrix matmul on the MXU (see
``_depthwise_conv_separable``).
"""
from typing import Sequence

import jax
import jax.numpy as jnp

Array = jax.Array


def _gaussian(kernel_size: int, sigma: float, dtype) -> Array:
    """1-d gaussian window (reference ``helper.py:11-27``)."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1.0, dtype=dtype)
    gauss = jnp.exp(-((dist / sigma) ** 2) / 2)
    return (gauss / gauss.sum())[None, :]  # (1, kernel_size)


def _separable_factors(
    kernel_size: Sequence[int], sigma: Sequence[float], gaussian: bool, dtype
) -> Sequence[Array]:
    """Per-dimension 1-d filter factors for the (always separable) SSIM/UQI
    windows: gaussian = outer product of 1-d gaussians, uniform box = outer
    product of 1-d boxes."""
    if gaussian:
        return [_gaussian(k, s, dtype)[0] for k, s in zip(kernel_size, sigma)]
    return [jnp.ones((k,), dtype) / k for k in kernel_size]


def _banded_filter_matrix(f: Array, size_in: int) -> Array:
    """``(size_in, size_in - k + 1)`` banded matrix ``B[i, j] = f[i - j]``.

    Right-multiplying a row of length ``size_in`` by ``B`` equals the
    valid-mode correlation of the row with ``f`` — the 1-d filter becomes a
    dense matmul.
    """
    k = f.shape[-1]
    size_out = size_in - k + 1
    i = jnp.arange(size_in)[:, None]
    j = jnp.arange(size_out)[None, :]
    d = i - j
    return jnp.where((d >= 0) & (d < k), jnp.take(f, jnp.clip(d, 0, k - 1)), 0.0).astype(f.dtype)


# past this spatial size the banded matmul's (size x size) extra FLOPs
# outweigh the MXU advantage over the k-tap conv
_BANDED_MAX_SIZE = 2048


def _depthwise_conv_separable(x: Array, factors: Sequence[Array]) -> Array:
    """Valid-mode depthwise filtering, one 1-d pass per spatial dim.

    An 11x11 window as a full 2-d depthwise conv costs 121 taps/pixel and
    lowers badly on TPU (grouped convolutions bypass the MXU). The window is
    always an outer product here, so each dim is filtered independently —
    and each 1-d pass is expressed as a dense **banded-matrix matmul** over
    that axis, which XLA maps straight onto the MXU. For spatial sizes past
    ``_BANDED_MAX_SIZE`` the O(size^2) matmul loses to the k-tap conv and
    the pass falls back to ``conv_general_dilated``. Everything runs at
    ``Precision.HIGHEST``: quality metrics (SSIM/UQI) are reported to ~4
    decimal places and the TPU default bf16 accumulation introduces ~1e-3
    error in the filtered moments — visible in the final score.
    """
    channel = x.shape[1]
    spatial = x.ndim - 2
    dn = ("NCHW", "OIHW", "NCHW") if spatial == 2 else ("NCDHW", "OIDHW", "NCDHW")
    out = x
    for dim, f in enumerate(factors):
        axis = 2 + dim
        size_in = out.shape[axis]
        if size_in <= _BANDED_MAX_SIZE:
            band = _banded_filter_matrix(f, size_in)
            moved = jnp.moveaxis(out, axis, -1)
            filtered = jnp.matmul(moved, band, precision=jax.lax.Precision.HIGHEST)
            out = jnp.moveaxis(filtered, -1, axis)
        else:
            kshape = [1] * spatial
            kshape[dim] = f.shape[-1]
            kernel = jnp.broadcast_to(f.reshape(kshape), (channel, 1, *kshape))
            out = jax.lax.conv_general_dilated(
                out,
                kernel,
                window_strides=(1,) * spatial,
                padding="VALID",
                dimension_numbers=dn,
                feature_group_count=channel,
                precision=jax.lax.Precision.HIGHEST,
            )
    return out


def _reflect_pad(x: Array, pads: Sequence[int]) -> Array:
    """Reflect-pad the trailing spatial dims of an NC... tensor."""
    pad_width = [(0, 0), (0, 0)] + [(p, p) for p in pads]
    return jnp.pad(x, pad_width, mode="reflect")

"""Spectral Distortion Index kernel (reference
``src/torchmetrics/functional/image/d_lambda.py``, 132 LoC).

TPU-first: the reference's O(C^2) Python double loop over band pairs
(``d_lambda.py:55-60``) is replaced by ONE batched UQI evaluation over all
C*C band pairs stacked into the batch axis.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.uqi import _uqi_compute
from metrics_tpu.parallel.sync import reduce
from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _spectral_distortion_index_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``d_lambda.py:24-42``."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype != target.dtype:
        raise TypeError(
            f"Expected `ms` and `fused` to have the same data type. Got ms: {preds.dtype} and fused: {target.dtype}."
        )
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(
            f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


def _band_pair_uqi_matrix(x: Array) -> Array:
    """(C, C) matrix of UQI between every pair of bands of ``x`` — all pairs
    evaluated in one conv by stacking them into the batch axis."""
    b, c, h, w = x.shape
    k = x[:, :, None]  # (B, C, 1, H, W)
    r = x[:, None, :]  # (B, 1, C, H, W)
    pairs_k = jnp.broadcast_to(k, (b, c, c, h, w)).reshape(b * c * c, 1, h, w)
    pairs_r = jnp.broadcast_to(r, (b, c, c, h, w)).reshape(b * c * c, 1, h, w)
    # per-pair UQI, averaged over the batch like the reference's per-pair call
    vals = _uqi_compute(pairs_k, pairs_r, reduction="none")  # (B*C*C, 1, h', w')
    vals = vals.reshape(b, c, c, -1).mean(axis=(0, 3))
    return vals


def _spectral_distortion_index_compute(
    preds: Array,
    target: Array,
    p: int = 1,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """Reference ``d_lambda.py:45-70``."""
    length = preds.shape[1]
    m1 = _band_pair_uqi_matrix(target)
    m2 = _band_pair_uqi_matrix(preds)

    diff = jnp.abs(m1 - m2) ** p
    if length == 1:
        output = diff ** (1.0 / p)
    else:
        output = (1.0 / (length * (length - 1)) * jnp.sum(diff)) ** (1.0 / p)
    return reduce(output, reduction)


def spectral_distortion_index(
    preds: Array,
    target: Array,
    p: int = 1,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """D-lambda (reference ``d_lambda.py:73-132``).

    Example:
        >>> import jax
        >>> preds = jax.random.uniform(jax.random.PRNGKey(42), (2, 3, 16, 16))
        >>> target = preds * 0.9
        >>> float(spectral_distortion_index(preds, target)) < 0.1
        True
    """
    if not isinstance(p, int) or p <= 0:
        raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
    preds, target = _spectral_distortion_index_update(preds, target)
    return _spectral_distortion_index_compute(preds, target, p, reduction)

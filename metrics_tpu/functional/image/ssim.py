"""SSIM / Multi-Scale SSIM kernels (reference
``src/torchmetrics/functional/image/ssim.py``, 487 LoC).

TPU-first: the five filtered moments (mu_p, mu_t, E[p^2], E[t^2], E[pt]) are
computed with ONE depthwise convolution over a 5B-stacked batch (the
reference does the same stacking, ``ssim.py:148-153``) — a single MXU conv
per SSIM evaluation; reflect-pad + valid conv keeps parity with the
reference's padding scheme.
"""
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.helper import (
    _depthwise_conv_separable,
    _reflect_pad,
    _separable_factors,
)
from metrics_tpu.parallel.sync import reduce
from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _ssim_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``ssim.py:13-34``."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_same_shape(preds, target)
    if preds.ndim not in (4, 5):
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW or BxCxDxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


def _ssim_compute(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """Reference ``ssim.py:37-185``."""
    is_3d = preds.ndim == 5
    spatial = 3 if is_3d else 2

    if not isinstance(kernel_size, Sequence):
        kernel_size = spatial * [kernel_size]
    if not isinstance(sigma, Sequence):
        sigma = spatial * [sigma]

    if len(kernel_size) != spatial or len(sigma) != spatial:
        raise ValueError(
            f"`kernel_size` has dimension {len(kernel_size)}, but expected to be two less than target dimensionality,"
            f" which is: {preds.ndim}"
        )
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")

    if data_range is None:
        data_range = jnp.maximum(preds.max() - preds.min(), target.max() - target.min())

    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2

    channel = preds.shape[1]
    dtype = preds.dtype if jnp.issubdtype(preds.dtype, jnp.floating) else jnp.float32
    preds = preds.astype(dtype)
    target = target.astype(dtype)
    gauss_kernel_size = [int(3.5 * s + 0.5) * 2 + 1 for s in sigma]

    if gaussian_kernel:
        pads = [(gs - 1) // 2 for gs in gauss_kernel_size]
        factors = _separable_factors(gauss_kernel_size, sigma, True, dtype)
    else:
        pads = [(ks - 1) // 2 for ks in kernel_size]
        factors = _separable_factors(kernel_size, sigma, False, dtype)

    preds_p = _reflect_pad(preds, pads)
    target_p = _reflect_pad(target, pads)

    input_list = jnp.concatenate(
        (preds_p, target_p, preds_p * preds_p, target_p * target_p, preds_p * target_p)
    )  # (5B, C, ...)
    outputs = _depthwise_conv_separable(input_list, factors)
    b = preds.shape[0]
    mu_pred, mu_target, e_pred_sq, e_target_sq, e_pred_target = (outputs[i * b : (i + 1) * b] for i in range(5))

    mu_pred_sq = mu_pred**2
    mu_target_sq = mu_target**2
    mu_pred_target = mu_pred * mu_target

    sigma_pred_sq = e_pred_sq - mu_pred_sq
    sigma_target_sq = e_target_sq - mu_target_sq
    sigma_pred_target = e_pred_target - mu_pred_target

    upper = 2 * sigma_pred_target + c2
    lower = sigma_pred_sq + sigma_target_sq + c2

    ssim_idx_full_image = ((2 * mu_pred_target + c1) * upper) / ((mu_pred_sq + mu_target_sq + c1) * lower)

    # The reflect-padded border is excluded from the score (reference
    # ``ssim.py:178-185``): only interior pixels whose window never touched
    # padding enter the mean. The full (uncropped) map is still returned for
    # ``return_full_image``.
    crop = tuple(slice(p, -p) if p else slice(None) for p in pads)
    ssim_idx = ssim_idx_full_image[(Ellipsis, *crop)]

    if return_contrast_sensitivity:
        # The reference crops cs over the last two dims only, always with the
        # first two pad amounts — even for 3D inputs, where the depth border
        # stays in (``ssim.py:183-185``).
        cs_crop = tuple(slice(p, -p) if p else slice(None) for p in pads[:2])
        contrast_sensitivity = (upper / lower)[(Ellipsis, *cs_crop)]
        return (
            reduce(ssim_idx.reshape(b, -1).mean(-1), reduction),
            reduce(contrast_sensitivity.reshape(b, -1).mean(-1), reduction),
        )
    if return_full_image:
        return reduce(ssim_idx.reshape(b, -1).mean(-1), reduction), reduce(ssim_idx_full_image, reduction)
    return reduce(ssim_idx.reshape(b, -1).mean(-1), reduction)


def structural_similarity_index_measure(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """SSIM (reference ``ssim.py:253-330``).

    Example:
        >>> import jax
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (8, 3, 16, 16))
        >>> target = preds * 0.75
        >>> float(structural_similarity_index_measure(preds, target)) > 0.9
        True
    """
    preds, target = _ssim_update(preds, target)
    return _ssim_compute(
        preds,
        target,
        gaussian_kernel,
        sigma,
        kernel_size,
        reduction,
        data_range,
        k1,
        k2,
        return_full_image,
        return_contrast_sensitivity,
    )


def _get_normalized_sim_and_cs(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    normalize: Optional[str] = None,
) -> Tuple[Array, Array]:
    """Reference ``ssim.py:333-360``."""
    sim, contrast_sensitivity = _ssim_compute(
        preds,
        target,
        gaussian_kernel,
        sigma,
        kernel_size,
        reduction,
        data_range,
        k1,
        k2,
        return_contrast_sensitivity=True,
    )
    if normalize == "relu":
        sim = jax.nn.relu(sim)
        contrast_sensitivity = jax.nn.relu(contrast_sensitivity)
    return sim, contrast_sensitivity


def _avg_pool(x: Array) -> Array:
    spatial = x.ndim - 2
    window = (1, 1) + (2,) * spatial
    return jax.lax.reduce_window(x, 0.0, jax.lax.add, window, window, "VALID") / (2**spatial)


def _multiscale_ssim_compute(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = None,
) -> Array:
    """Reference ``ssim.py:363-487``."""
    spatial = 3 if preds.ndim == 5 else 2
    if not isinstance(kernel_size, Sequence):
        kernel_size = spatial * [kernel_size]
    if not isinstance(sigma, Sequence):
        sigma = spatial * [sigma]

    if preds.shape[-1] < 2 ** len(betas) or preds.shape[-2] < 2 ** len(betas):
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)}, the image height and width dimensions must be"
            f" larger than or equal to {2 ** len(betas)}."
        )
    _betas_div = max(1, (len(betas) - 1)) ** 2
    if preds.shape[-2] // _betas_div <= kernel_size[0] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[0]},"
            f" the image height must be larger than {(kernel_size[0] - 1) * _betas_div}."
        )
    if preds.shape[-1] // _betas_div <= kernel_size[1] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[1]},"
            f" the image width must be larger than {(kernel_size[1] - 1) * _betas_div}."
        )

    # The per-scale sim/cs are reduced with the caller's reduction BEFORE the
    # beta-weighted product (reference ``ssim.py:382-412``): for
    # "elementwise_mean" each scale contributes one scalar, so heterogeneous
    # batches are averaged per scale, not per sample.
    sim_list: List[Array] = []
    cs_list: List[Array] = []
    for _ in range(len(betas)):
        sim, contrast_sensitivity = _get_normalized_sim_and_cs(
            preds, target, gaussian_kernel, sigma, kernel_size, reduction, data_range, k1, k2, normalize=normalize
        )
        sim_list.append(sim)
        cs_list.append(contrast_sensitivity)
        preds = _avg_pool(preds)
        target = _avg_pool(target)

    sim_stack = jnp.stack(sim_list)
    cs_stack = jnp.stack(cs_list)

    if normalize == "simple":
        sim_stack = (sim_stack + 1) / 2
        cs_stack = (cs_stack + 1) / 2

    betas_arr = jnp.asarray(betas, dtype=sim_stack.dtype)
    if reduction is None or reduction == "none":
        # Per-sample path. (The reference's own "none" branch mis-shapes the
        # exponent and only runs when batch == len(betas); this is the sane
        # per-sample semantics instead.)
        sim_stack = sim_stack ** betas_arr[:, None]
        cs_stack = cs_stack ** betas_arr[:, None]
        return jnp.prod(jnp.concatenate([cs_stack[:-1], sim_stack[-1:]]), axis=0)
    sim_stack = sim_stack**betas_arr
    cs_stack = cs_stack**betas_arr
    return jnp.prod(cs_stack[:-1]) * sim_stack[-1]


def multiscale_structural_similarity_index_measure(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = None,
) -> Array:
    """MS-SSIM (reference ``ssim.py:430-487``).

    Example:
        >>> import jax
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (2, 1, 192, 192))
        >>> target = preds * 0.75
        >>> float(multiscale_structural_similarity_index_measure(preds, target, data_range=1.0)) > 0.9
        True
    """
    if not isinstance(betas, tuple) or not all(isinstance(b, float) for b in betas):
        raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
    if normalize and normalize not in ("relu", "simple"):
        raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")
    preds, target = _ssim_update(preds, target)
    return _multiscale_ssim_compute(
        preds, target, gaussian_kernel, sigma, kernel_size, reduction, data_range, k1, k2, betas, normalize
    )

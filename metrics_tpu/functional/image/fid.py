"""FID math (reference ``src/torchmetrics/image/fid.py``, 313 LoC).

TPU-first: the reference computes the matrix square root with **scipy**
``sqrtm`` on CPU via an autograd Function (``image/fid.py:61-95``) — a
host round-trip per compute. Here the square root of
``sigma1 @ sigma2`` is a Newton–Schulz iteration: pure matmuls, runs on
the MXU, differentiable, jittable.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _newton_schulz_sqrtm(mat: Array, num_iters: int = 50, eps: float = 1e-12) -> Array:
    """Matrix square root of a PSD matrix via Newton–Schulz iteration.

    Replaces scipy ``sqrtm`` (reference ``image/fid.py:61-95``); converges
    quadratically for matrices with ``||I - A/||A||_F|| < 1`` which holds for
    the PSD covariance products FID feeds it.
    """
    dim = mat.shape[0]
    norm = jnp.sqrt(jnp.sum(mat * mat)) + eps
    y = mat / norm
    ident = jnp.eye(dim, dtype=mat.dtype)
    z = ident

    def body(_, carry):
        y, z = carry
        t = 0.5 * (3.0 * ident - z @ y)
        return y @ t, t @ z

    y, z = jax.lax.fori_loop(0, num_iters, body, (y, z))
    return y * jnp.sqrt(norm)


def _mean_cov(features: Array) -> Tuple[Array, Array]:
    """Feature mean and unbiased covariance."""
    n = features.shape[0]
    mu = features.mean(axis=0)
    centered = features - mu
    sigma = centered.T @ centered / (n - 1)
    return mu, sigma


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array, eps: float = 1e-6) -> Array:
    """Frechet distance between two Gaussians (reference ``image/fid.py:98-127``).

    Near-singular covariance products can carry tiny negative numerical
    eigenvalues, which the Newton–Schulz iteration turns into NaN; like the
    reference's scipy path, the computation falls back to diagonally-loaded
    covariances ``sigma + eps * I`` when that happens (selected branchlessly
    so the whole thing stays jittable).
    """
    diff = mu1 - mu2
    offset = jnp.eye(sigma1.shape[0], dtype=sigma1.dtype) * eps

    # Validity needs more than finiteness: on ill-conditioned products the
    # fp32 iteration can "converge" to finite garbage. Probe under
    # stop_gradient (no backward is ever built through a bad iteration) and
    # accept only if the residual ||S@S - A||/||A|| is small; otherwise run
    # the diagonally-loaded fallback — selected via lax.cond so just one
    # branch executes and differentiates.
    prod = jax.lax.stop_gradient(sigma1 @ sigma2)
    probe = _newton_schulz_sqrtm(prod)
    prod_norm = jnp.sqrt(jnp.sum(prod * prod))
    residual = jnp.sqrt(jnp.sum((probe @ probe - prod) ** 2)) / (prod_norm + 1e-30)
    ok = jnp.isfinite(residual) & (residual < 1e-2)
    tr_covmean = jax.lax.cond(
        ok,
        lambda: jnp.trace(_newton_schulz_sqrtm(sigma1 @ sigma2)),
        lambda: jnp.trace(_newton_schulz_sqrtm((sigma1 + offset) @ (sigma2 + offset))),
    )
    return diff @ diff + jnp.trace(sigma1) + jnp.trace(sigma2) - 2 * tr_covmean


def frechet_inception_distance_from_features(real_features: Array, fake_features: Array) -> Array:
    """FID from pre-extracted feature matrices ``(N, D)``."""
    real_features = jnp.asarray(real_features, jnp.float64 if jax.config.read("jax_enable_x64") else jnp.float32)
    fake_features = jnp.asarray(fake_features, real_features.dtype)
    mu1, sigma1 = _mean_cov(real_features)
    mu2, sigma2 = _mean_cov(fake_features)
    return _compute_fid(mu1, sigma1, mu2, sigma2)


def _poly_kernel(f1: Array, f2: Array, degree: int = 3, gamma=None, coef: float = 1.0) -> Array:
    """Polynomial kernel (reference ``image/kid.py:24-40``)."""
    if gamma is None:
        gamma = 1.0 / f1.shape[1]
    return (f1 @ f2.T * gamma + coef) ** degree


def _poly_mmd(f_real: Array, f_fake: Array, degree: int = 3, gamma=None, coef: float = 1.0) -> Array:
    """Unbiased polynomial-kernel MMD^2 (reference ``image/kid.py:43-56``)."""
    k_11 = _poly_kernel(f_real, f_real, degree, gamma, coef)
    k_22 = _poly_kernel(f_fake, f_fake, degree, gamma, coef)
    k_12 = _poly_kernel(f_real, f_fake, degree, gamma, coef)

    m = f_real.shape[0]
    diag_x = jnp.diagonal(k_11)
    diag_y = jnp.diagonal(k_22)

    kt_xx_sums = k_11.sum(axis=-1) - diag_x
    kt_yy_sums = k_22.sum(axis=-1) - diag_y
    k_xy_sums = k_12.sum(axis=0)

    value = (kt_xx_sums.sum() + kt_yy_sums.sum()) / (m * (m - 1))
    value -= 2 * k_xy_sums.sum() / (m**2)
    return value

"""FID math (reference ``src/torchmetrics/image/fid.py``, 313 LoC).

TPU-first: the reference computes the matrix square root with **scipy**
``sqrtm`` on CPU via an autograd Function (``image/fid.py:61-95``) — a
host round-trip per compute. Here the square root of
``sigma1 @ sigma2`` is a Newton–Schulz iteration: pure matmuls, runs on
the MXU, differentiable, jittable.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

# All FID linear algebra runs at full fp32 MXU precision: the Newton–Schulz
# iteration is only locally stable, and the TPU default (one bf16 pass) loses
# enough bits to push marginally-conditioned products into divergence.
_HI = jax.lax.Precision.HIGHEST


def _mm(a: Array, b: Array) -> Array:
    return jnp.matmul(a, b, precision=_HI)


def _newton_schulz_sqrtm(mat: Array, num_iters: int = 50, eps: float = 1e-12) -> Array:
    """Matrix square root of a PSD matrix via Newton–Schulz iteration.

    Replaces scipy ``sqrtm`` (reference ``image/fid.py:61-95``); converges
    quadratically for matrices with ``||I - A/||A||_F|| < 1`` which holds for
    well-conditioned PSD covariance products (rank-deficient ones are handled
    by the fallback ladder in :func:`_compute_fid`).
    """
    dim = mat.shape[0]
    norm = jnp.sqrt(jnp.sum(mat * mat)) + eps
    y = mat / norm
    ident = jnp.eye(dim, dtype=mat.dtype)
    z = ident

    # A diverging iteration must produce finite garbage, not NaN: the caller
    # rejects it via the residual check, but NaN primals would poison the
    # zero-cotangent backward pass of the *unselected* branch (0 * NaN = NaN
    # leaks into the input gradients). On a converging iteration the iterates
    # stay O(1), so the clamp is inactive and exactness is untouched; a
    # diverging one is clamped well below fp32 overflow (1e6^2 * dim stays
    # finite through every product below).
    clamp = 1e6

    def body(_, carry):
        y, z = carry
        t = 0.5 * (3.0 * ident - _mm(z, y))
        return (
            jnp.clip(_mm(y, t), -clamp, clamp),
            jnp.clip(_mm(t, z), -clamp, clamp),
        )

    y, z = jax.lax.fori_loop(0, num_iters, body, (y, z))
    return y * jnp.sqrt(norm)


def _trace_sqrtm_psd_product(sigma1: Array, sigma2: Array) -> Array:
    """Exact ``trace(sqrtm(sigma1 @ sigma2))`` for PSD factors via eigh.

    ``sigma1 @ sigma2`` is similar to the PSD matrix
    ``sqrtm(sigma1) @ sigma2 @ sqrtm(sigma1)``, so its eigenvalues are real
    and non-negative; the trace of the square root is the sum of their square
    roots. Unlike Newton–Schulz this is unconditionally stable in the forward
    direction, but its *gradient* is undefined at repeated/zero eigenvalues
    (eigh eigenvector JVPs divide by eigenvalue gaps) — callers that have the
    centered feature matrices should prefer
    :func:`_trace_sqrtm_from_centered`, whose gradients stay finite.
    """
    w1, v1 = jnp.linalg.eigh(sigma1)
    s1h = _mm(v1 * jnp.sqrt(jnp.clip(w1, 0.0)), v1.T)
    inner = _mm(_mm(s1h, sigma2), s1h)
    ev = jnp.linalg.eigvalsh((inner + inner.T) / 2)
    return jnp.sqrt(jnp.clip(ev, 0.0)).sum()


def _trace_sqrtm_from_centered(xc: Array, yc: Array) -> Array:
    """``trace(sqrtm(sigma1 @ sigma2))`` as a nuclear norm of centered features.

    With ``sigma1 = xc.T @ xc / (n-1)`` and ``sigma2 = yc.T @ yc / (m-1)``,
    the nonzero eigenvalues of ``sigma1 @ sigma2`` are (by cyclic
    permutation) the eigenvalues of ``(xc @ yc.T)(xc @ yc.T).T / ((n-1)(m-1))``
    — i.e. the squared singular values of ``xc @ yc.T``. Hence

        trace(sqrtm(sigma1 @ sigma2)) = ||xc @ yc.T||_* / sqrt((n-1)(m-1)).

    Exact for every rank (no square root of eigenvalues is ever formed — the
    singular values *are* the square roots), and differentiable with finite
    gradients even at rank deficiency, where the eigh formulation NaNs.
    """
    n, m = xc.shape[0], yc.shape[0]
    cross = _mm(xc, yc.T)
    sv = jnp.linalg.svd(cross, compute_uv=False)
    return sv.sum() / jnp.sqrt(jnp.asarray((n - 1) * (m - 1), cross.dtype))


def _mean_cov(features: Array) -> Tuple[Array, Array, Array]:
    """Feature mean, unbiased covariance, and the centered features.

    The centered matrix is returned so callers can hand it to
    :func:`_compute_fid`'s terminal fallback without re-materializing the
    ``O(N * D)`` subtraction this function already formed.
    """
    n = features.shape[0]
    mu = features.mean(axis=0)
    centered = features - mu
    sigma = _mm(centered.T, centered) / (n - 1)
    return mu, sigma, centered


def _mean_cov_masked(features: Array, mask: Array) -> Tuple[Array, Array, Array]:
    """Masked feature mean and unbiased covariance — the static-shape
    (CatBuffer) form of :func:`_mean_cov`: invalid rows are zero-weight, so
    the whole thing jits over a fixed ``(capacity, D)`` buffer.

    Also returns the effective sample count (traced)."""
    w = jnp.asarray(mask, features.dtype)[:, None]
    n = w.sum()
    mu = (features * w).sum(axis=0) / n
    centered = (features - mu) * w  # invalid rows contribute nothing
    sigma = _mm(centered.T, centered) / (n - 1)
    return mu, sigma, n


def _compute_fid(
    mu1: Array, sigma1: Array, mu2: Array, sigma2: Array, eps: float = 1e-6, centered=None
) -> Array:
    """Frechet distance between two Gaussians (reference ``image/fid.py:98-127``).

    Near-singular covariance products can carry tiny negative numerical
    eigenvalues, which the Newton–Schulz iteration turns into NaN; like the
    reference's scipy path, the computation falls back to diagonally-loaded
    covariances ``sigma + eps * I`` when that happens (selected branchlessly
    so the whole thing stays jittable).
    """
    diff = mu1 - mu2
    offset = jnp.eye(sigma1.shape[0], dtype=sigma1.dtype) * eps

    # Validity needs more than finiteness: on ill-conditioned products the
    # fp32 iteration can "converge" to finite garbage, so each Newton–Schulz
    # result is accepted only if its residual ||S@S - A||/||A|| is small
    # (checked on stop_gradient values — no backward runs through the check).
    # The ladder: (1) Newton–Schulz on the raw product, (2) Newton–Schulz on
    # diagonally-loaded covariances, (3) an exact terminal formulation that
    # handles rank-deficient N < D covariances — the nuclear-norm identity on
    # centered features when the caller provides them (finite gradients), the
    # eigh trace otherwise. Each iteration runs exactly once: the probed
    # result is itself the branch value, and later rungs live inside
    # lax.cond lambdas so they only execute when the earlier rung fails.
    def _ns_residual_ok(sq: Array, prod: Array) -> Array:
        sq, prod = jax.lax.stop_gradient((sq, prod))
        prod_norm = jnp.sqrt(jnp.sum(prod * prod))
        residual = jnp.sqrt(jnp.sum((_mm(sq, sq) - prod) ** 2)) / (prod_norm + 1e-30)
        return jnp.isfinite(residual) & (residual < 1e-2)

    if centered is not None:
        xc, yc = centered
        # The (n, m) cross matrix must stay SVD-sized in *both* dimensions:
        # past 16x the eigh terminal's d^2 footprint (e.g. a huge accumulated
        # real set against a small fake batch) the eigh trace computes the
        # same exact value in O(d^2) memory. Shapes are static, so this is a
        # trace-time pick.
        d = sigma1.shape[0]
        if xc.shape[0] * yc.shape[0] <= 16 * d * d:
            terminal = lambda: _trace_sqrtm_from_centered(xc, yc)
        else:
            terminal = lambda: _trace_sqrtm_psd_product(sigma1, sigma2)
    else:
        terminal = lambda: _trace_sqrtm_psd_product(sigma1, sigma2)

    def _loaded_rung():
        loaded = _mm(sigma1 + offset, sigma2 + offset)
        sq = _newton_schulz_sqrtm(loaded)
        return jax.lax.cond(
            _ns_residual_ok(sq, loaded), lambda: jnp.trace(sq), terminal
        )

    prod = _mm(sigma1, sigma2)
    sq1 = _newton_schulz_sqrtm(prod)
    tr_covmean = jax.lax.cond(
        _ns_residual_ok(sq1, prod), lambda: jnp.trace(sq1), _loaded_rung
    )
    return jnp.sum(diff * diff) + jnp.trace(sigma1) + jnp.trace(sigma2) - 2 * tr_covmean


def frechet_inception_distance_from_features(real_features: Array, fake_features: Array) -> Array:
    """FID from pre-extracted feature matrices ``(N, D)``."""
    real_features = jnp.asarray(real_features, jnp.float64 if jax.config.read("jax_enable_x64") else jnp.float32)
    fake_features = jnp.asarray(fake_features, real_features.dtype)
    mu1, sigma1, xc = _mean_cov(real_features)
    mu2, sigma2, yc = _mean_cov(fake_features)
    return _compute_fid(mu1, sigma1, mu2, sigma2, centered=(xc, yc))


def _poly_kernel(f1: Array, f2: Array, degree: int = 3, gamma=None, coef: float = 1.0) -> Array:
    """Polynomial kernel (reference ``image/kid.py:24-40``)."""
    if gamma is None:
        gamma = 1.0 / f1.shape[1]
    return (f1 @ f2.T * gamma + coef) ** degree


def _poly_mmd(f_real: Array, f_fake: Array, degree: int = 3, gamma=None, coef: float = 1.0) -> Array:
    """Unbiased polynomial-kernel MMD^2 (reference ``image/kid.py:43-56``)."""
    k_11 = _poly_kernel(f_real, f_real, degree, gamma, coef)
    k_22 = _poly_kernel(f_fake, f_fake, degree, gamma, coef)
    k_12 = _poly_kernel(f_real, f_fake, degree, gamma, coef)

    m = f_real.shape[0]
    diag_x = jnp.diagonal(k_11)
    diag_y = jnp.diagonal(k_22)

    kt_xx_sums = k_11.sum(axis=-1) - diag_x
    kt_yy_sums = k_22.sum(axis=-1) - diag_y
    k_xy_sums = k_12.sum(axis=0)

    value = (kt_xx_sums.sum() + kt_yy_sums.sum()) / (m * (m - 1))
    value -= 2 * k_xy_sums.sum() / (m**2)
    return value

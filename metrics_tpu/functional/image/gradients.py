"""Image gradients (reference
``src/torchmetrics/functional/image/gradients.py``, 81 LoC)."""
from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _image_gradients_validate(img: Array) -> None:
    """Reference ``gradients.py:8-13``."""
    if not isinstance(img, (jax.Array,)) and not hasattr(img, "ndim"):
        raise TypeError(f"The `img` expects an array type but got {type(img)}")
    if img.ndim != 4:
        raise RuntimeError(f"The `img` expects a 4D tensor but got {img.ndim}D tensor")


def _compute_image_gradients(img: Array) -> Tuple[Array, Array]:
    """Reference ``gradients.py:16-33``."""
    dy = jnp.pad(img[..., 1:, :] - img[..., :-1, :], ((0, 0), (0, 0), (0, 1), (0, 0)))
    dx = jnp.pad(img[..., :, 1:] - img[..., :, :-1], ((0, 0), (0, 0), (0, 0), (0, 1)))
    return dy, dx


def image_gradients(img: Array) -> Tuple[Array, Array]:
    """1-step finite-difference gradients ``(dy, dx)`` (reference ``gradients.py:36-81``).

    Example:
        >>> import jax.numpy as jnp
        >>> image = jnp.arange(0, 1*1*5*5, dtype=jnp.float32).reshape(1, 1, 5, 5)
        >>> dy, dx = image_gradients(image)
        >>> dy[0, 0, :, :]
        Array([[5., 5., 5., 5., 5.],
               [5., 5., 5., 5., 5.],
               [5., 5., 5., 5., 5.],
               [5., 5., 5., 5., 5.],
               [0., 0., 0., 0., 0.]], dtype=float32)
    """
    img = jnp.asarray(img)
    _image_gradients_validate(img)
    return _compute_image_gradients(img)

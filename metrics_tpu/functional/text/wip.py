"""Word information preserved (reference ``functional/text/wip.py:21-92``)."""
from typing import List, Tuple, Union

import jax

from metrics_tpu.functional.text.wil import _wil_update

Array = jax.Array

# Same accumulated statistics as WIL (reference's _wip_update mirrors _wil_update).
_wip_update = _wil_update


def _wip_compute(errors: Array, target_total: Array, preds_total: Array) -> Array:
    return (errors / target_total) * (errors / preds_total)


def word_information_preserved(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word information preserved (higher is better).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> round(float(word_information_preserved(preds, target)), 4)
        0.3472
    """
    errors, target_total, preds_total = _wip_update(preds, target)
    return _wip_compute(errors, target_total, preds_total)

"""SacreBLEU (reference ``functional/text/sacre_bleu.py:1-364``).

Same accumulated statistics as BLEU (``bleu.py``); only the host-side
tokenizer differs. The tokenizers implement the canonical sacrebleu specs
(mteval-v13a, international/unicode-punctuation, zh, char — source spec:
https://github.com/mjpost/sacrebleu/tree/master/sacrebleu/tokenizers).
"""
import re
from typing import Optional, Sequence, Union

import jax

from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update

Array = jax.Array

AVAILABLE_TOKENIZERS = ("none", "13a", "zh", "intl", "char", "ja-mecab")

# CJK unicode ranges (sacrebleu's zh tokenizer spec).
_CJK_RANGES = (
    ("㐀", "䶵"),
    ("一", "龥"),
    ("龦", "龻"),
    ("豈", "鶴"),
    ("侮", "頻"),
    ("並", "龎"),
    ("\U00020000", "\U0002a6d6"),
    ("\U0002f800", "\U0002fa1d"),
    ("＀", "￯"),
    ("⺀", "⻿"),
    ("　", "〿"),
    ("㇀", "㇯"),
    ("⼀", "⿟"),
    ("⿰", "⿿"),
    ("㄀", "ㄯ"),
    ("ㆠ", "ㆿ"),
    ("︐", "︟"),
    ("︰", "﹏"),
    ("☀", "⛿"),
    ("✀", "➿"),
    ("㈀", "㋿"),
    ("㌀", "㏿"),
)

# mteval-v13a post-split regexes.
_13A_RULES = (
    (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
    (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
    (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
    (re.compile(r"([0-9])(-)"), r"\1 \2 "),
)

try:  # unicode-category rules need the third-party ``regex`` module
    import regex as _regex_mod

    _INTL_RULES = (
        (_regex_mod.compile(r"(\P{N})(\p{P})"), r"\1 \2 "),
        (_regex_mod.compile(r"(\p{P})(\P{N})"), r" \1 \2"),
        (_regex_mod.compile(r"(\p{S})"), r" \1 "),
    )
except ImportError:  # pragma: no cover - regex is in the baked image
    _INTL_RULES = None


def _apply_rules(line: str, rules) -> str:
    for pattern, repl in rules:
        line = pattern.sub(repl, line)
    return " ".join(line.split())


def _unescape_html(line: str) -> str:
    if "&" in line:
        line = line.replace("&quot;", '"').replace("&amp;", "&")
        line = line.replace("&lt;", "<").replace("&gt;", ">")
    return line


def _is_cjk(char: str) -> bool:
    return any(lo <= char <= hi for lo, hi in _CJK_RANGES)


def _tokenize_13a(line: str) -> str:
    line = line.replace("<skipped>", "").replace("-\n", "").replace("\n", " ")
    return _apply_rules(_unescape_html(line), _13A_RULES)


def _tokenize_intl(line: str) -> str:
    if _INTL_RULES is None:  # pragma: no cover
        raise ModuleNotFoundError("`intl` tokenizer requires the `regex` package")
    return _apply_rules(line, _INTL_RULES)


def _tokenize_zh(line: str) -> str:
    line = line.strip()
    spaced = []
    for char in line:
        if _is_cjk(char):
            spaced.extend((" ", char, " "))
        else:
            spaced.append(char)
    return _apply_rules(_unescape_html("".join(spaced)), _13A_RULES)


def _tokenize_char(line: str) -> str:
    return " ".join(line.strip())


# ja-mecab: sacrebleu's Japanese tokenizer (reference vendors it via the
# ``mecab-python3`` wheel, ``functional/text/sacre_bleu.py`` tokenizer
# table). When MeCab is importable we match sacrebleu exactly
# (``MeCab.Tagger('-Owakati')`` morphological split); otherwise a
# deterministic pure-Python fallback segments on Japanese script
# boundaries — kanji / hiragana / katakana / latin runs, punctuation
# isolated — so Japanese SacreBLEU is *available* everywhere (fallback
# token boundaries approximate, not identical to, MeCab's morphemes).

_HIRAGANA = ("ぁ", "ゟ")
_KATAKANA = ("゠", "ヿ")  # includes the prolonged-sound mark
_KANJI_RANGES = (("一", "鿿"), ("㐀", "䶿"), ("豈", "﫿"))

_MECAB_TAGGER: Union[None, bool, object] = None


def _ja_char_class(char: str) -> str:
    if _HIRAGANA[0] <= char <= _HIRAGANA[1]:
        return "hira"
    if _KATAKANA[0] <= char <= _KATAKANA[1]:
        return "kata"
    if any(lo <= char <= hi for lo, hi in _KANJI_RANGES):
        return "kanji"
    if char.isspace():
        return "space"
    if char.isalnum():
        return "word"
    return "punct"


def _segment_ja_fallback(line: str) -> str:
    tokens, run, prev = [], "", None
    for char in line.strip():
        cls = _ja_char_class(char)
        if cls == "space":
            if run:
                tokens.append(run)
                run = ""
            prev = None
            continue
        if cls == "punct":
            if run:
                tokens.append(run)
                run = ""
            tokens.append(char)
            prev = None
            continue
        if cls != prev and run:
            tokens.append(run)
            run = ""
        run += char
        prev = cls
    if run:
        tokens.append(run)
    return " ".join(tokens)


def _tokenize_ja_mecab(line: str) -> str:
    global _MECAB_TAGGER
    if _MECAB_TAGGER is None:
        try:
            import MeCab

            try:
                import ipadic

                _MECAB_TAGGER = MeCab.Tagger(ipadic.MECAB_ARGS + " -Owakati")
            except ImportError:
                _MECAB_TAGGER = MeCab.Tagger("-Owakati")
        except Exception:
            _MECAB_TAGGER = False
            from metrics_tpu.utilities.prints import rank_zero_warn

            rank_zero_warn(
                "ja-mecab tokenizer: MeCab is not installed; falling back to approximate "
                "script-boundary segmentation. Scores are deterministic here but will DIFFER from "
                "environments where MeCab is available — install `mecab-python3` for sacrebleu-"
                "identical Japanese tokenization.",
                UserWarning,
            )
    if _MECAB_TAGGER:
        return _MECAB_TAGGER.parse(line.strip()).strip()
    return _segment_ja_fallback(line)


_TOKENIZERS = {
    "none": lambda line: line,
    "13a": _tokenize_13a,
    "zh": _tokenize_zh,
    "intl": _tokenize_intl,
    "char": _tokenize_char,
    "ja-mecab": _tokenize_ja_mecab,
}


class _SacreBLEUTokenizer:
    """Callable tokenizer: spec-named transform + optional lowercase + split."""

    def __init__(self, tokenize: str = "13a", lowercase: bool = False) -> None:
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS}")
        self._fn = _TOKENIZERS[tokenize]
        self._lowercase = lowercase

    def __call__(self, line: str) -> Sequence[str]:
        tokenized = self._fn(line)
        if self._lowercase:
            tokenized = tokenized.lower()
        return tokenized.split()


def sacre_bleu_score(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    smooth: bool = False,
    tokenize: str = "13a",
    lowercase: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """SacreBLEU: BLEU with a standardized, reproducible tokenization.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> round(float(sacre_bleu_score(preds, target)), 4)
        0.7598
    """
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)
    target_lists = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
    numerator, denominator, preds_len, target_len = _bleu_score_update(
        preds, target_lists, n_gram, tokenizer
    )
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, weights, smooth)

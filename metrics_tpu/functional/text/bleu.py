"""BLEU score (reference ``functional/text/bleu.py:1-206``).

Honest host/device split (SURVEY.md §7 hard part 4): n-gram counting is
inherently string work and happens on host with Python ``Counter``s; the
accumulated statistics are four tiny device tensors (clipped-match numerator
and candidate denominator per n-gram order, plus the two corpus lengths) with
``sum`` reduction, so distributed sync and the final precision / brevity
penalty / geometric-mean math are pure XLA.
"""
from collections import Counter
from typing import Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def _count_ngram(tokens: Sequence[str], n_gram: int) -> Counter:
    """Multiset of all 1..n_gram grams of a token sequence."""
    counter: Counter = Counter()
    for order in range(1, n_gram + 1):
        for start in range(len(tokens) - order + 1):
            counter[tuple(tokens[start : start + order])] += 1
    return counter


def _tokenize_fn(sentence: str) -> Sequence[str]:
    return sentence.split()


def _bleu_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn,
) -> Tuple[Array, Array, Array, Array]:
    """Host n-gram statistics for a batch → device count tensors.

    Returns ``(numerator, denominator, preds_len, target_len)`` where the
    first two are ``(n_gram,)`` arrays of clipped matches / candidate counts
    and the target length uses the closest-reference-length convention.
    """
    target_tokens = [[list(tokenizer(line)) if line else [] for line in refs] for refs in target]
    pred_tokens = [list(tokenizer(line)) if line else [] for line in preds]

    numerator = [0.0] * n_gram
    denominator = [0.0] * n_gram
    preds_len = 0.0
    target_len = 0.0
    for pred, refs in zip(pred_tokens, target_tokens):
        preds_len += len(pred)
        ref_lens = [len(ref) for ref in refs]
        # closest reference length; ties break to the first reference in list
        # order (the reference's convention — nltk instead breaks to the
        # shortest, which diverges on corpora with tied |len-diff|)
        diffs = [abs(len(pred) - ref_len) for ref_len in ref_lens]
        target_len += ref_lens[diffs.index(min(diffs))]
        pred_counter = _count_ngram(pred, n_gram)
        ref_counter: Counter = Counter()
        for ref in refs:
            ref_counter |= _count_ngram(ref, n_gram)
        clipped = pred_counter & ref_counter
        for ngram, count in clipped.items():
            numerator[len(ngram) - 1] += count
        for ngram, count in pred_counter.items():
            denominator[len(ngram) - 1] += count

    return (
        jnp.asarray(numerator, jnp.float32),
        jnp.asarray(denominator, jnp.float32),
        jnp.asarray(preds_len, jnp.float32),
        jnp.asarray(target_len, jnp.float32),
    )


def _bleu_score_compute(
    preds_len: Array,
    target_len: Array,
    numerator: Array,
    denominator: Array,
    n_gram: int,
    weights: Sequence[float],
    smooth: bool,
) -> Array:
    """Device-side BLEU formula: smoothed precisions, BP, weighted geo-mean.

    Branchless (jit-friendly): the zero-match early exit and the brevity
    penalty condition become ``where`` masks.
    """
    weights_arr = jnp.asarray(weights, jnp.float32)
    if smooth:
        precision = (numerator + 1.0) / (denominator + 1.0)
        precision = precision.at[0].set(numerator[0] / denominator[0])
    else:
        precision = numerator / denominator

    any_zero = jnp.min(numerator) == 0.0
    safe_precision = jnp.where(precision > 0, precision, 1.0)  # log input guard; masked below
    geometric_mean = jnp.exp(jnp.sum(weights_arr * jnp.log(safe_precision)))
    brevity_penalty = jnp.where(preds_len > target_len, 1.0, jnp.exp(1 - target_len / preds_len))
    return jnp.where(any_zero, 0.0, brevity_penalty * geometric_mean)


def bleu_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """Corpus BLEU of machine-translated text against one or more references.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> round(float(bleu_score(preds, target)), 4)
        0.7598
    """
    preds_list = [preds] if isinstance(preds, str) else preds
    target_list = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
    if len(preds_list) != len(target_list):
        raise ValueError(f"Corpus has different size {len(preds_list)} != {len(target_list)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    numerator, denominator, preds_len, target_len = _bleu_score_update(
        preds_list, target_list, n_gram
    )
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, weights, smooth)

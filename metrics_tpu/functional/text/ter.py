"""Translation Edit Rate (reference ``functional/text/ter.py:1-587``).

Tercom algorithm (Snover et al. 2006): greedy phrase shifts that reduce the
hypothesis→reference edit distance, repeated until no shift helps; TER =
(shifts + final edit distance) / average reference length. The shift-candidate
filtering heuristics below *are* the metric definition (they follow tercom /
sacrebleu's ``lib_ter.py`` semantics), so this is host-side sequential work
feeding two scalar ``sum`` statistics; only the final ratio is device math.

Divergence from the reference implementation: the edit-distance DP here is a
plain full-table DP with backtracking (no beam pruning, no suffix cache — the
reference's ``helper.py:36,96`` speed heuristics that can return non-minimal
distances in degenerate cases), and the *hypothesis* is shifted against the
reference per the original tercom orientation.
"""
import re
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array

_MAX_SHIFT_SIZE = 10
_MAX_SHIFT_DIST = 50
_MAX_SHIFT_CANDIDATES = 1000

# Ops for the alignment trace: match, substitute, hyp-only advance (extra hyp
# word), ref-only advance (missing hyp word).
_OP_MATCH, _OP_SUB, _OP_HYP, _OP_REF = 0, 1, 2, 3


class _TercomTokenizer:
    """Tercom normalizer (tercom ``Normalizer.java`` / sacrebleu ``tokenizer_ter.py`` spec)."""

    _ASIAN_PUNCTUATION = r"([、。〈-】〔-〟｡-･・])"
    _FULL_WIDTH_PUNCTUATION = r"([．，？：；！＂（）])"

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
    ) -> None:
        self.normalize = normalize
        self.no_punctuation = no_punctuation
        self.lowercase = lowercase
        self.asian_support = asian_support

    def __call__(self, sentence: str) -> str:
        if not sentence:
            return ""
        if self.lowercase:
            sentence = sentence.lower()
        if self.normalize:
            sentence = self._normalize_general(sentence)
            if self.asian_support:
                sentence = self._normalize_asian(sentence)
        if self.no_punctuation:
            sentence = re.sub(r"[\.,\?:;!\"\(\)]", "", sentence)
            if self.asian_support:
                sentence = re.sub(self._ASIAN_PUNCTUATION, "", sentence)
                sentence = re.sub(self._FULL_WIDTH_PUNCTUATION, "", sentence)
        return " ".join(sentence.split())

    @staticmethod
    def _normalize_general(sentence: str) -> str:
        sentence = f" {sentence} "
        for pattern, repl in (
            (r"\n-", ""),
            (r"\n", " "),
            (r"&quot;", '"'),
            (r"&amp;", "&"),
            (r"&lt;", "<"),
            (r"&gt;", ">"),
            (r"([{-~[-` -&(-+:-@/])", r" \1 "),
            (r"'s ", r" 's "),
            (r"'s$", r" 's"),
            (r"([^0-9])([\.,])", r"\1 \2 "),
            (r"([\.,])([^0-9])", r" \1 \2"),
            (r"([0-9])(-)", r"\1 \2 "),
        ):
            sentence = re.sub(pattern, repl, sentence)
        return sentence

    @classmethod
    def _normalize_asian(cls, sentence: str) -> str:
        sentence = re.sub(r"([一-鿿㐀-䶿])", r" \1 ", sentence)
        sentence = re.sub(r"([㇀-㇯⺀-⻿])", r" \1 ", sentence)
        sentence = re.sub(r"([㌀-㏿豈-﫿︰-﹏])", r" \1 ", sentence)
        sentence = re.sub(r"([㈀-㼢])", r" \1 ", sentence)
        sentence = re.sub(cls._ASIAN_PUNCTUATION, r" \1 ", sentence)
        sentence = re.sub(cls._FULL_WIDTH_PUNCTUATION, r" \1 ", sentence)
        return sentence


def _edit_distance_with_trace(hyp: List[str], ref: List[str]) -> Tuple[int, List[int]]:
    """Min edit distance + backtracked op trace, tercom tie preference.

    Ties resolve substitute/match first, then hyp-advance, then ref-advance
    (matching sacrebleu's operation preference so shift alignments agree).
    """
    m, n = len(hyp), len(ref)
    INF = 1 << 30
    cost = [[0] * (n + 1) for _ in range(m + 1)]
    op = [[_OP_REF] * (n + 1) for _ in range(m + 1)]
    for j in range(n + 1):
        cost[0][j] = j
    for i in range(1, m + 1):
        cost[i][0] = i
        op[i][0] = _OP_HYP
        row, prev = cost[i], cost[i - 1]
        for j in range(1, n + 1):
            if hyp[i - 1] == ref[j - 1]:
                diag, diag_op = prev[j - 1], _OP_MATCH
            else:
                diag, diag_op = prev[j - 1] + 1, _OP_SUB
            best, best_op = diag, diag_op
            up = prev[j] + 1
            if up < best:
                best, best_op = up, _OP_HYP
            left = row[j - 1] + 1
            if left < best:
                best, best_op = left, _OP_REF
            row[j] = best
            op[i][j] = best_op

    trace: List[int] = []
    i, j = m, n
    while i > 0 or j > 0:
        o = op[i][j]
        trace.append(o)
        if o in (_OP_MATCH, _OP_SUB):
            i -= 1
            j -= 1
        elif o == _OP_HYP:
            i -= 1
        else:
            j -= 1
    trace.reverse()
    return cost[m][n], trace


def _trace_to_alignment(trace: List[int]) -> Tuple[Dict[int, int], List[int], List[int]]:
    """ref_pos → hyp_pos alignment plus per-position error flags."""
    hyp_pos = ref_pos = -1
    alignments: Dict[int, int] = {}
    ref_errors: List[int] = []
    hyp_errors: List[int] = []
    for o in trace:
        if o == _OP_MATCH or o == _OP_SUB:
            hyp_pos += 1
            ref_pos += 1
            alignments[ref_pos] = hyp_pos
            err = int(o == _OP_SUB)
            ref_errors.append(err)
            hyp_errors.append(err)
        elif o == _OP_HYP:
            hyp_pos += 1
            hyp_errors.append(1)
        else:  # _OP_REF
            ref_pos += 1
            alignments[ref_pos] = hyp_pos
            ref_errors.append(1)
    return alignments, ref_errors, hyp_errors


def _find_shifted_pairs(hyp: List[str], ref: List[str]):
    """Matching (hyp_start, ref_start, length) sub-spans eligible for a shift."""
    for hyp_start in range(len(hyp)):
        for ref_start in range(len(ref)):
            if abs(ref_start - hyp_start) > _MAX_SHIFT_DIST:
                continue
            for length in range(1, _MAX_SHIFT_SIZE):
                if hyp_start + length - 1 >= len(hyp) or ref_start + length - 1 >= len(ref):
                    break
                if hyp[hyp_start + length - 1] != ref[ref_start + length - 1]:
                    break
                yield hyp_start, ref_start, length
                if len(hyp) == hyp_start + length or len(ref) == ref_start + length:
                    break


def _shift_is_ineligible(
    alignments: Dict[int, int],
    hyp_errors: List[int],
    ref_errors: List[int],
    hyp_start: int,
    ref_start: int,
    length: int,
) -> bool:
    """Tercom corner cases: only shift spans that are misplaced on both sides."""
    if sum(hyp_errors[hyp_start : hyp_start + length]) == 0:
        return True
    if sum(ref_errors[ref_start : ref_start + length]) == 0:
        return True
    if hyp_start <= alignments[ref_start] < hyp_start + length:
        return True
    return False


def _perform_shift(words: List[str], start: int, length: int, target: int) -> List[str]:
    """Move ``words[start:start+length]`` so it lands at position ``target``."""
    if target < start:
        return words[:target] + words[start : start + length] + words[target:start] + words[start + length :]
    if target > start + length:
        return words[:start] + words[start + length : target] + words[start : start + length] + words[target:]
    return (
        words[:start]
        + words[start + length : length + target]
        + words[start : start + length]
        + words[length + target :]
    )


def _best_shift(
    hyp: List[str], ref: List[str], base_distance: int, checked_candidates: int
) -> Tuple[int, List[str], int]:
    """One round of tercom shift search: best gain over all candidates."""
    _, trace = _edit_distance_with_trace(hyp, ref)
    alignments, ref_errors, hyp_errors = _trace_to_alignment(trace)

    best: Optional[Tuple[int, int, int, int, List[str]]] = None
    for hyp_start, ref_start, length in _find_shifted_pairs(hyp, ref):
        if _shift_is_ineligible(alignments, hyp_errors, ref_errors, hyp_start, ref_start, length):
            continue
        prev_idx = -1
        for offset in range(-1, length):
            if ref_start + offset == -1:
                idx = 0
            elif ref_start + offset in alignments:
                idx = alignments[ref_start + offset] + 1
            else:
                break
            if idx == prev_idx:
                continue
            prev_idx = idx
            shifted = _perform_shift(hyp, hyp_start, length, idx)
            gain = base_distance - _edit_distance_with_trace(shifted, ref)[0]
            candidate = (gain, length, -hyp_start, -idx, shifted)
            checked_candidates += 1
            if best is None or candidate > best:
                best = candidate
        if checked_candidates >= _MAX_SHIFT_CANDIDATES:
            break

    if best is None:
        return 0, hyp, checked_candidates
    gain, _, _, _, shifted = best
    return gain, shifted, checked_candidates


def _translation_edit_rate(hyp: List[str], ref: List[str]) -> float:
    """Edits (shifts + remaining edit distance) for one hypothesis/reference."""
    if len(ref) == 0:
        return 0.0
    num_shifts = 0
    checked_candidates = 0
    words = list(hyp)
    while True:
        base_distance, _ = _edit_distance_with_trace(words, ref)
        gain, new_words, checked_candidates = _best_shift(words, ref, base_distance, checked_candidates)
        if gain <= 0 or checked_candidates >= _MAX_SHIFT_CANDIDATES:
            break
        num_shifts += 1
        words = new_words
    edit_distance, _ = _edit_distance_with_trace(words, ref)
    return float(num_shifts + edit_distance)


def _compute_sentence_statistics(
    pred_words: List[str], target_words: List[List[str]]
) -> Tuple[float, float]:
    """Best (lowest) edits over references + average reference length."""
    tgt_lengths = 0.0
    best_num_edits = float(2e16)
    for tgt in target_words:
        num_edits = _translation_edit_rate(pred_words, tgt)
        tgt_lengths += len(tgt)
        best_num_edits = min(best_num_edits, num_edits)
    return best_num_edits, tgt_lengths / max(len(target_words), 1)


def _score_from_statistics(num_edits, tgt_length):
    return jnp.where(
        tgt_length > 0,
        num_edits / jnp.where(tgt_length > 0, tgt_length, 1.0),
        jnp.where(num_edits > 0, 1.0, 0.0),
    )


def _ter_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    tokenizer: _TercomTokenizer,
    collect_sentence_scores: bool = False,
) -> Tuple[Array, Array, Optional[List[Array]]]:
    """Summed edits and reference lengths for a batch of sentence pairs."""
    if isinstance(preds, str):
        preds = [preds]
    target_corpus = [[tgt] if isinstance(tgt, str) else list(tgt) for tgt in target]
    if len(preds) != len(target_corpus):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target_corpus)}")

    total_num_edits = 0.0
    total_tgt_length = 0.0
    sentence_scores: Optional[List[Array]] = [] if collect_sentence_scores else None
    for pred, refs in zip(preds, target_corpus):
        pred_words = tokenizer(pred.rstrip()).split()
        tgt_words = [tokenizer(ref.rstrip()).split() for ref in refs]
        num_edits, tgt_length = _compute_sentence_statistics(pred_words, tgt_words)
        total_num_edits += num_edits
        total_tgt_length += tgt_length
        if sentence_scores is not None:
            sentence_scores.append(
                jnp.atleast_1d(_score_from_statistics(jnp.asarray(num_edits), jnp.asarray(tgt_length)))
            )
    return (
        jnp.asarray(total_num_edits, jnp.float32),
        jnp.asarray(total_tgt_length, jnp.float32),
        sentence_scores,
    )


def _ter_compute(total_num_edits: Array, total_tgt_length: Array) -> Array:
    return _score_from_statistics(total_num_edits, total_tgt_length)


def translation_edit_rate(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
    return_sentence_level_score: bool = False,
):
    """Corpus TER (lower is better).

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> round(float(translation_edit_rate(preds, target)), 4)
        0.1538
    """
    for name, value in (
        ("normalize", normalize),
        ("no_punctuation", no_punctuation),
        ("lowercase", lowercase),
        ("asian_support", asian_support),
    ):
        if not isinstance(value, bool):
            raise ValueError(f"Expected argument `{name}` to be of type boolean but got {value}.")

    tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
    total_num_edits, total_tgt_length, sentence_scores = _ter_update(
        preds, target, tokenizer, collect_sentence_scores=return_sentence_level_score
    )
    score = _ter_compute(total_num_edits, total_tgt_length)
    if return_sentence_level_score:
        return score, sentence_scores
    return score

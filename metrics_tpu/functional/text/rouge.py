"""ROUGE score (reference ``functional/text/rouge.py:42-496``).

Host side: normalization, stemming, n-gram/LCS statistics per sentence pair
(rouge is inherently string work — google-research/rouge semantics). Device
side: per-sentence (precision, recall, fmeasure) triples accumulate into
``sum`` states so the corpus mean and distributed sync are XLA math.

Divergence from the reference: sentence splitting for rougeLsum falls back to
a regex splitter when nltk's punkt data is unavailable (this environment has
no network to download it); explicit ``"\\n"`` splits are always honored
first, matching the google-research implementation's input convention.
"""
import re
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp

ALLOWED_ROUGE_KEYS = {
    "rouge1": 1, "rouge2": 2, "rouge3": 3, "rouge4": 4, "rouge5": 5,
    "rouge6": 6, "rouge7": 7, "rouge8": 8, "rouge9": 9,
    "rougeL": "L", "rougeLsum": "Lsum",
}
ALLOWED_ACCUMULATE_VALUES = ("avg", "best")

_STATS = ("fmeasure", "precision", "recall")


def _split_sentence(text: str) -> Sequence[str]:
    """Sentence-split for rougeLsum: newlines, then nltk, then regex fallback."""
    text = text.replace("<n>", "")  # pegasus newline token
    if "\n" in text:
        return [s for s in text.split("\n") if s.strip()]
    try:
        import nltk

        return nltk.sent_tokenize(text)
    except (ImportError, LookupError):
        return [s for s in re.split(r"(?<=[.!?])\s+", text) if s.strip()]


def _normalize_and_tokenize(
    text: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> List[str]:
    """Rouge text normalization: lowercase alphanumerics, optional stemming."""
    text = normalizer(text) if callable(normalizer) else re.sub(r"[^a-z0-9]+", " ", text.lower())
    tokens = tokenizer(text) if callable(tokenizer) else re.split(r"\s+", text)
    if stemmer:
        tokens = [stemmer.stem(tok) if len(tok) > 3 else tok for tok in tokens]
    return [tok for tok in tokens if isinstance(tok, str) and len(tok) > 0]


def _prf(hits: float, pred_len: int, target_len: int) -> Dict[str, float]:
    if pred_len == 0 or target_len == 0:
        return dict(precision=0.0, recall=0.0, fmeasure=0.0)
    precision = hits / pred_len
    recall = hits / target_len
    if precision == recall == 0.0:
        return dict(precision=0.0, recall=0.0, fmeasure=0.0)
    return dict(precision=precision, recall=recall, fmeasure=2 * precision * recall / (precision + recall))


def _ngram_counter(tokens: Sequence[str], n: int) -> Counter:
    return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))


def _rouge_n_score(pred: Sequence[str], target: Sequence[str], n_gram: int) -> Dict[str, float]:
    pred_counts, target_counts = _ngram_counter(pred, n_gram), _ngram_counter(target, n_gram)
    pred_len, target_len = sum(pred_counts.values()), sum(target_counts.values())
    hits = sum((pred_counts & target_counts).values())
    return _prf(hits, pred_len, target_len)


def _lcs_table(pred: Sequence[str], target: Sequence[str]) -> List[List[int]]:
    table = [[0] * (len(pred) + 1) for _ in range(len(target) + 1)]
    for i in range(1, len(target) + 1):
        for j in range(1, len(pred) + 1):
            if target[i - 1] == pred[j - 1]:
                table[i][j] = table[i - 1][j - 1] + 1
            else:
                table[i][j] = max(table[i - 1][j], table[i][j - 1])
    return table


def _rouge_l_score(pred: Sequence[str], target: Sequence[str]) -> Dict[str, float]:
    if not pred or not target:
        return _prf(0.0, len(pred), len(target))
    lcs = _lcs_table(pred, target)[-1][-1]
    return _prf(lcs, len(pred), len(target))


def _backtracked_lcs_indices(pred: Sequence[str], target: Sequence[str]) -> List[int]:
    """Indices into ``target`` of one longest common subsequence."""
    table = _lcs_table(pred, target)
    i, j = len(pred), len(target)
    picked: List[int] = []
    while i > 0 and j > 0:
        if pred[i - 1] == target[j - 1]:
            picked.insert(0, j - 1)
            i -= 1
            j -= 1
        elif table[j][i - 1] > table[j - 1][i]:
            i -= 1
        else:
            j -= 1
    return picked


def _rouge_lsum_score(
    pred_sentences: Sequence[Sequence[str]], target_sentences: Sequence[Sequence[str]]
) -> Dict[str, float]:
    """Union-LCS summary score (google-research/rouge ``rouge_scorer.py``)."""
    pred_len = sum(map(len, pred_sentences))
    target_len = sum(map(len, target_sentences))
    if pred_len == 0 or target_len == 0:
        return _prf(0.0, pred_len, target_len)

    pred_counts: Counter = Counter()
    target_counts: Counter = Counter()
    for sent in pred_sentences:
        pred_counts.update(sent)
    for sent in target_sentences:
        target_counts.update(sent)

    hits = 0
    for tgt in target_sentences:
        union: set = set()
        for pred in pred_sentences:
            union.update(_backtracked_lcs_indices(pred, tgt))
        for token in (tgt[i] for i in sorted(union)):
            if pred_counts[token] > 0 and target_counts[token] > 0:
                hits += 1
                pred_counts[token] -= 1
                target_counts[token] -= 1
    return _prf(hits, pred_len, target_len)


def _rouge_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    rouge_keys_values: Sequence[Union[int, str]],
    accumulate: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Dict[Union[int, str], List[Dict[str, float]]]:
    """Per-sentence rouge stats with best/avg multi-reference accumulation."""
    results: Dict[Union[int, str], List[Dict[str, float]]] = {key: [] for key in rouge_keys_values}

    for pred_raw, refs_raw in zip(preds, target):
        pred = _normalize_and_tokenize(pred_raw, stemmer, normalizer, tokenizer)
        if "Lsum" in rouge_keys_values:
            pred_lsum = [
                _normalize_and_tokenize(s, stemmer, normalizer, tokenizer)
                for s in _split_sentence(pred_raw)
            ]

        per_ref: List[Dict[Union[int, str], Dict[str, float]]] = []
        for ref_raw in refs_raw:
            ref = _normalize_and_tokenize(ref_raw, stemmer, normalizer, tokenizer)
            scores: Dict[Union[int, str], Dict[str, float]] = {}
            for key in rouge_keys_values:
                if isinstance(key, int):
                    scores[key] = _rouge_n_score(pred, ref, key)
                elif key == "L":
                    scores[key] = _rouge_l_score(pred, ref)
                else:  # Lsum
                    ref_lsum = [
                        _normalize_and_tokenize(s, stemmer, normalizer, tokenizer)
                        for s in _split_sentence(ref_raw)
                    ]
                    scores[key] = _rouge_lsum_score(pred_lsum, ref_lsum)
            per_ref.append(scores)

        if accumulate == "best":
            first_key = rouge_keys_values[0]
            best_idx = max(range(len(per_ref)), key=lambda i: per_ref[i][first_key]["fmeasure"])
            for key in rouge_keys_values:
                results[key].append(per_ref[best_idx][key])
        else:  # avg
            for key in rouge_keys_values:
                averaged = {
                    stat: sum(ref_scores[key][stat] for ref_scores in per_ref) / len(per_ref)
                    for stat in _STATS
                }
                results[key].append(averaged)

    return results


def _rouge_score_compute(sums: Dict[str, Any], count) -> Dict[str, Any]:
    """Corpus means from accumulated sums (device math)."""
    return {name: value / count for name, value in sums.items()}


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    accumulate: str = "best",
    use_stemmer: bool = False,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
) -> Dict[str, Any]:
    """ROUGE-N / ROUGE-L / ROUGE-Lsum with precision/recall/fmeasure per key.

    Example:
        >>> preds = "My name is John"
        >>> target = "Is your name John"
        >>> res = rouge_score(preds, target, rouge_keys="rouge1")
        >>> round(float(res["rouge1_fmeasure"]), 4)
        0.75
    """
    if accumulate not in ALLOWED_ACCUMULATE_VALUES:
        raise ValueError(
            f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
        )
    if isinstance(rouge_keys, str):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS:
            raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS)}")
    rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]

    stemmer = None
    if use_stemmer:
        from nltk.stem.porter import PorterStemmer

        stemmer = PorterStemmer()

    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [[target]]
    else:
        target = [[tgt] if isinstance(tgt, str) else list(tgt) for tgt in target]
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")

    sentence_results = _rouge_score_update(
        preds, target, rouge_keys_values, accumulate, stemmer, normalizer, tokenizer
    )
    output: Dict[str, Any] = {}
    for key_name, key_value in zip(rouge_keys, rouge_keys_values):
        scores = sentence_results[key_value]
        for stat in _STATS:
            vals = [s[stat] for s in scores]
            output[f"{key_name}_{stat}"] = jnp.asarray(sum(vals) / len(vals) if vals else 0.0, jnp.float32)
    return output

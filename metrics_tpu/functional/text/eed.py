"""Extended Edit Distance (reference ``functional/text/eed.py:114-405``).

EED (Stanchev, Wang, Ney, WMT 2019): a CDER-style character-level DP with a
long-jump operation at blanks and a coverage penalty.

TPU-native formulation: the reference runs a per-character Python loop
(``eed.py:146-166``). Here one DP row update is fully vectorized —
the deletion chain ``next[i] = min(next[i-1]+del, base[i])`` is the prefix-min
``min_j (base[j] - j·del) + i·del``, an ``associative_scan``; the long jump is
a row-min broadcast — so the whole DP is a ``lax.scan`` over reference
characters with O(|hyp|) vector work per step, ``vmap``-ped over all
(hypothesis, reference) pairs at once.
"""
import re
import unicodedata
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from metrics_tpu.functional.text.helper import _bucket

Array = jax.Array

_INF = np.float32(1e30)  # plain numpy: a jnp scalar here would init the backend at import


def _eed_pair_kernel(
    hyp_ids: Array, hyp_len: Array, ref_ids: Array, ref_len: Array,
    alpha: float, rho: float, deletion: float, insertion: float,
) -> Array:
    """EED score for one padded (hyp, ref) codepoint pair."""
    h_cap = hyp_ids.shape[0]
    idx = jnp.arange(h_cap + 1)
    valid = idx <= hyp_len  # positions 0..hyp_len are live

    row0 = jnp.where(idx == 0, 0.0, 1.0)
    row0 = jnp.where(valid, row0, _INF)
    visits0 = jnp.full((h_cap + 1,), -1, jnp.int32)

    space = jnp.asarray(ord(" "), ref_ids.dtype)

    def step(carry, w):
        row, visits = carry
        ref_char, w_active = w
        # substitution / match against hyp char i-1
        hyp_chars = jnp.concatenate([jnp.zeros((1,), hyp_ids.dtype), hyp_ids])  # align to idx
        sub_cost = jnp.where(hyp_chars == ref_char, 0.0, 1.0)
        shifted_row = jnp.concatenate([jnp.full((1,), _INF), row[:-1]])  # row[i-1]
        base = jnp.minimum(shifted_row + sub_cost, row + insertion)
        base = jnp.where(idx == 0, row + 1.0, base)
        base = jnp.where(valid, base, _INF)
        # deletion chain as prefix-min: next[i] = min_{j<=i}(base[j] + (i-j)*deletion)
        next_row = lax.associative_scan(jnp.minimum, base - idx * deletion) + idx * deletion
        next_row = jnp.where(valid, next_row, _INF)
        # coverage bookkeeping: first index achieving the row minimum
        row_min = jnp.min(next_row)
        min_index = jnp.argmin(next_row)
        visits_new = visits.at[min_index].add(1)
        # long jump at blanks
        jumped = jnp.minimum(next_row, alpha + row_min)
        next_row = jnp.where(ref_char == space, jumped, next_row)
        next_row = jnp.where(valid, next_row, _INF)
        # padded ref steps leave the carry untouched
        row_out = jnp.where(w_active, next_row, row)
        visits_out = jnp.where(w_active, visits_new, visits)
        return (row_out, visits_out), None

    steps = (ref_ids, jnp.arange(ref_ids.shape[0]) < ref_len)
    (row, visits), _ = lax.scan(step, (row0, visits0), steps)

    coverage = rho * jnp.sum(jnp.where(valid, jnp.where(visits >= 0, visits, 1), 0).astype(jnp.float32))
    errors = row[hyp_len]
    return jnp.minimum(1.0, (errors + coverage) / (ref_len.astype(jnp.float32) + coverage))


def _eed_batch(hyp_ids, hyp_len, ref_ids, ref_len, alpha, rho, deletion, insertion):
    kernel = jax.vmap(
        lambda a, al, b, bl: _eed_pair_kernel(a, al, b, bl, alpha, rho, deletion, insertion)
    )
    return jax.jit(kernel)(hyp_ids, hyp_len, ref_ids, ref_len)


def _encode_chars(strings: Sequence[str], cap: int) -> Tuple[Array, Array]:
    arr = np.full((len(strings), cap), -1, np.int32)
    for row, s in enumerate(strings):
        codes = [ord(c) for c in s][:cap]
        arr[row, : len(codes)] = codes
    lens = np.asarray([min(len(s), cap) for s in strings], np.int32)
    return jnp.asarray(arr), jnp.asarray(lens)


def _preprocess_en(sentence: str) -> str:
    """EED English normalization (rwth-i6/ExtendedEditDistance ``util.py`` spec)."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    sentence = sentence.rstrip()
    for pattern, repl in ((".", " ."), ("!", " !"), ("?", " ?"), (",", " ,")):
        sentence = sentence.replace(pattern, repl)
    sentence = re.sub(r"\s+", " ", sentence)
    sentence = re.sub(r"(\d) ([.,]) (\d)", r"\1\2\3", sentence)
    sentence = re.sub(r"(Dr|Jr|Prof|Rev|Gen|Mr|Mt|Mrs|Ms) .", r"\1.", sentence)
    for pattern, repl in (("e . g .", "e.g."), ("i . e .", "i.e."), ("U . S .", "U.S.")):
        sentence = sentence.replace(pattern, repl)
    return " " + sentence + " "


def _preprocess_ja(sentence: str) -> str:
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    return unicodedata.normalize("NFKC", sentence.rstrip())


def _eed_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> List[Array]:
    """Per-sentence EED scores (best = lowest over references).

    All (hyp, ref) pairs in the batch run through one vmapped DP kernel.
    """
    if isinstance(preds, str):
        preds = [preds]
    target_corpus = [[tgt] if isinstance(tgt, str) else list(tgt) for tgt in target]
    if len(preds) != len(target_corpus):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target_corpus)}")
    if len(preds) == 0 or any(len(refs) == 0 for refs in target_corpus):
        return []

    if language == "en":
        preprocess = _preprocess_en
    elif language == "ja":
        preprocess = _preprocess_ja
    else:
        raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")

    hyp_strings: List[str] = []
    ref_strings: List[str] = []
    pair_owner: List[int] = []
    for i, (pred, refs) in enumerate(zip(preds, target_corpus)):
        pred_p = preprocess(pred)
        for ref in refs:
            hyp_strings.append(pred_p)
            ref_strings.append(preprocess(ref))
            pair_owner.append(i)

    h_cap = _bucket(max(len(s) for s in hyp_strings))
    r_cap = _bucket(max(len(s) for s in ref_strings))
    hyp_ids, hyp_len = _encode_chars(hyp_strings, h_cap)
    ref_ids, ref_len = _encode_chars(ref_strings, r_cap)
    scores = _eed_batch(hyp_ids, hyp_len, ref_ids, ref_len, alpha, rho, deletion, insertion)

    scores_np = np.asarray(scores)
    best = np.full(len(preds), np.inf, np.float32)
    for pair_idx, owner in enumerate(pair_owner):
        best[owner] = min(best[owner], scores_np[pair_idx])
    return [jnp.asarray(s) for s in best]


def _eed_compute(sentence_level_scores: List[Array]) -> Array:
    if len(sentence_level_scores) == 0:
        return jnp.asarray(0.0)
    return sum(sentence_level_scores) / len(sentence_level_scores)


def extended_edit_distance(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    return_sentence_level_score: bool = False,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
):
    """Extended edit distance (lower is better; scores in [0, 1]).

    Example:
        >>> preds = ["this is the prediction", "here is an other sample"]
        >>> target = ["this is the reference", "here is another one"]
        >>> round(float(extended_edit_distance(preds=preds, target=target)), 4)
        0.3078
    """
    for name, value in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
        if not isinstance(value, float) or value < 0:
            raise ValueError(f"Parameter `{name}` is expected to be a non-negative float.")

    sentence_scores = _eed_update(preds, target, language, alpha, rho, deletion, insertion)
    average = _eed_compute(sentence_scores)
    if return_sentence_level_score:
        return average, sentence_scores
    return average

"""BERTScore (reference ``functional/text/bert.py:1-630``).

Greedy cosine matching of contextual token embeddings with optional IDF
weighting (Zhang et al., ICLR 2020). The matching math — normalize, masked
``bpd,brd->bpr`` similarity, row/column max, IDF-weighted sum — is one
jittable XLA kernel (``_bert_score_from_embeddings``).

Encoder contract (same as FID's injected extractor, ``image/fid.py``): this
environment has no network, so no pretrained weights are bundled. The
``encoder`` callable maps a list of sentences to
``(embeddings (N, L, D), attention_mask (N, L), input_ids (N, L))``; any HF
flax/torch model with local weights wraps in a few lines. Alternatively pass
precomputed dicts with those keys.
"""
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_EncoderOutput = Tuple[np.ndarray, np.ndarray, np.ndarray]


def _strip_special_tokens(attention_mask: Array) -> Array:
    """Zero the first token ([CLS]) and last attended token ([SEP]) per row."""
    mask = attention_mask.astype(jnp.float32)
    idx = jnp.arange(mask.shape[1])[None, :]
    last = (mask * (idx + 1)).max(axis=1) - 1  # index of last attended token
    mask = jnp.where(idx == 0, 0.0, mask)
    mask = jnp.where(idx == last[:, None], 0.0, mask)
    return mask


def _idf_weights(input_ids: np.ndarray, attention_mask: np.ndarray) -> Dict[int, float]:
    """Corpus IDF per token id: log((N+1)/(df+1)) over reference sentences."""
    num_docs = input_ids.shape[0]
    df: Dict[int, int] = {}
    for row in range(num_docs):
        for token in set(input_ids[row][attention_mask[row] > 0].tolist()):
            df[token] = df.get(token, 0) + 1
    return {token: float(np.log((num_docs + 1) / (count + 1))) for token, count in df.items()}


def _idf_scale(input_ids: np.ndarray, mask: np.ndarray, idf: Optional[Dict[int, float]]) -> np.ndarray:
    """Per-token weights normalized to sum 1 per sentence (uniform if no idf)."""
    if idf is None:
        weights = mask.astype(np.float32)
    else:
        lookup = np.vectorize(lambda t: idf.get(int(t), 0.0), otypes=[np.float32])
        weights = lookup(input_ids) * mask
    denom = weights.sum(-1, keepdims=True)
    return weights / np.where(denom > 0, denom, 1.0)


@jax.jit
def _bert_score_from_embeddings(
    pred_emb: Array, pred_mask: Array, pred_scale: Array,
    target_emb: Array, target_mask: Array, target_scale: Array,
) -> Tuple[Array, Array, Array]:
    """Greedy-matching precision/recall/F1 per sentence pair (device math)."""
    def normalize(emb, mask):
        norm = jnp.linalg.norm(emb, axis=-1, keepdims=True)
        emb = emb / jnp.where(norm > 0, norm, 1.0)
        return emb * mask[..., None]

    pred_n = normalize(pred_emb, pred_mask)
    target_n = normalize(target_emb, target_mask)
    cos_sim = jnp.einsum("bpd,brd->bpr", pred_n, target_n)
    precision = jnp.sum(cos_sim.max(axis=2) * pred_scale, axis=-1)
    recall = jnp.sum(cos_sim.max(axis=1) * target_scale, axis=-1)
    denom = precision + recall
    f1 = jnp.where(denom > 0, 2 * precision * recall / jnp.where(denom > 0, denom, 1.0), 0.0)
    return precision, recall, f1


def _encode(
    text: Union[Sequence[str], Dict[str, Any]],
    encoder: Optional[Callable[[List[str]], _EncoderOutput]],
    max_length: int,
) -> _EncoderOutput:
    if isinstance(text, dict):
        emb = np.asarray(text["embeddings"], np.float32)
        mask = np.asarray(text["attention_mask"])
        ids = np.asarray(text.get("input_ids", np.zeros(mask.shape, np.int64)))
        return emb, mask, ids
    if encoder is None:
        raise ValueError(
            "BERTScore needs an `encoder` callable (or precomputed embedding dicts): this build "
            "bundles no pretrained weights. Wrap any local HF model as "
            "`encoder(sentences) -> (embeddings, attention_mask, input_ids)`."
        )
    emb, mask, ids = encoder(list(text))
    return (
        np.asarray(emb, np.float32)[:, :max_length],
        np.asarray(mask)[:, :max_length],
        np.asarray(ids)[:, :max_length],
    )


def _pad_to(arr: np.ndarray, length: int) -> np.ndarray:
    if arr.shape[1] == length:
        return arr
    pad = [(0, 0), (0, length - arr.shape[1])] + [(0, 0)] * (arr.ndim - 2)
    return np.pad(arr, pad)


def bert_score(
    preds: Union[Sequence[str], Dict[str, Any]],
    target: Union[Sequence[str], Dict[str, Any]],
    encoder: Optional[Callable[[List[str]], _EncoderOutput]] = None,
    idf: bool = False,
    max_length: int = 512,
    rescale_with_baseline: bool = False,
    baseline: Optional[Sequence[float]] = None,
) -> Dict[str, Array]:
    """BERTScore precision/recall/f1 per sentence pair.

    ``baseline`` (three floats: precision/recall/f1 baselines) enables the
    original implementation's rescaling ``(x - b) / (1 - b)`` without a
    baseline-file download.
    """
    pred_emb, pred_mask, pred_ids = _encode(preds, encoder, max_length)
    target_emb, target_mask, target_ids = _encode(target, encoder, max_length)
    if pred_emb.shape[0] != target_emb.shape[0]:
        raise ValueError("Expected the same number of predicted and reference sentences.")

    length = max(pred_emb.shape[1], target_emb.shape[1])
    pred_emb, pred_mask, pred_ids = (_pad_to(a, length) for a in (pred_emb, pred_mask, pred_ids))
    target_emb, target_mask, target_ids = (_pad_to(a, length) for a in (target_emb, target_mask, target_ids))

    pred_mask_j = _strip_special_tokens(jnp.asarray(pred_mask))
    target_mask_j = _strip_special_tokens(jnp.asarray(target_mask))
    idf_map = _idf_weights(target_ids, np.asarray(target_mask)) if idf else None
    pred_scale = jnp.asarray(_idf_scale(pred_ids, np.asarray(pred_mask_j), idf_map))
    target_scale = jnp.asarray(_idf_scale(target_ids, np.asarray(target_mask_j), idf_map))

    precision, recall, f1 = _bert_score_from_embeddings(
        jnp.asarray(pred_emb), pred_mask_j, pred_scale,
        jnp.asarray(target_emb), target_mask_j, target_scale,
    )
    if rescale_with_baseline:
        if baseline is None:
            raise ValueError(
                "`rescale_with_baseline` requires the `baseline` argument (no baseline files are bundled)."
            )
        b_p, b_r, b_f = (jnp.asarray(b, jnp.float32) for b in baseline)
        precision = (precision - b_p) / (1.0 - b_p)
        recall = (recall - b_r) / (1.0 - b_r)
        f1 = (f1 - b_f) / (1.0 - b_f)
    return {"precision": precision, "recall": recall, "f1": f1}

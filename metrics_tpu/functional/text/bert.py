"""BERTScore (reference ``functional/text/bert.py:1-630``).

Greedy cosine matching of contextual token embeddings with optional IDF
weighting (Zhang et al., ICLR 2020). The matching math — normalize, masked
``bpd,brd->bpr`` similarity, row/column max, IDF-weighted sum — is one
jittable XLA kernel (``_bert_score_from_embeddings``).

Encoder contract (same as FID's injected extractor, ``image/fid.py``): the
``encoder`` callable maps a list of sentences to
``(embeddings (N, L, D), attention_mask (N, L), input_ids (N, L))``. The
real-architecture path is :class:`metrics_tpu.nets.BertEncoder` — a flax
BERT key-compatible with HF ``BertModel`` checkpoints
(``BertEncoder(tokenizer, weights=hf_state_dict)`` gives published-scale
scores). Alternatively pass precomputed dicts with those keys.

When no encoder is given, a bundled :class:`HashTextEncoder` is used so the
surface works out of the box — a deterministic CRC32-hash-vocab tokenizer
with a fixed random embedding table and light neighbor mixing. **It is NOT a
pretrained language model**: scores are self-consistent (identical text
scores 1.0, related text scores higher than unrelated) but are not
comparable to published BERTScore numbers. Inject a real encoder for
calibrated scores; the reference downloads RoBERTa weights instead
(``functional/text/bert.py:29,551-552``), which this offline build cannot.
"""
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_EncoderOutput = Tuple[np.ndarray, np.ndarray, np.ndarray]


def _strip_special_tokens(attention_mask: Array) -> Array:
    """Zero the first token ([CLS]) and last attended token ([SEP]) per row."""
    mask = attention_mask.astype(jnp.float32)
    idx = jnp.arange(mask.shape[1])[None, :]
    last = (mask * (idx + 1)).max(axis=1) - 1  # index of last attended token
    mask = jnp.where(idx == 0, 0.0, mask)
    mask = jnp.where(idx == last[:, None], 0.0, mask)
    return mask


def _idf_weights(input_ids: np.ndarray, attention_mask: np.ndarray) -> Dict[int, float]:
    """Corpus IDF per token id: log((N+1)/(df+1)) over reference sentences."""
    num_docs = input_ids.shape[0]
    df: Dict[int, int] = {}
    for row in range(num_docs):
        for token in set(input_ids[row][attention_mask[row] > 0].tolist()):
            df[token] = df.get(token, 0) + 1
    return {token: float(np.log((num_docs + 1) / (count + 1))) for token, count in df.items()}


def _idf_scale(input_ids: np.ndarray, mask: np.ndarray, idf: Optional[Dict[int, float]]) -> np.ndarray:
    """Per-token weights normalized to sum 1 per sentence (uniform if no idf)."""
    if idf is None:
        weights = mask.astype(np.float32)
    else:
        lookup = np.vectorize(lambda t: idf.get(int(t), 0.0), otypes=[np.float32])
        weights = lookup(input_ids) * mask
    denom = weights.sum(-1, keepdims=True)
    return weights / np.where(denom > 0, denom, 1.0)


@jax.jit
def _bert_score_from_embeddings(
    pred_emb: Array, pred_mask: Array, pred_scale: Array,
    target_emb: Array, target_mask: Array, target_scale: Array,
) -> Tuple[Array, Array, Array]:
    """Greedy-matching precision/recall/F1 per sentence pair (device math)."""
    def normalize(emb, mask):
        norm = jnp.linalg.norm(emb, axis=-1, keepdims=True)
        emb = emb / jnp.where(norm > 0, norm, 1.0)
        return emb * mask[..., None]

    pred_n = normalize(pred_emb, pred_mask)
    target_n = normalize(target_emb, target_mask)
    cos_sim = jnp.einsum("bpd,brd->bpr", pred_n, target_n)
    precision = jnp.sum(cos_sim.max(axis=2) * pred_scale, axis=-1)
    recall = jnp.sum(cos_sim.max(axis=1) * target_scale, axis=-1)
    denom = precision + recall
    f1 = jnp.where(denom > 0, 2 * precision * recall / jnp.where(denom > 0, denom, 1.0), 0.0)
    return precision, recall, f1


class HashTextEncoder:
    """Bundled offline encoder satisfying BERTScore's encoder contract.

    Deterministic end to end: sentences are word/punctuation tokenized,
    token ids come from CRC32 hashing into a fixed vocab, embeddings from a
    seeded random table, and a light fixed neighbor-mixing pass
    (``0.6·tok + 0.25·prev + 0.15·next``) gives tokens context sensitivity
    so reorderings and substitutions move the score. Two processes with the
    same seed produce bit-identical embeddings — safe for distributed
    accumulation.

    **Calibration caveat (read this):** this is a structural stand-in, not a
    language model. Scores are meaningful relatively (identity = 1.0,
    related > unrelated) but NOT comparable to published BERTScore values
    computed with pretrained transformers.
    """

    _CLS, _SEP, _RESERVED = 1, 2, 3

    def __init__(self, dim: int = 128, vocab_size: int = 1 << 15, seed: int = 0) -> None:
        rng = np.random.default_rng(seed)
        self.table = rng.standard_normal((vocab_size, dim), dtype=np.float32)
        self.vocab_size = vocab_size
        self.dim = dim

    @staticmethod
    def _tokenize(sentence: str) -> List[str]:
        import re

        return re.findall(r"\w+|[^\w\s]", sentence.lower())

    def _token_id(self, token: str) -> int:
        import zlib

        return self._RESERVED + zlib.crc32(token.encode("utf-8")) % (self.vocab_size - self._RESERVED)

    def __call__(self, sentences: List[str]) -> _EncoderOutput:
        rows = [[self._CLS] + [self._token_id(t) for t in self._tokenize(s)] + [self._SEP] for s in sentences]
        length = max((len(r) for r in rows), default=0)
        if length == 0:
            return (
                np.zeros((0, 0, self.dim), np.float32),
                np.zeros((0, 0), np.int64),
                np.zeros((0, 0), np.int64),
            )
        ids = np.zeros((len(rows), length), np.int64)
        mask = np.zeros((len(rows), length), np.int64)
        for i, r in enumerate(rows):
            ids[i, : len(r)] = r
            mask[i, : len(r)] = 1
        emb = self.table[ids] * mask[..., None].astype(np.float32)
        prev_tok = np.roll(emb, 1, axis=1)
        prev_tok[:, 0] = 0
        next_tok = np.roll(emb, -1, axis=1)
        next_tok[:, -1] = 0
        emb = 0.6 * emb + 0.25 * prev_tok + 0.15 * next_tok
        return emb.astype(np.float32), mask, ids


_DEFAULT_ENCODER: Optional[HashTextEncoder] = None
_DEFAULT_ENCODER_WARNED = False


def _default_encoder() -> HashTextEncoder:
    global _DEFAULT_ENCODER, _DEFAULT_ENCODER_WARNED
    if _DEFAULT_ENCODER is None:
        _DEFAULT_ENCODER = HashTextEncoder()
    if not _DEFAULT_ENCODER_WARNED:
        from metrics_tpu.utilities.prints import rank_zero_warn

        rank_zero_warn(
            "BERTScore is using the bundled HashTextEncoder (deterministic hash-vocab embeddings), "
            "not a pretrained language model: scores are self-consistent but NOT comparable to "
            "published BERTScore numbers. Pass `encoder=` wrapping a local HF model for calibrated "
            "scores.",
            UserWarning,
        )
        _DEFAULT_ENCODER_WARNED = True
    return _DEFAULT_ENCODER


def _encode(
    text: Union[Sequence[str], Dict[str, Any]],
    encoder: Optional[Callable[[List[str]], _EncoderOutput]],
    max_length: int,
) -> _EncoderOutput:
    if isinstance(text, dict):
        emb = np.asarray(text["embeddings"], np.float32)
        mask = np.asarray(text["attention_mask"])
        ids = np.asarray(text.get("input_ids", np.zeros(mask.shape, np.int64)))
        return emb, mask, ids
    if encoder is None:
        encoder = _default_encoder()
    emb, mask, ids = encoder(list(text))
    return (
        np.asarray(emb, np.float32)[:, :max_length],
        np.asarray(mask)[:, :max_length],
        np.asarray(ids)[:, :max_length],
    )


def _pad_to(arr: np.ndarray, length: int) -> np.ndarray:
    if arr.shape[1] == length:
        return arr
    pad = [(0, 0), (0, length - arr.shape[1])] + [(0, 0)] * (arr.ndim - 2)
    return np.pad(arr, pad)


def bert_score(
    preds: Union[Sequence[str], Dict[str, Any]],
    target: Union[Sequence[str], Dict[str, Any]],
    encoder: Optional[Callable[[List[str]], _EncoderOutput]] = None,
    idf: bool = False,
    max_length: int = 512,
    rescale_with_baseline: bool = False,
    baseline: Optional[Sequence[float]] = None,
) -> Dict[str, Array]:
    """BERTScore precision/recall/f1 per sentence pair.

    ``baseline`` (three floats: precision/recall/f1 baselines) enables the
    original implementation's rescaling ``(x - b) / (1 - b)`` without a
    baseline-file download.

    Example (bundled HashTextEncoder — see the module docstring's
    calibration caveat; inject ``encoder=`` for published-comparable
    scores):
        >>> import warnings
        >>> with warnings.catch_warnings():
        ...     warnings.simplefilter("ignore")
        ...     score = bert_score(["the cat is on the mat"], ["the cat is on the mat"])
        >>> round(float(score["f1"][0]), 2)
        1.0
    """
    pred_emb, pred_mask, pred_ids = _encode(preds, encoder, max_length)
    target_emb, target_mask, target_ids = _encode(target, encoder, max_length)
    if pred_emb.shape[0] != target_emb.shape[0]:
        raise ValueError("Expected the same number of predicted and reference sentences.")
    if pred_emb.shape[0] == 0:
        empty = jnp.zeros((0,), jnp.float32)
        return {"precision": empty, "recall": empty, "f1": empty}

    length = max(pred_emb.shape[1], target_emb.shape[1])
    pred_emb, pred_mask, pred_ids = (_pad_to(a, length) for a in (pred_emb, pred_mask, pred_ids))
    target_emb, target_mask, target_ids = (_pad_to(a, length) for a in (target_emb, target_mask, target_ids))

    pred_mask_j = _strip_special_tokens(jnp.asarray(pred_mask))
    target_mask_j = _strip_special_tokens(jnp.asarray(target_mask))
    idf_map = _idf_weights(target_ids, np.asarray(target_mask)) if idf else None
    pred_scale = jnp.asarray(_idf_scale(pred_ids, np.asarray(pred_mask_j), idf_map))
    target_scale = jnp.asarray(_idf_scale(target_ids, np.asarray(target_mask_j), idf_map))

    precision, recall, f1 = _bert_score_from_embeddings(
        jnp.asarray(pred_emb), pred_mask_j, pred_scale,
        jnp.asarray(target_emb), target_mask_j, target_scale,
    )
    if rescale_with_baseline:
        if baseline is None:
            raise ValueError(
                "`rescale_with_baseline` requires the `baseline` argument (no baseline files are bundled)."
            )
        b_p, b_r, b_f = (jnp.asarray(b, jnp.float32) for b in baseline)
        precision = (precision - b_p) / (1.0 - b_p)
        recall = (recall - b_r) / (1.0 - b_r)
        f1 = (f1 - b_f) / (1.0 - b_f)
    return {"precision": precision, "recall": recall, "f1": f1}

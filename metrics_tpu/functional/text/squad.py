"""SQuAD exact-match / F1 (reference ``functional/text/squad.py:20-253``).

The official SQuAD v1.1 evaluation semantics: per-question max over ground
truths of normalized exact-match and token F1. Host string work feeding three
scalar ``sum`` statistics.
"""
import re
import string
from collections import Counter
from typing import Any, Dict, List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

SINGLE_PRED_TYPE = Dict[str, str]
PREDS_TYPE = Union[SINGLE_PRED_TYPE, List[SINGLE_PRED_TYPE]]
SINGLE_TARGET_TYPE = Dict[str, Any]
TARGETS_TYPE = Union[SINGLE_TARGET_TYPE, List[SINGLE_TARGET_TYPE]]

SQuAD_FORMAT = {
    "answers": {"answer_start": [1], "text": ["This is a test text"]},
    "context": "This is a test context.",
    "id": "1",
    "question": "Is this a test?",
    "title": "train test",
}

_ARTICLES_RE = re.compile(r"\b(a|an|the)\b")
_PUNC = set(string.punctuation)


def _normalize_text(text: str) -> str:
    """Lowercase; strip punctuation, articles, and extra whitespace."""
    text = "".join(ch for ch in text.lower() if ch not in _PUNC)
    return " ".join(_ARTICLES_RE.sub(" ", text).split())


def _get_tokens(text: str) -> List[str]:
    return _normalize_text(text).split() if text else []


def _f1_score(predicted_answer: str, target_answer: str) -> float:
    target_tokens = _get_tokens(target_answer)
    predicted_tokens = _get_tokens(predicted_answer)
    if not target_tokens or not predicted_tokens:
        # no-answer case: credit only if both are empty
        return float(target_tokens == predicted_tokens)
    num_same = sum((Counter(target_tokens) & Counter(predicted_tokens)).values())
    if num_same == 0:
        return 0.0
    precision = num_same / len(predicted_tokens)
    recall = num_same / len(target_tokens)
    return 2 * precision * recall / (precision + recall)


def _exact_match_score(prediction: str, ground_truth: str) -> float:
    return float(_normalize_text(prediction) == _normalize_text(ground_truth))


def _squad_input_check(preds: PREDS_TYPE, targets: TARGETS_TYPE):
    """Validate and reshape inputs to {id: pred_text} + SQuAD article dicts."""
    if isinstance(preds, dict):
        preds = [preds]
    if isinstance(targets, dict):
        targets = [targets]

    for pred in preds:
        if "prediction_text" not in pred or "id" not in pred:
            raise KeyError(
                "Expected keys in a single prediction are 'prediction_text' and 'id'."
                "Please make sure that 'prediction_text' maps to the answer string and 'id' maps to the key string."
            )
    for target in targets:
        if "answers" not in target or "id" not in target:
            raise KeyError(
                "Expected keys in a single target are 'answers' and 'id'."
                "Please make sure that 'answers' maps to a `SQuAD` format dictionary and 'id' maps to the key "
                f"string.\nSQuAD Format: {SQuAD_FORMAT}"
            )
        if "text" not in target["answers"]:
            raise KeyError(
                "Expected keys in a 'answers' are 'text'."
                "Please make sure that 'answer' maps to a `SQuAD` format dictionary.\n"
                f"SQuAD Format: {SQuAD_FORMAT}"
            )

    preds_dict = {pred["id"]: pred["prediction_text"] for pred in preds}
    qas = [
        {"answers": [{"text": txt} for txt in tgt["answers"]["text"]], "id": tgt["id"]}
        for tgt in targets
    ]
    return preds_dict, [{"paragraphs": [{"qas": qas}]}]


def _squad_update(preds: Dict[str, str], target: List[Dict[str, Any]]) -> Tuple[Array, Array, Array]:
    """Summed F1 / exact-match / total over a batch of SQuAD articles."""
    f1 = 0.0
    exact_match = 0.0
    total = 0
    for article in target:
        for paragraph in article["paragraphs"]:
            for qa in paragraph["qas"]:
                total += 1
                if qa["id"] not in preds:
                    rank_zero_warn(f"Unanswered question {qa['id']} will receive score 0.")
                    continue
                truths = [answer["text"] for answer in qa["answers"]]
                pred = preds[qa["id"]]
                exact_match += max(_exact_match_score(pred, truth) for truth in truths)
                f1 += max(_f1_score(pred, truth) for truth in truths)
    return jnp.asarray(f1, jnp.float32), jnp.asarray(exact_match, jnp.float32), jnp.asarray(total, jnp.int32)


def _squad_compute(f1: Array, exact_match: Array, total: Array) -> Dict[str, Array]:
    return {"exact_match": 100.0 * exact_match / total, "f1": 100.0 * f1 / total}


def squad(preds: PREDS_TYPE, target: TARGETS_TYPE) -> Dict[str, Array]:
    """SQuAD v1.1 exact-match and token-F1 (scores in percent).

    Example:
        >>> preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]
        >>> target = [{"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"}]
        >>> {k: float(v) for k, v in squad(preds, target).items()}
        {'exact_match': 100.0, 'f1': 100.0}
    """
    preds_dict, target_dict = _squad_input_check(preds, target)
    f1, exact_match, total = _squad_update(preds_dict, target_dict)
    return _squad_compute(f1, exact_match, total)

"""chrF / chrF++ (reference ``functional/text/chrf.py:1-635``).

Host side: char/word n-gram counting per sentence with best-matching-reference
selection (canonical chrF spec, https://github.com/m-popovic/chrF). Device
side: the accumulated statistics are six small ``(order,)`` count arrays with
``sum`` reduction, and the corpus F-beta over orders is one vectorized
expression instead of the reference's per-order dict loop
(``chrf.py:263-287``).
"""
from collections import Counter
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_PUNCTUATIONS = set("!\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~")
_EPS_SMOOTHING = 1e-16


def _characters_of(sentence: str, whitespace: bool) -> List[str]:
    if whitespace:
        return list(sentence)
    return list(sentence.strip().replace(" ", ""))


def _words_of(sentence: str) -> List[str]:
    """Whitespace words with leading/trailing punctuation split off."""
    out: List[str] = []
    for word in sentence.strip().split():
        if len(word) > 1 and word[-1] in _PUNCTUATIONS:
            out.extend((word[:-1], word[-1]))
        elif len(word) > 1 and word[0] in _PUNCTUATIONS:
            out.extend((word[0], word[1:]))
        else:
            out.append(word)
    return out


def _ngram_counters(items: List[str], max_order: int) -> List[Counter]:
    """One Counter per order 1..max_order."""
    counters = []
    for order in range(1, max_order + 1):
        counters.append(Counter(tuple(items[i : i + order]) for i in range(len(items) - order + 1)))
    return counters


def _sentence_stats(
    sentence: str, n_char_order: int, n_word_order: int, lowercase: bool, whitespace: bool
) -> Tuple[List[Counter], List[Counter]]:
    if lowercase:
        sentence = sentence.lower()
    return (
        _ngram_counters(_characters_of(sentence, whitespace), n_char_order),
        _ngram_counters(_words_of(sentence), n_word_order),
    )


def _matches(a: List[Counter], b: List[Counter]) -> np.ndarray:
    return np.asarray([sum((x & y).values()) for x, y in zip(a, b)], np.float32)


def _totals(counters: List[Counter]) -> np.ndarray:
    return np.asarray([sum(c.values()) for c in counters], np.float32)


def _fscore_from_counts(
    matching_char: Array, matching_word: Array,
    pred_char: Array, pred_word: Array,
    target_char: Array, target_word: Array,
    n_order: float, beta: float,
) -> Array:
    """Vectorized chrF F-beta: mean over all char+word orders (device math)."""
    matching = jnp.concatenate([jnp.atleast_1d(matching_char), jnp.atleast_1d(matching_word)])
    pred_tot = jnp.concatenate([jnp.atleast_1d(pred_char), jnp.atleast_1d(pred_word)])
    target_tot = jnp.concatenate([jnp.atleast_1d(target_char), jnp.atleast_1d(target_word)])
    precision = jnp.where(pred_tot > 0, matching / jnp.where(pred_tot > 0, pred_tot, 1.0), 0.0)
    recall = jnp.where(target_tot > 0, matching / jnp.where(target_tot > 0, target_tot, 1.0), 0.0)
    denom = jnp.maximum(beta**2 * precision + recall, _EPS_SMOOTHING)
    f_scores = (1 + beta**2) * precision * recall / denom
    return jnp.sum(f_scores) / n_order


def _chrf_score_update(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    n_char_order: int,
    n_word_order: int,
    beta: float,
    lowercase: bool,
    whitespace: bool,
    collect_sentence_scores: bool = False,
):
    """Accumulate corpus chrF statistics for a batch (host counting).

    For each hypothesis, every reference is scored and the best-matching
    reference's statistics enter the corpus totals (chrF spec).

    Returns six numpy count arrays (char/word × matching/pred/target) and an
    optional list of sentence-level scores.
    """
    if isinstance(preds, str):
        preds = [preds]
    target_corpus = [[tgt] if isinstance(tgt, str) else list(tgt) for tgt in target]
    if len(preds) != len(target_corpus):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target_corpus)}")

    n_order = float(n_char_order + n_word_order)
    matching_char = np.zeros(n_char_order, np.float32)
    matching_word = np.zeros(n_word_order, np.float32)
    pred_char = np.zeros(n_char_order, np.float32)
    pred_word = np.zeros(n_word_order, np.float32)
    target_char = np.zeros(n_char_order, np.float32)
    target_word = np.zeros(n_word_order, np.float32)
    sentence_scores: List[Array] = []

    for pred, refs in zip(preds, target_corpus):
        p_char, p_word = _sentence_stats(pred, n_char_order, n_word_order, lowercase, whitespace)
        p_char_tot, p_word_tot = _totals(p_char), _totals(p_word)
        pred_char += p_char_tot
        pred_word += p_word_tot

        # Zero-stat start + strict improvement, matching the reference
        # (``functional/text/chrf.py:332-364``): when every reference ties at
        # f==0, NO target/matching counts enter the corpus totals (the pred
        # counts above were already added unconditionally). Picking e.g. the
        # first reference instead inflates the recall denominator — found by
        # the text differential fuzz (round 5).
        best = (
            0.0,
            np.zeros(n_char_order, np.float32),
            np.zeros(n_word_order, np.float32),
            np.zeros(n_char_order, np.float32),
            np.zeros(n_word_order, np.float32),
        )
        for ref in refs:
            r_char, r_word = _sentence_stats(ref, n_char_order, n_word_order, lowercase, whitespace)
            m_char, m_word = _matches(p_char, r_char), _matches(p_word, r_word)
            t_char, t_word = _totals(r_char), _totals(r_word)
            f = float(
                _fscore_from_counts(
                    m_char, m_word, p_char_tot, p_word_tot, t_char, t_word, n_order, beta
                )
            )
            if f > best[0]:
                best = (f, m_char, m_word, t_char, t_word)

        f, m_char, m_word, t_char, t_word = best
        matching_char += m_char
        matching_word += m_word
        target_char += t_char
        target_word += t_word
        if collect_sentence_scores:
            sentence_scores.append(jnp.asarray([f], jnp.float32))

    return (
        matching_char, matching_word, pred_char, pred_word, target_char, target_word,
        sentence_scores if collect_sentence_scores else None,
    )


def chrf_score(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    n_char_order: int = 6,
    n_word_order: int = 2,
    beta: float = 2.0,
    lowercase: bool = False,
    whitespace: bool = False,
    return_sentence_level_score: bool = False,
):
    """chrF (``n_word_order=0``) / chrF++ (``n_word_order=2``, default) score.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat']]
        >>> round(float(chrf_score(preds, target)), 4)
        0.4942
    """
    if not isinstance(n_char_order, int) or n_char_order < 1:
        raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
    if not isinstance(n_word_order, int) or n_word_order < 0:
        raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
    if beta < 0:
        raise ValueError("Expected argument `beta` to be greater than 0.")

    m_char, m_word, p_char, p_word, t_char, t_word, sentence_scores = _chrf_score_update(
        preds, target, n_char_order, n_word_order, beta, lowercase, whitespace,
        collect_sentence_scores=return_sentence_level_score,
    )
    n_order = float(n_char_order + n_word_order)
    score = _fscore_from_counts(
        jnp.asarray(m_char), jnp.asarray(m_word), jnp.asarray(p_char), jnp.asarray(p_word),
        jnp.asarray(t_char), jnp.asarray(t_word), n_order, beta,
    )
    if return_sentence_level_score:
        return score, jnp.concatenate(sentence_scores) if sentence_scores else jnp.zeros(0)
    return score

"""Word information lost (reference ``functional/text/wil.py:22-93``).

Uses the reference's hit approximation ``hits = Σ max(|pred|,|tgt|) − Σ edits``
(stored negated, as ``errors − total``), so WIL/WIP match it exactly.
"""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _edit_distances, _tokenize_words

Array = jax.Array


def _wil_update(
    preds: Union[str, List[str]], target: Union[str, List[str]]
) -> Tuple[Array, Array, Array]:
    """Returns (edits − max-len total, total target words, total pred words)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    distances, pred_lens, target_lens = _edit_distances(preds, target, _tokenize_words)
    total = jnp.maximum(pred_lens, target_lens).sum()
    errors = distances.sum() - total
    return (
        errors.astype(jnp.float32),
        target_lens.sum().astype(jnp.float32),
        pred_lens.sum().astype(jnp.float32),
    )


def _wil_compute(errors: Array, target_total: Array, preds_total: Array) -> Array:
    return 1 - ((errors / target_total) * (errors / preds_total))


def word_information_lost(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word information lost (lower is better).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> round(float(word_information_lost(preds, target)), 4)
        0.6528
    """
    errors, target_total, preds_total = _wil_update(preds, target)
    return _wil_compute(errors, target_total, preds_total)

"""Char error rate (reference ``functional/text/cer.py:23-83``)."""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _edit_distances, _tokenize_chars

Array = jax.Array


def _cer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Summed char-level edit operations and total reference chars."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    distances, _, target_lens = _edit_distances(preds, target, _tokenize_chars)
    return distances.sum().astype(jnp.float32), target_lens.sum().astype(jnp.float32)


def _cer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def char_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Character error rate over reference characters (lower is better).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> round(float(char_error_rate(preds=preds, target=target)), 4)
        0.3415
    """
    errors, total = _cer_update(preds, target)
    return _cer_compute(errors, total)

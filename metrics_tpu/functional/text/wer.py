"""Word error rate (reference ``functional/text/wer.py:23-81``).

Tokenization is host work; the edit-distance DP runs on device as a batched
wavefront scan (``helper._batched_edit_distance``) instead of the reference's
per-pair Python loop.
"""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _edit_distances, _tokenize_words

Array = jax.Array


def _wer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Summed edit operations and total reference words for a batch."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    distances, _, target_lens = _edit_distances(preds, target, _tokenize_words)
    return distances.sum().astype(jnp.float32), target_lens.sum().astype(jnp.float32)


def _wer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def word_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word error rate: edit operations per reference word (lower is better).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> float(word_error_rate(preds=preds, target=target))
        0.5
    """
    errors, total = _wer_update(preds, target)
    return _wer_compute(errors, total)

"""Match error rate (reference ``functional/text/mer.py:23-88``)."""
from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _edit_distances, _tokenize_words

Array = jax.Array


def _mer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Summed edit operations and total = Σ max(|pred|, |target|)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    distances, pred_lens, target_lens = _edit_distances(preds, target, _tokenize_words)
    total = jnp.maximum(pred_lens, target_lens).sum()
    return distances.sum().astype(jnp.float32), total.astype(jnp.float32)


def _mer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def match_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Match error rate: edits per aligned word slot (lower is better).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> round(float(match_error_rate(preds=preds, target=target)), 4)
        0.4444
    """
    errors, total = _mer_update(preds, target)
    return _mer_compute(errors, total)

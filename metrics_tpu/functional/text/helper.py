"""Shared text machinery: host tokenization + a device edit-distance kernel.

The reference computes Levenshtein distances with a per-pair Python DP loop on
the host (``/root/reference/src/torchmetrics/functional/text/helper.py`` —
``_edit_distance`` and the cached ``_LevenshteinEditDistance`` used by TER).
Here the DP runs **on device** as an anti-diagonal wavefront: a single
``lax.scan`` over the ``M+N`` anti-diagonals of the DP table, each scan step a
vectorized elementwise min over one diagonal, ``vmap``-ped over the batch of
sentence pairs. Strings are tokenized host-side into padded int32 id arrays
(strings cannot live on a TPU); everything after that is XLA.

Shapes are bucketed to powers of two so jit recompiles O(log max_len) times,
not once per sentence length.
"""
from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Array = jax.Array

_BIG = np.int32(1 << 30)


def _bucket(n: int, minimum: int = 8) -> int:
    """Round up to a power of two to bound jit recompilation."""
    size = minimum
    while size < n:
        size *= 2
    return size


def _levenshtein_diag(a: Array, a_len: Array, b: Array, b_len: Array) -> Array:
    """Edit distance between two padded id sequences via wavefront DP.

    ``D[i, j]`` (cost of turning ``a[:i]`` into ``b[:j]``) is computed one
    anti-diagonal ``k = i + j`` at a time; diagonal ``k`` depends only on
    diagonals ``k-1`` and ``k-2`` elementwise, so each step is vector math on
    the MXU-adjacent VPU rather than a scalar host loop. A cell ``(i, j)``
    only ever depends on cells with smaller-or-equal ``i`` and ``j``, so the
    pad region beyond ``(a_len, b_len)`` cannot pollute the answer.
    """
    m = a.shape[0]
    n = b.shape[0]
    idx = jnp.arange(m + 1, dtype=jnp.int32)

    # diag k=0: D[0,0]=0; diag k=1: D[0,1]=D[1,0]=1
    d_km2 = jnp.where(idx == 0, 0, _BIG).astype(jnp.int32)
    d_km1 = jnp.where(idx <= 1, 1, _BIG).astype(jnp.int32)

    def step(carry, k):
        d1, d2 = carry  # diagonals k-1 and k-2
        a_i = jnp.take(a, idx - 1, mode="clip")      # a[i-1]
        b_j = jnp.take(b, k - idx - 1, mode="clip")  # b[j-1], j = k - i
        shifted_d1 = jnp.roll(d1, 1).at[0].set(_BIG)
        shifted_d2 = jnp.roll(d2, 1).at[0].set(_BIG)
        substitute = shifted_d2 + jnp.where(a_i == b_j, 0, 1)
        insert = d1 + 1          # D[i, j-1] + 1
        delete = shifted_d1 + 1  # D[i-1, j] + 1
        d = jnp.minimum(substitute, jnp.minimum(insert, delete))
        d = jnp.where(idx == 0, k, d)  # D[0, k] = k
        d = jnp.where(idx == k, k, d)  # D[k, 0] = k (no-op once k > m)
        valid = (k - idx >= 0) & (k - idx <= n)
        d = jnp.where(valid, d, _BIG)
        return (d, d1), d[a_len]  # D[a_len, k - a_len]; the answer when k = a_len + b_len

    (_, _), taps = lax.scan(step, (d_km1, d_km2), jnp.arange(2, m + n + 1, dtype=jnp.int32))
    total = a_len + b_len
    return jnp.where(total <= 1, total, taps[jnp.maximum(total - 2, 0)]).astype(jnp.int32)


@jax.jit
def _batched_edit_distance(
    pred_ids: Array, pred_len: Array, target_ids: Array, target_len: Array
) -> Array:
    """Per-pair Levenshtein distances for a batch of padded id sequences."""
    return jax.vmap(_levenshtein_diag)(pred_ids, pred_len, target_ids, target_len)


def _encode_batch(
    token_lists_a: Sequence[Sequence[str]], token_lists_b: Sequence[Sequence[str]]
) -> Tuple[Array, Array, Array, Array]:
    """Map two token batches onto one shared integer vocabulary, padded.

    The vocabulary is throwaway (ids only need to agree within the batch);
    lengths are bucketed to powers of two so the device kernel compiles a
    bounded number of shapes.
    """
    vocab: dict = {}

    def ids_of(tokens: Sequence[str]) -> List[int]:
        out = []
        for tok in tokens:
            if tok not in vocab:
                vocab[tok] = len(vocab)
            out.append(vocab[tok])
        return out

    a_ids = [ids_of(t) for t in token_lists_a]
    b_ids = [ids_of(t) for t in token_lists_b]
    max_a = _bucket(max((len(x) for x in a_ids), default=1))
    max_b = _bucket(max((len(x) for x in b_ids), default=1))
    batch = len(a_ids)
    a_arr = np.full((batch, max_a), -1, np.int32)
    b_arr = np.full((batch, max_b), -2, np.int32)  # distinct pad ids: pads never match
    for row, ids in enumerate(a_ids):
        a_arr[row, : len(ids)] = ids
    for row, ids in enumerate(b_ids):
        b_arr[row, : len(ids)] = ids
    a_len = np.asarray([len(x) for x in a_ids], np.int32)
    b_len = np.asarray([len(x) for x in b_ids], np.int32)
    return jnp.asarray(a_arr), jnp.asarray(a_len), jnp.asarray(b_arr), jnp.asarray(b_len)


def _edit_distances(
    preds: Sequence[str],
    target: Sequence[str],
    tokenize: Callable[[str], Sequence[str]],
) -> Tuple[Array, Array, Array]:
    """Host tokenization → device batched DP.

    Returns per-pair ``(distances, pred_lens, target_lens)`` as device arrays.
    """
    pred_tokens = [list(tokenize(p)) for p in preds]
    target_tokens = [list(tokenize(t)) for t in target]
    a_arr, a_len, b_arr, b_len = _encode_batch(pred_tokens, target_tokens)
    return _batched_edit_distance(a_arr, a_len, b_arr, b_len), a_len, b_len


def _tokenize_words(sentence: str) -> Sequence[str]:
    return sentence.split()


def _tokenize_chars(sentence: str) -> Sequence[str]:
    return list(sentence)

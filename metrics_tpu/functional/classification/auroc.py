"""AUROC kernels (reference
``src/torchmetrics/functional/classification/auroc.py``, 269 LoC).
"""
import warnings
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.roc import roc
from metrics_tpu.utilities.checks import _input_format_classification
from metrics_tpu.utilities.compute import _auc_compute_without_check
from metrics_tpu.utilities.data import _bincount
from metrics_tpu.utilities.enums import AverageMethod, DataType

Array = jax.Array


def _auroc_update(preds: Array, target: Array) -> Tuple[Array, Array, DataType]:
    """Validate inputs and flatten multi-dim layouts (reference ``auroc.py:28-49``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _, _, mode = _input_format_classification(preds, target)

    if mode == DataType.MULTIDIM_MULTICLASS and preds.ndim == target.ndim + 1:
        n_classes = preds.shape[1]
        preds = jnp.moveaxis(preds, 0, 1).reshape(n_classes, -1).T
        target = target.reshape(-1)
    if mode == DataType.MULTILABEL and preds.ndim > 2:
        n_classes = preds.shape[1]
        preds = jnp.moveaxis(preds, 0, 1).reshape(n_classes, -1).T
        target = jnp.moveaxis(target, 0, 1).reshape(n_classes, -1).T

    return preds, target, mode


def _auroc_compute(
    preds: Array,
    target: Array,
    mode: DataType,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    max_fpr: Optional[float] = None,
    sample_weights: Optional[Sequence] = None,
) -> Array:
    """Reference ``auroc.py:53-195``."""
    if mode == DataType.BINARY:
        num_classes = 1

    if max_fpr is not None:
        if not isinstance(max_fpr, float) or not 0 < max_fpr <= 1:
            raise ValueError(f"`max_fpr` should be a float in range (0, 1], got: {max_fpr}")
        if mode != DataType.BINARY:
            raise ValueError(
                "Partial AUC computation not available in multilabel/multiclass setting,"
                f" 'max_fpr' must be set to `None`, received `{max_fpr}`."
            )

    if mode == DataType.MULTILABEL:
        if average == AverageMethod.MICRO:
            fpr, tpr, _ = roc(preds.reshape(-1), target.reshape(-1), 1, pos_label, sample_weights)
        elif num_classes:
            output = [
                roc(preds[:, i], target[:, i], num_classes=1, pos_label=1, sample_weights=sample_weights)
                for i in range(num_classes)
            ]
            fpr = [o[0] for o in output]
            tpr = [o[1] for o in output]
        else:
            raise ValueError("Detected input to be `multilabel` but you did not provide `num_classes` argument")
    else:
        if mode != DataType.BINARY:
            if num_classes is None:
                raise ValueError("Detected input to `multiclass` but you did not provide `num_classes` argument")
            if average == AverageMethod.WEIGHTED and len(jnp.unique(target)) < num_classes:
                # classes with 0 observations are excluded (weight would be 0)
                target_bool_mat = jax.nn.one_hot(target, num_classes, dtype=jnp.bool_)
                class_observed = target_bool_mat.sum(axis=0) > 0
                for c in range(num_classes):
                    if not bool(class_observed[c]):
                        warnings.warn(f"Class {c} had 0 observations, omitted from AUROC calculation", UserWarning)
                preds = preds[:, class_observed]
                target_bool_mat = target_bool_mat[:, class_observed]
                target = jnp.nonzero(target_bool_mat)[1]
                num_classes = int(class_observed.sum())
                if num_classes == 1:
                    raise ValueError("Found 1 non-empty class in `multiclass` AUROC calculation")
        fpr, tpr, _ = roc(preds, target, num_classes, pos_label, sample_weights)

    if max_fpr is None or max_fpr == 1:
        if mode == DataType.MULTILABEL and average == AverageMethod.MICRO:
            pass
        elif num_classes != 1:
            auc_scores = [_auc_compute_without_check(x, y, 1.0) for x, y in zip(fpr, tpr)]
            if average == AverageMethod.NONE:
                return jnp.stack(auc_scores)
            if average == AverageMethod.MACRO:
                return jnp.mean(jnp.stack(auc_scores))
            if average == AverageMethod.WEIGHTED:
                if mode == DataType.MULTILABEL:
                    support = jnp.sum(target, axis=0)
                else:
                    support = _bincount(target.reshape(-1), minlength=num_classes)
                return jnp.sum(jnp.stack(auc_scores) * support / support.sum())
            allowed_average = (AverageMethod.NONE.value, AverageMethod.MACRO.value, AverageMethod.WEIGHTED.value)
            raise ValueError(
                f"Argument `average` expected to be one of the following: {allowed_average} but got {average}"
            )
        return _auc_compute_without_check(fpr, tpr, 1.0)

    # partial AUC over [0, max_fpr] with McClish correction (reference ``:179-195``)
    max_area = jnp.asarray(max_fpr, jnp.float32)
    stop = int(jnp.searchsorted(fpr, max_area, side="right"))
    weight = (max_area - fpr[stop - 1]) / (fpr[stop] - fpr[stop - 1])
    interp_tpr = tpr[stop - 1] + weight * (tpr[stop] - tpr[stop - 1])
    tpr = jnp.concatenate([tpr[:stop], interp_tpr.reshape(1)])
    fpr = jnp.concatenate([fpr[:stop], max_area.reshape(1)])
    partial_auc = _auc_compute_without_check(fpr, tpr, 1.0)
    min_area = 0.5 * max_area**2
    return 0.5 * (1 + (partial_auc - min_area) / (max_area - min_area))


def auroc(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    max_fpr: Optional[float] = None,
    sample_weights: Optional[Sequence] = None,
) -> Array:
    """Area under the ROC curve (reference ``auroc.py:198-269``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([0.13, 0.26, 0.08, 0.19, 0.34])
        >>> target = jnp.array([0, 0, 1, 1, 1])
        >>> auroc(preds, target, pos_label=1)
        Array(0.5, dtype=float32)
    """
    preds, target, mode = _auroc_update(preds, target)
    return _auroc_compute(preds, target, mode, num_classes, pos_label, average, max_fpr, sample_weights)


# --------------------------------------------------------------------------
# Masked (static-shape) AUROC — the jittable compute for CatBuffer states
# --------------------------------------------------------------------------


def _binary_auroc_masked(preds: Array, target: Array, mask: Array) -> Array:
    """AUROC of the rows where ``mask`` is True, as the tie-averaged rank
    statistic (Mann-Whitney U) — exactly the trapezoidal ROC area the eager
    kernel computes, but with static shapes: one sort + two searchsorteds,
    no data-dependent thresholds. Designed for :class:`CatBuffer` states
    (padding rows are zero-weight).
    """
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target)
    mask = jnp.asarray(mask, bool)
    pos = mask & (target == 1)
    neg = mask & (target != 1)
    n_pos = jnp.sum(pos.astype(jnp.float32))
    n_neg = jnp.sum(neg.astype(jnp.float32))
    # negatives sorted with padding pushed to +inf (never counted as "less");
    # the <= count is capped at the true negative total so a legitimate +inf
    # prediction doesn't absorb the padding sentinel as ties
    neg_sorted = jnp.sort(jnp.where(neg, preds, jnp.inf))
    less = jnp.searchsorted(neg_sorted, preds, side="left").astype(jnp.float32)
    leq = jnp.minimum(jnp.searchsorted(neg_sorted, preds, side="right").astype(jnp.float32), n_neg)
    u = jnp.sum(jnp.where(pos, less + 0.5 * (leq - less), 0.0))
    return u / (n_pos * n_neg)


def _multiclass_auroc_masked(
    preds: Array,
    target: Array,
    mask: Array,
    num_classes: int,
    average: Optional[str] = "macro",
) -> Array:
    """One-vs-rest masked AUROC over a ``(cap, C)`` score buffer."""
    per_class = jax.vmap(
        lambda c: _binary_auroc_masked(preds[:, c], (target == c).astype(jnp.int32), mask)
    )(jnp.arange(num_classes))
    if average in (AverageMethod.NONE, "none", None):
        return per_class
    # classes absent from the buffer (no positives or no negatives) are NaN
    # (0/0); averages are taken over the defined classes only
    counts = jax.vmap(lambda c: jnp.sum((mask & (target == c)).astype(jnp.float32)))(jnp.arange(num_classes))
    n_valid = jnp.sum(mask.astype(jnp.float32))
    defined = (counts > 0) & (counts < n_valid)
    safe = jnp.where(defined, per_class, 0.0)
    if average == AverageMethod.MACRO:
        return jnp.sum(safe) / jnp.sum(defined.astype(jnp.float32))
    if average == AverageMethod.WEIGHTED:
        weights = jnp.where(defined, counts, 0.0)
        return jnp.sum(safe * weights / jnp.sum(weights))
    raise ValueError(f"Average {average!r} is not supported in masked AUROC")

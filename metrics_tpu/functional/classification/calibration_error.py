"""Calibration error kernels (reference
``src/torchmetrics/functional/classification/calibration_error.py``, 212 LoC).

TPU-first: binning is a ``segment_sum`` with static ``n_bins`` (the
reference's ``torch.bucketize`` + ``scatter_add_``, ``:51-80``) — one fused
deterministic reduction; the pre-1.6 Python bin loop has no analogue here.
The "are these probabilities?" re-normalization check is computed in-graph
with ``where`` so the kernel stays jittable.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _input_format_classification
from metrics_tpu.utilities.enums import DataType

Array = jax.Array


def _ce_compute(
    confidences: Array,
    accuracies: Array,
    bin_boundaries: Array,
    norm: str = "l1",
    debias: bool = False,
) -> Array:
    """Reference ``calibration_error.py:83-126`` — bins the samples and
    delegates to :func:`_ce_compute_from_bins` (one copy of the CE math)."""
    n_bins = bin_boundaries.shape[0] - 1
    count, conf_sum, acc_sum = _ce_bin_update(
        confidences, accuracies, n_bins, boundaries=bin_boundaries
    )
    return _ce_compute_from_bins(count, conf_sum, acc_sum, norm=norm, debias=debias)


def _ce_bin_update(
    confidences: Array, accuracies: Array, n_bins: int, valid: Array = None, boundaries: Array = None
) -> Tuple[Array, Array, Array]:
    """Fold a batch of (confidence, accuracy) pairs into static ``(n_bins,)``
    count/confidence-sum/accuracy-sum counters.

    The binned formulation of the reference's cat-list accumulation
    (``calibration_error.py:49-50``): since ``_ce_compute`` only ever needs
    per-bin sums, the counters are EXACT — not an approximation — while
    being constant-memory, jittable, and shardable (all three are plain
    ``sum`` states). Both the cat-list path (:func:`_ce_compute`) and the
    binned metric state flow through this one binning, so their indexing
    can never diverge.

    ``valid`` optionally masks rows (the SPMD ragged-batch contract shared
    with the CatBuffer metrics).
    """
    if boundaries is None:
        boundaries = jnp.linspace(0, 1, n_bins + 1, dtype=jnp.float32)
    indices = jnp.clip(jnp.searchsorted(boundaries, confidences, side="left") - 1, 0, n_bins - 1)
    weight = jnp.ones_like(confidences) if valid is None else jnp.asarray(valid, confidences.dtype)
    count = jax.ops.segment_sum(weight, indices, num_segments=n_bins)
    conf = jax.ops.segment_sum(confidences * weight, indices, num_segments=n_bins)
    acc = jax.ops.segment_sum(accuracies * weight, indices, num_segments=n_bins)
    return count, conf, acc


def _ce_compute_from_bins(
    count_bin: Array, conf_sum_bin: Array, acc_sum_bin: Array, norm: str = "l1", debias: bool = False
) -> Array:
    """The CE math from pre-accumulated per-bin sums (reference
    ``calibration_error.py:83-126``) — the single copy both the cat-list
    path (via :func:`_ce_compute`) and the binned metric state consume."""
    if norm not in {"l1", "l2", "max"}:
        raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")
    safe = jnp.where(count_bin == 0, 1.0, count_bin)
    conf_bin = jnp.where(count_bin == 0, 0.0, conf_sum_bin / safe)
    acc_bin = jnp.where(count_bin == 0, 0.0, acc_sum_bin / safe)
    prop_bin = count_bin / count_bin.sum()
    if norm == "l1":
        return jnp.sum(jnp.abs(acc_bin - conf_bin) * prop_bin)
    if norm == "max":
        return jnp.max(jnp.abs(acc_bin - conf_bin))
    ce = jnp.sum((acc_bin - conf_bin) ** 2 * prop_bin)
    if debias:
        # reference ``:109-112``: Nadeau-style bias correction on the l2 term
        n_total = count_bin.sum()
        debias_bins = (acc_bin * (acc_bin - 1) * prop_bin) / (prop_bin * n_total - 1)
        ce = ce + jnp.sum(jnp.nan_to_num(debias_bins))
    return jnp.where(ce > 0, jnp.sqrt(jnp.clip(ce, 0)), 0.0)


def _ce_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Top-1 confidences and correctness (reference ``calibration_error.py:129-167``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _, _, mode = _input_format_classification(preds, target)

    if mode == DataType.BINARY:
        in01 = jnp.all((preds >= 0) & (preds <= 1))
        preds = jnp.where(in01, preds, jax.nn.sigmoid(preds))
        confidences, accuracies = preds, target
    elif mode == DataType.MULTICLASS:
        in01 = jnp.all((preds >= 0) & (preds <= 1))
        preds = jnp.where(in01, preds, jax.nn.softmax(preds, axis=1))
        confidences = preds.max(axis=1)
        accuracies = preds.argmax(axis=1) == target
    elif mode == DataType.MULTIDIM_MULTICLASS:
        flat = jnp.moveaxis(preds, 1, -1).reshape(-1, preds.shape[1])
        confidences = flat.max(axis=1)
        accuracies = flat.argmax(axis=1) == target.reshape(-1)
    else:
        raise ValueError(
            f"Calibration error is not well-defined for data with size {preds.shape} and targets {target.shape}."
        )
    return confidences.astype(jnp.float32), accuracies.astype(jnp.float32)


def calibration_error(preds: Array, target: Array, n_bins: int = 15, norm: str = "l1") -> Array:
    """Top-label calibration error (reference ``calibration_error.py:170-212``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([0.25, 0.25, 0.55, 0.75, 0.75])
        >>> target = jnp.array([0, 0, 1, 1, 1])
        >>> print(f"{calibration_error(preds, target, n_bins=2, norm='l1'):.3f}")
        0.290
    """
    if norm not in ("l1", "l2", "max"):
        raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")
    if not isinstance(n_bins, int) or n_bins <= 0:
        raise ValueError(f"Expected argument `n_bins` to be a int larger than 0 but got {n_bins}")

    confidences, accuracies = _ce_update(preds, target)
    bin_boundaries = jnp.linspace(0, 1, n_bins + 1, dtype=jnp.float32)
    return _ce_compute(confidences, accuracies, bin_boundaries, norm=norm)

"""Multilabel ranking kernels (reference
``src/torchmetrics/functional/classification/ranking.py``, 242 LoC).

TPU-first: the reference's per-sample Python loop in label ranking average
precision (``ranking.py:122-135``) is replaced by a broadcast pairwise
comparison — ``rank(x_i in S) = #{j in S : x_j <= x_i}``, the max-rank tie
rule of the reference's ``_rank_data`` (``ranking.py:20-26``) — one
``(N, L, L)`` fused reduction, fully jittable.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.ops import ascending_ranks

Array = jax.Array


def _check_ranking_input(preds: Array, target: Array, sample_weight: Optional[Array] = None) -> None:
    """Reference ``ranking.py:29-43``."""
    if preds.ndim != 2 or target.ndim != 2:
        raise ValueError(
            "Expected both predictions and target to matrices of shape `[N,C]`"
            f" but got {preds.ndim} and {target.ndim}"
        )
    if preds.shape != target.shape:
        raise ValueError("Expected both predictions and target to have same shape")
    if sample_weight is not None:
        if sample_weight.ndim != 1 or sample_weight.shape[0] != preds.shape[0]:
            raise ValueError(
                "Expected sample weights to be 1 dimensional and have same size"
                f" as the first dimension of preds and target but got {sample_weight.shape}"
            )


def _coverage_error_update(
    preds: Array, target: Array, sample_weight: Optional[Array] = None
) -> Tuple[Array, int, Optional[Array]]:
    """Reference ``ranking.py:46-66``."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if sample_weight is not None:
        sample_weight = jnp.asarray(sample_weight)
    _check_ranking_input(preds, target, sample_weight)
    offset = jnp.where(target == 0, jnp.abs(preds.min()) + 10, 0.0)
    preds_mod = preds + offset
    preds_min = preds_mod.min(axis=1)
    coverage = jnp.sum(preds >= preds_min[:, None], axis=1).astype(jnp.float32)
    if sample_weight is not None:
        coverage = coverage * sample_weight
        sample_weight = sample_weight.sum()
    return coverage.sum(), coverage.size, sample_weight


def _coverage_error_compute(coverage: Array, n_elements: int, sample_weight: Optional[Array] = None) -> Array:
    """Reference ``ranking.py:69-72``."""
    if sample_weight is not None and sample_weight != 0.0:
        return coverage / sample_weight
    return coverage / n_elements


def coverage_error(preds: Array, target: Array, sample_weight: Optional[Array] = None) -> Array:
    """Multilabel coverage error (reference ``ranking.py:75-103``)."""
    coverage, n_elements, sample_weight = _coverage_error_update(preds, target, sample_weight)
    return _coverage_error_compute(coverage, n_elements, sample_weight)


def _label_ranking_average_precision_update(
    preds: Array, target: Array, sample_weight: Optional[Array] = None
) -> Tuple[Array, int, Optional[Array]]:
    """Vectorized LRAP accumulation (reference ``ranking.py:106-135``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if sample_weight is not None:
        sample_weight = jnp.asarray(sample_weight)
    _check_ranking_input(preds, target, sample_weight)
    neg_preds = -preds
    n_preds, n_labels = neg_preds.shape
    relevant = target == 1

    # pairwise <= comparisons give max-ranks in one shot
    le = neg_preds[:, None, :] <= neg_preds[:, :, None]  # (N, i, j): x_j <= x_i
    rank_all = jnp.sum(le, axis=2)  # rank among all labels
    rank_rel = jnp.sum(le & relevant[:, None, :], axis=2)  # rank among relevant labels

    n_rel = relevant.sum(axis=1)
    ratio = jnp.where(relevant, rank_rel / rank_all, 0.0)
    score_rows = jnp.where(
        (n_rel > 0) & (n_rel < n_labels),
        ratio.sum(axis=1) / jnp.maximum(n_rel, 1),
        1.0,
    )
    if sample_weight is not None:
        score_rows = score_rows * sample_weight
        sample_weight = sample_weight.sum()
    return score_rows.sum(), n_preds, sample_weight


def _label_ranking_average_precision_compute(
    score: Array, n_elements: int, sample_weight: Optional[Array] = None
) -> Array:
    """Reference ``ranking.py:138-143``."""
    if sample_weight is not None and sample_weight != 0.0:
        return score / sample_weight
    return score / n_elements


def label_ranking_average_precision(preds: Array, target: Array, sample_weight: Optional[Array] = None) -> Array:
    """Label ranking average precision (reference ``ranking.py:146-174``)."""
    score, n_elements, sample_weight = _label_ranking_average_precision_update(preds, target, sample_weight)
    return _label_ranking_average_precision_compute(score, n_elements, sample_weight)


def _label_ranking_loss_update(
    preds: Array, target: Array, sample_weight: Optional[Array] = None
) -> Tuple[Array, int, Optional[Array]]:
    """Mask-based label ranking loss (reference ``ranking.py:177-210``);
    the reference's row-dropping is a ``where`` mask here (static shapes)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if sample_weight is not None:
        sample_weight = jnp.asarray(sample_weight)
    _check_ranking_input(preds, target, sample_weight)
    n_preds, n_labels = preds.shape
    relevant = target == 1
    n_relevant = relevant.sum(axis=1)
    mask = (n_relevant > 0) & (n_relevant < n_labels)

    inverse = jax.vmap(ascending_ranks)(preds)  # argsort(argsort(...)) via packed radix
    per_label_loss = ((n_labels - inverse) * relevant).astype(jnp.float32)
    correction = 0.5 * n_relevant * (n_relevant + 1)
    denom = n_relevant * (n_labels - n_relevant)
    loss = (per_label_loss.sum(axis=1) - correction) / jnp.maximum(denom, 1)
    loss = jnp.where(mask, loss, 0.0)
    if sample_weight is not None:
        loss = loss * sample_weight
        sample_weight = sample_weight.sum()
    return loss.sum(), n_preds, sample_weight


def _label_ranking_loss_compute(loss: Array, n_elements: int, sample_weight: Optional[Array] = None) -> Array:
    """Reference ``ranking.py:213-217``."""
    if sample_weight is not None and sample_weight != 0.0:
        return loss / sample_weight
    return loss / n_elements


def label_ranking_loss(preds: Array, target: Array, sample_weight: Optional[Array] = None) -> Array:
    """Label ranking loss (reference ``ranking.py:220-242``)."""
    loss, n_elements, sample_weight = _label_ranking_loss_update(preds, target, sample_weight)
    return _label_ranking_loss_compute(loss, n_elements, sample_weight)

"""AUC kernel (reference
``src/torchmetrics/functional/classification/auc.py``, 133 LoC).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.compute import _auc_compute

Array = jax.Array


def _auc_update(x: Array, y: Array) -> Tuple[Array, Array]:
    """Shape checks (reference ``auc.py:20-40``)."""
    if x.ndim > 1:
        x = x.squeeze()
    if y.ndim > 1:
        y = y.squeeze()
    if x.ndim > 1 or y.ndim > 1:
        raise ValueError(f"Expected both `x` and `y` tensor to be 1d, but got tensors with dimension {x.ndim} and {y.ndim}")
    if x.shape != y.shape:
        raise ValueError(f"Expected the same shape for `x` and `y` tensors, but got {x.shape} and {y.shape}")
    return x, y


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Area under the curve via the trapezoidal rule (reference ``auc.py:112-133``).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([0, 1, 2, 3])
        >>> y = jnp.array([0, 1, 2, 2])
        >>> auc(x, y)
        Array(4., dtype=float32)
    """
    x, y = _auc_update(jnp.asarray(x), jnp.asarray(y))
    return _auc_compute(x.astype(jnp.float32), y.astype(jnp.float32), reorder=reorder)

"""AUC kernel (reference
``src/torchmetrics/functional/classification/auc.py``, 133 LoC).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.ops import ascending_order
from metrics_tpu.utilities.compute import _auc_compute

Array = jax.Array


def _auc_update(x: Array, y: Array) -> Tuple[Array, Array]:
    """Shape checks (reference ``auc.py:20-40``)."""
    if x.ndim > 1:
        x = x.squeeze()
    if y.ndim > 1:
        y = y.squeeze()
    if x.ndim > 1 or y.ndim > 1:
        raise ValueError(f"Expected both `x` and `y` tensor to be 1d, but got tensors with dimension {x.ndim} and {y.ndim}")
    if x.shape != y.shape:
        raise ValueError(f"Expected the same shape for `x` and `y` tensors, but got {x.shape} and {y.shape}")
    return x, y


def _auc_compute_masked(x: Array, y: Array, mask: Array, reorder: bool = False) -> Array:
    """Trapezoidal AUC over the rows where ``mask`` is True — the
    static-shape (CatBuffer) form of ``_auc_compute``.

    Invalid rows are compacted to the tail by a stable argsort (on ``x``
    when ``reorder``, else on insertion position), and trapezoid segments
    touching an invalid endpoint contribute zero — identical to running the
    dense kernel on just the valid rows, but with fixed shapes so the whole
    thing jits/shards.
    """
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    mask = jnp.asarray(mask, bool)
    n = x.shape[0]
    if reorder:
        key = jnp.where(mask, x, jnp.inf)
    else:
        key = jnp.where(mask, jnp.arange(n, dtype=jnp.float32), jnp.inf)
    order = ascending_order(key)
    x_s, y_s, m_s = x[order], y[order], mask[order]
    valid_pair = m_s[:-1] & m_s[1:]
    dx = jnp.where(valid_pair, jnp.diff(x_s), 0.0)
    area = jnp.sum(jnp.where(valid_pair, (y_s[:-1] + y_s[1:]) * dx / 2.0, 0.0))
    if reorder:
        return area
    # direction check on the valid pairs only (invalid dx is 0 → neutral)
    sign = jnp.where(jnp.all(dx >= 0), 1.0, jnp.where(jnp.all(dx <= 0), -1.0, jnp.nan))
    return area * sign


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Area under the curve via the trapezoidal rule (reference ``auc.py:112-133``).

    Example:
        >>> import jax.numpy as jnp
        >>> x = jnp.array([0, 1, 2, 3])
        >>> y = jnp.array([0, 1, 2, 2])
        >>> auc(x, y)
        Array(4., dtype=float32)
    """
    x, y = _auc_update(jnp.asarray(x), jnp.asarray(y))
    return _auc_compute(x.astype(jnp.float32), y.astype(jnp.float32), reorder=reorder)

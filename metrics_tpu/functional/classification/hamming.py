"""Hamming distance kernel (reference
``src/torchmetrics/functional/classification/hamming.py``, 96 LoC).
"""
from typing import Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _input_format_classification

Array = jax.Array


def _hamming_distance_update(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
) -> Tuple[Array, int]:
    """Count positions where prediction equals target (reference ``hamming.py:23-42``)."""
    preds, target, _ = _input_format_classification(preds, target, threshold=threshold)
    correct = jnp.sum(preds == target).astype(jnp.int32)
    total = preds.size
    return correct, total


def _hamming_distance_compute(correct: Array, total: Union[int, Array]) -> Array:
    """Reference ``hamming.py:45-60``."""
    return 1 - correct.astype(jnp.float32) / total


def hamming_distance(preds: Array, target: Array, threshold: float = 0.5) -> Array:
    """Average Hamming loss (reference ``hamming.py:63-96``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([[0, 1], [1, 1]])
        >>> preds = jnp.array([[0, 1], [0, 1]])
        >>> hamming_distance(preds, target)
        Array(0.25, dtype=float32)
    """
    correct, total = _hamming_distance_update(preds, target, threshold)
    return _hamming_distance_compute(correct, total)

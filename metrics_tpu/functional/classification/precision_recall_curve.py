"""Precision-recall curve kernels (reference
``src/torchmetrics/functional/classification/precision_recall_curve.py``, 331 LoC).

Curve metrics have inherently data-dependent output shapes (one point per
distinct score), so these kernels run **eagerly** through XLA ops on concrete
arrays — they are the exact-curve complement to the static-shape binned
variants in ``binned_precision_recall.py`` (which are fully jittable and the
recommended form inside compiled TPU code; SURVEY.md §7 step 3).
"""
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.masked_common import masked_curve_prologue
from metrics_tpu.ops import descending_order, partition_order
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _binary_clf_curve(
    preds: Array,
    target: Array,
    sample_weights: Optional[Sequence] = None,
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """Cumulative fps/tps per distinct threshold, sklearn-style
    (reference ``precision_recall_curve.py:23-61``)."""
    if sample_weights is not None and not isinstance(sample_weights, jax.Array):
        sample_weights = jnp.asarray(sample_weights, jnp.float32)

    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim > target.ndim:
        preds = preds[:, 0]
    # bucketed-rank kernel: bit-identical permutation to jnp.argsort(-preds)
    # at a fraction of the variadic-sort cost (ops/bucketed_rank.py)
    desc_score_indices = descending_order(preds)

    preds = preds[desc_score_indices]
    target = target[desc_score_indices]

    weight = sample_weights[desc_score_indices] if sample_weights is not None else 1.0

    # indices of distinct prediction values (+ the end of the curve)
    distinct_value_indices = jnp.nonzero(preds[1:] - preds[:-1])[0]
    threshold_idxs = jnp.concatenate([distinct_value_indices, jnp.array([target.shape[0] - 1])])
    target = (target == pos_label).astype(jnp.int32)
    tps = jnp.cumsum(target * weight, axis=0)[threshold_idxs]

    if sample_weights is not None:
        fps = jnp.cumsum((1 - target) * weight, axis=0)[threshold_idxs]
    else:
        fps = 1 + threshold_idxs - tps

    return fps, tps, preds[threshold_idxs]


def _precision_recall_curve_update(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
) -> Tuple[Array, Array, int, Optional[int]]:
    """Flatten inputs into (binary | per-class) layout
    (reference ``precision_recall_curve.py:64-128``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim == target.ndim:
        if pos_label is None:
            pos_label = 1
        if num_classes is not None and num_classes != 1:
            # multilabel
            if num_classes != preds.shape[1]:
                raise ValueError(
                    f"Argument `num_classes` was set to {num_classes} in"
                    f" metric `precision_recall_curve` but detected {preds.shape[1]}"
                    " number of classes from predictions"
                )
            preds = jnp.moveaxis(preds, 0, 1).reshape(num_classes, -1).T
            target = jnp.moveaxis(target, 0, 1).reshape(num_classes, -1).T
        else:
            preds = preds.reshape(-1)
            target = target.reshape(-1)
            num_classes = 1
    elif preds.ndim == target.ndim + 1:
        if pos_label is not None:
            rank_zero_warn(
                "Argument `pos_label` should be `None` when running"
                f" multiclass precision recall curve. Got {pos_label}"
            )
        if num_classes != preds.shape[1]:
            raise ValueError(
                f"Argument `num_classes` was set to {num_classes} in"
                f" metric `precision_recall_curve` but detected {preds.shape[1]}"
                " number of classes from predictions"
            )
        preds = jnp.moveaxis(preds, 0, 1).reshape(num_classes, -1).T
        target = target.reshape(-1)
    else:
        raise ValueError("preds and target must have same number of dimensions, or one additional dimension for preds")

    return preds, target, num_classes, pos_label


def _precision_recall_curve_compute_single_class(
    preds: Array,
    target: Array,
    pos_label: int,
    sample_weights: Optional[Sequence] = None,
) -> Tuple[Array, Array, Array]:
    """Reference ``precision_recall_curve.py:131-165``."""
    fps, tps, thresholds = _binary_clf_curve(preds=preds, target=target, sample_weights=sample_weights, pos_label=pos_label)
    precision = tps / (tps + fps)
    recall = tps / tps[-1]

    # stop when full recall attained; reverse so recall is decreasing
    last_ind = jnp.nonzero(tps == tps[-1])[0][0]
    sl = slice(0, int(last_ind) + 1)

    precision = jnp.concatenate([precision[sl][::-1], jnp.ones(1, precision.dtype)])
    recall = jnp.concatenate([recall[sl][::-1], jnp.zeros(1, recall.dtype)])
    thresholds = thresholds[sl][::-1]

    return precision, recall, thresholds


def _binary_precision_recall_curve_masked(
    preds: Array, target: Array, mask: Array
) -> Tuple[Array, Array, Array]:
    """Exact binary PR curve over the masked rows — static shapes for
    :class:`CatBuffer` ring states.

    Matches the eager path's conventions: points at unique valid
    thresholds, truncated at first full recall, ordered by decreasing
    recall, with the terminal ``(precision=1, recall=0)`` appended.
    ``precision``/``recall`` are ``(cap + 1,)`` (tail repeats the terminal
    point — zero-width for any step integral); ``thresholds`` is ``(cap,)``
    padded with its final (maximum) threshold.
    """
    cap = preds.shape[0]
    parts = masked_curve_prologue(preds, target, mask)
    s, tps, kv, boundary = parts.s, parts.tps, parts.kv, parts.boundary
    n_pos = parts.n_pos

    comp = partition_order(boundary)
    b_tps, b_kv, b_thr = tps[comp], kv[comp], s[comp]
    n_b = boundary.sum()
    i = jnp.arange(cap)

    # keep boundaries up to (and including) the first that attains full
    # recall: those whose preceding boundary had not yet reached n_pos
    prev_tps = jnp.concatenate([jnp.zeros((1,)), b_tps[:-1]])
    kept = (i < n_b) & (prev_tps < jnp.maximum(n_pos, 1.0))
    m = kept.sum()

    b_prec = b_tps / jnp.maximum(b_kv, 1.0)
    b_rec = b_tps / jnp.maximum(n_pos, 1.0)

    # reverse the kept prefix (recall decreasing), then the (1, 0) terminal
    rev = jnp.clip(m - 1 - i, 0, cap - 1).astype(jnp.int32)
    precision = jnp.where(i < m, jnp.take(b_prec, rev), 1.0)
    recall = jnp.where(i < m, jnp.take(b_rec, rev), 0.0)
    thresholds = jnp.where(i < m, jnp.take(b_thr, rev), jnp.take(b_thr, 0))
    precision = jnp.concatenate([precision, jnp.ones((1,), jnp.float32)])
    recall = jnp.concatenate([recall, jnp.zeros((1,), jnp.float32)])
    return precision, recall, thresholds


def _multiclass_precision_recall_curve_masked(
    preds: Array, target: Array, mask: Array, num_classes: int
) -> Tuple[Array, Array, Array]:
    """One-vs-rest masked PR curves, stacked ``(C, ...)`` (static shapes
    cannot carry per-class dynamic lengths)."""
    return jax.vmap(
        lambda c: _binary_precision_recall_curve_masked(
            preds[:, c], (jnp.asarray(target) == c).astype(jnp.int32), mask
        )
    )(jnp.arange(num_classes))


def _precision_recall_curve_compute_multi_class(
    preds: Array,
    target: Array,
    num_classes: int,
    sample_weights: Optional[Sequence] = None,
) -> Tuple[List[Array], List[Array], List[Array]]:
    """Per-class one-vs-rest curves (reference ``precision_recall_curve.py:168-207``)."""
    precision, recall, thresholds = [], [], []
    for cls in range(num_classes):
        preds_cls = preds[:, cls]
        prc_args = dict(preds=preds_cls, target=target, num_classes=1, pos_label=cls, sample_weights=sample_weights)
        if target.ndim > 1:
            prc_args.update(dict(target=target[:, cls], pos_label=1))
        res = precision_recall_curve(**prc_args)
        precision.append(res[0])
        recall.append(res[1])
        thresholds.append(res[2])
    return precision, recall, thresholds


def _precision_recall_curve_compute(
    preds: Array,
    target: Array,
    num_classes: int,
    pos_label: Optional[int] = None,
    sample_weights: Optional[Sequence] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Reference ``precision_recall_curve.py:210-266``."""
    if num_classes == 1:
        if pos_label is None:
            pos_label = 1
        return _precision_recall_curve_compute_single_class(preds, target, pos_label, sample_weights)
    return _precision_recall_curve_compute_multi_class(preds, target, num_classes, sample_weights)


def precision_recall_curve(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    sample_weights: Optional[Sequence] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Precision-recall pairs at every distinct threshold
    (reference ``precision_recall_curve.py:269-331``).

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0, 1, 2, 3])
        >>> target = jnp.array([0, 1, 1, 0])
        >>> precision, recall, thresholds = precision_recall_curve(pred, target, pos_label=1)
        >>> precision
        Array([0.6666667, 0.5      , 0.       , 1.       ], dtype=float32)
        >>> recall
        Array([1. , 0.5, 0. , 0. ], dtype=float32)
        >>> thresholds
        Array([1, 2, 3], dtype=int32)
    """
    preds, target, num_classes, pos_label = _precision_recall_curve_update(preds, target, num_classes, pos_label)
    return _precision_recall_curve_compute(preds, target, num_classes, pos_label, sample_weights)

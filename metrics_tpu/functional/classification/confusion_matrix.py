"""Confusion-matrix kernel (reference
``src/torchmetrics/functional/classification/confusion_matrix.py``, 186 LoC).

TPU-first: the bincount over ``target * C + pred`` is a one-hot reduction
(``utilities/data._bincount``) that XLA lowers onto the MXU — deterministic by
construction, unlike the reference's CUDA ``torch.bincount`` path.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import (
    _check_shape_and_type_consistency,
    _input_format_classification,
    _input_squeeze,
    _is_concrete,
)
from metrics_tpu.utilities.data import _bincount
from metrics_tpu.utilities.enums import DataType
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _confusion_matrix_update(
    preds: Array, target: Array, num_classes: int, threshold: float = 0.5, multilabel: bool = False
) -> Array:
    """Accumulate the (un-normalized) confusion matrix
    (reference ``confusion_matrix.py:25-54``): ``(C, C)`` counts, or
    ``(C, 2, 2)`` per-class binary matrices when ``multilabel=True``."""
    # resolve the case statically so num_classes can be passed through for
    # multiclass inputs — keeps the canonicalizer free of data-dependent
    # class-count inference (stays jittable; reference infers from data)
    p_sq, t_sq = _input_squeeze(jnp.asarray(preds), jnp.asarray(target))
    static_case, _ = _check_shape_and_type_consistency(p_sq, t_sq)
    nc_arg = num_classes if static_case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) else None
    preds, target, mode = _input_format_classification(p_sq, t_sq, threshold, num_classes=nc_arg)
    if mode not in (DataType.BINARY, DataType.MULTILABEL):
        preds = jnp.argmax(preds, axis=1)
        target = jnp.argmax(target, axis=1)
    if multilabel:
        unique_mapping = ((2 * target + preds) + 4 * jnp.arange(num_classes)).reshape(-1)
        minlength = 4 * num_classes
    else:
        unique_mapping = (target.reshape(-1) * num_classes + preds.reshape(-1)).astype(jnp.int32)
        minlength = num_classes**2

    bins = _bincount(unique_mapping, minlength=minlength)
    if multilabel:
        return bins.reshape(num_classes, 2, 2)
    return bins.reshape(num_classes, num_classes)


def _confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    """Normalize the accumulated matrix (reference ``confusion_matrix.py:57-115``)."""
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument average needs to one of the following: {allowed_normalize}")
    if normalize is not None and normalize != "none":
        confmat = confmat.astype(jnp.float32)
        if normalize == "true":
            confmat = confmat / confmat.sum(axis=1, keepdims=True)
        elif normalize == "pred":
            confmat = confmat / confmat.sum(axis=0, keepdims=True)
        elif normalize == "all":
            confmat = confmat / confmat.sum()
        if _is_concrete(confmat):
            nan_elements = int(jnp.isnan(confmat).sum())
            if nan_elements:
                rank_zero_warn(f"{nan_elements} nan values found in confusion matrix have been replaced with zeros.")
        confmat = jnp.nan_to_num(confmat, nan=0.0, posinf=jnp.inf, neginf=-jnp.inf)
    return confmat


def confusion_matrix(
    preds: Array,
    target: Array,
    num_classes: int,
    normalize: Optional[str] = None,
    threshold: float = 0.5,
    multilabel: bool = False,
) -> Array:
    """Confusion matrix (reference ``confusion_matrix.py:118-186``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> confusion_matrix(preds, target, num_classes=2)
        Array([[2, 0],
               [1, 1]], dtype=int32)
    """
    confmat = _confusion_matrix_update(preds, target, num_classes, threshold, multilabel)
    return _confusion_matrix_compute(confmat, normalize)

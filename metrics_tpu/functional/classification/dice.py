"""Dice kernels (reference
``src/torchmetrics/functional/classification/dice.py``, 303 LoC).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import _reduce_stat_scores, _stat_scores_update
from metrics_tpu.utilities.checks import _input_squeeze
from metrics_tpu.utilities.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


def _dice_compute(
    tp: Array,
    fp: Array,
    fn: Array,
    average: Optional[str],
    mdmc_average: Optional[str],
    zero_division: int = 0,
) -> Array:
    """2*tp / (2*tp + fp + fn) with averaging (reference ``dice.py:110-160``)."""
    numerator = 2 * tp
    denominator = 2 * tp + fp + fn

    if average in (AverageMethod.MACRO, AverageMethod.NONE, None) and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        meaningless = (tp + fp + fn) == 0
        numerator = jnp.where(meaningless, -1, numerator)
        denominator = jnp.where(meaningless, -1, denominator)

    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != AverageMethod.WEIGHTED else (tp + fn).astype(jnp.float32),
        average=average,
        mdmc_average=mdmc_average,
        zero_division=zero_division,
    )


def dice(
    preds: Array,
    target: Array,
    zero_division: int = 0,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = "global",
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """Dice score (reference ``dice.py:163-303``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([2, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> dice(preds, target, average='micro')
        Array(0.25, dtype=float32)
    """
    allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    allowed_mdmc_average = (None, "samplewise", "global")
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
    if average in ("macro", "weighted", "none", None) and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

    reduce = "macro" if average in ("weighted", "none", None) else average
    preds, target = _input_squeeze(jnp.asarray(preds), jnp.asarray(target))
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _dice_compute(tp, fp, fn, average, mdmc_average, zero_division)

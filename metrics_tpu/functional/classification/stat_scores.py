"""True/false positive/negative counting — the classification backbone.

TPU-first redesign of reference
``src/torchmetrics/functional/classification/stat_scores.py``:

- ``_stat_scores`` (reference ``:63-107``) is elementwise masks + axis
  reductions — XLA fuses the whole thing into one pass over the inputs.
- ``_reduce_stat_scores`` (reference ``:231-289``) is rewritten **without
  boolean compression**: the reference drops classes via ``x[~cond]``
  (a dynamic shape, illegal under XLA); here droppable classes are marked
  with the ``-1`` sentinel and masked with ``where``, which is numerically
  identical (ignored classes get weight 0 and the weight renormalization
  reproduces the mean-over-kept-classes semantics).
- Negative ``ignore_index`` row-dropping (reference ``:28-60``) is
  inherently dynamic-shape and only supported eagerly (concrete inputs).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _input_format_classification
from metrics_tpu.utilities.enums import AverageMethod, DataType, MDMCAverageMethod

Array = jax.Array


def _del_column(data: Array, idx: int) -> Array:
    """Drop column ``idx`` (static shape; reference ``stat_scores.py:23-25``)."""
    return jnp.concatenate([data[:, :idx], data[:, idx + 1 :]], axis=1)


def _drop_negative_ignored_indices(
    preds: Array, target: Array, ignore_index: int, mode: DataType
) -> Tuple[Array, Array]:
    """Remove samples whose target equals a negative ``ignore_index``
    (reference ``stat_scores.py:28-60``). Dynamic output shape → eager only."""
    if mode == DataType.MULTIDIM_MULTICLASS and jnp.issubdtype(preds.dtype, jnp.floating):
        num_classes = preds.shape[1]
        preds = jnp.moveaxis(preds, 1, -1).reshape(-1, num_classes)
        target = target.reshape(-1)
    if mode in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS):
        keep = target != ignore_index
        preds = preds[keep]
        target = target[keep]
    return preds, target


def _stat_scores(
    preds: Array,
    target: Array,
    reduce: Optional[str] = "micro",
    valid: Optional[Array] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Count tp/fp/tn/fn over canonical ``(N, C)`` / ``(N, C, X)`` binary
    inputs (reference ``stat_scores.py:63-107``); output shape per ``reduce``
    as documented there.

    ``valid`` is an optional bool ``(N,)`` row mask: False rows contribute
    to NO counter — the traced row-drop path the fault channel's
    ``on_invalid='drop'`` and the padding ladder (``ops/padding.py``) ride
    for the stat-scores family. Only the row-reducing modes support it
    (micro/macro); per-sample outputs keep one row per input row, so a mask
    there would misalign downstream.
    """
    if reduce == "micro":
        dim = (0, 1) if preds.ndim == 2 else (1, 2)
    elif reduce == "macro":
        dim = 0 if preds.ndim == 2 else 2
    else:  # samples
        dim = 1

    true_pred = target == preds
    pos_pred = preds == 1

    if valid is not None:
        if reduce == "samples":
            raise ValueError("`valid` row masks are not supported with reduce='samples'")
        v = jnp.asarray(valid, bool).reshape((preds.shape[0],) + (1,) * (preds.ndim - 1))
    else:
        v = True  # broadcasts away

    tp = jnp.sum(true_pred & pos_pred & v, axis=dim)
    fp = jnp.sum((~true_pred) & pos_pred & v, axis=dim)
    tn = jnp.sum(true_pred & ~pos_pred & v, axis=dim)
    fn = jnp.sum((~true_pred) & ~pos_pred & v, axis=dim)
    # int64 counters (the reference uses long) when x64 is enabled; under
    # JAX's default x64-off config int64 silently downcasts, so int32 is the
    # honest dtype there — accumulators overflow past ~2.1B counts per entry.
    dtype = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return tp.astype(dtype), fp.astype(dtype), tn.astype(dtype), fn.astype(dtype)


def _stat_scores_update(
    preds: Array,
    target: Array,
    reduce: Optional[str] = "micro",
    mdmc_reduce: Optional[str] = None,
    num_classes: Optional[int] = None,
    top_k: Optional[int] = None,
    threshold: float = 0.5,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
    mode: Optional[DataType] = None,
    valid: Optional[Array] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Canonicalize inputs and count tp/fp/tn/fn
    (reference ``stat_scores.py:110-193``).

    ``valid`` is an optional bool ``(N,)`` row mask — masked rows contribute
    to no counter (see :func:`_stat_scores`); the canonicalization below
    preserves row order/count, so the mask stays aligned through it.
    """
    if valid is not None and ignore_index is not None and ignore_index < 0:
        # the negative-ignore path drops rows by concrete boolean indexing,
        # which would misalign the mask; no caller combines the two
        raise ValueError("`valid` row masks are not supported with a negative `ignore_index`")
    if valid is not None and (reduce == "samples" or mdmc_reduce == "samplewise"):
        # per-sample outputs keep one row per input row — a row mask cannot
        # remove its row from the downstream cat state
        raise ValueError("`valid` row masks are not supported with per-sample reductions")
    _negative_index_dropped = False
    if ignore_index is not None and ignore_index < 0:
        # resolve the case statically if the caller didn't pass it — without
        # this, a negative index would reach _del_column and silently
        # duplicate columns (the reference has this hole for every caller but
        # Accuracy; here the drop always runs)
        if mode is None:
            from metrics_tpu.utilities.checks import _check_shape_and_type_consistency, _input_squeeze

            mode, _ = _check_shape_and_type_consistency(*_input_squeeze(jnp.asarray(preds), jnp.asarray(target)))
        preds, target = _drop_negative_ignored_indices(preds, target, ignore_index, mode)
        _negative_index_dropped = True

    preds, target, _ = _input_format_classification(
        preds,
        target,
        threshold=threshold,
        num_classes=num_classes,
        multiclass=multiclass,
        top_k=top_k,
        ignore_index=ignore_index,
    )

    if ignore_index is not None and ignore_index >= preds.shape[1]:
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {preds.shape[1]} classes")
    if ignore_index is not None and preds.shape[1] == 1:
        raise ValueError("You can not use `ignore_index` with binary data.")

    if preds.ndim == 3:
        if not mdmc_reduce:
            raise ValueError(
                "When your inputs are multi-dimensional multi-class, you have to set the `mdmc_reduce` parameter"
            )
        if mdmc_reduce == "global":
            if valid is not None:
                # rows expand (N, C, X) -> (N*X, C) in n-major order: each
                # input row's mask bit covers its X extra-dim samples
                valid = jnp.repeat(jnp.asarray(valid, bool), preds.shape[2])
            preds = jnp.moveaxis(preds, 1, 2).reshape(-1, preds.shape[1])
            target = jnp.moveaxis(target, 1, 2).reshape(-1, target.shape[1])

    if ignore_index is not None and reduce != "macro" and not _negative_index_dropped:
        preds = _del_column(preds, ignore_index)
        target = _del_column(target, ignore_index)

    tp, fp, tn, fn = _stat_scores(preds, target, reduce=reduce, valid=valid)

    if ignore_index is not None and reduce == "macro" and not _negative_index_dropped:
        # mark the ignored class with the -1 sentinel (reference ``:187-191``)
        idx = jnp.arange(tp.shape[-1]) == ignore_index
        tp = jnp.where(idx, -1, tp)
        fp = jnp.where(idx, -1, fp)
        tn = jnp.where(idx, -1, tn)
        fn = jnp.where(idx, -1, fn)

    return tp, fp, tn, fn


def _stat_scores_compute(tp: Array, fp: Array, tn: Array, fn: Array) -> Array:
    """Stack [tp, fp, tn, fn, support] along a trailing axis
    (reference ``stat_scores.py:196-228``)."""
    outputs = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    return jnp.where(outputs < 0, -1, outputs)


def _reduce_stat_scores(
    numerator: Array,
    denominator: Array,
    weights: Optional[Array],
    average: Optional[str],
    mdmc_average: Optional[str],
    zero_division: float = 0.0,
) -> Array:
    """Score reduction ``weights * num / denom`` with sentinel semantics
    (reference ``stat_scores.py:231-289``): ``denominator < 0`` marks an
    ignored class (weight 0 / NaN when ``average=None``); ``denominator == 0``
    yields ``zero_division``. Pure ``where`` masking — no dynamic shapes."""
    numerator = jnp.asarray(numerator, jnp.float32)
    denominator = jnp.asarray(denominator, jnp.float32)
    zero_div_mask = denominator == 0
    ignore_mask = denominator < 0

    weights = jnp.ones_like(denominator) if weights is None else jnp.asarray(weights, jnp.float32)

    numerator = jnp.where(zero_div_mask, zero_division, numerator)
    denominator = jnp.where(zero_div_mask | ignore_mask, 1.0, denominator)
    weights = jnp.where(ignore_mask, 0.0, weights)

    if average not in (AverageMethod.MICRO, AverageMethod.NONE, None):
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)

    scores = weights * (numerator / denominator)
    scores = jnp.where(jnp.isnan(scores), zero_division, scores)

    if mdmc_average == MDMCAverageMethod.SAMPLEWISE:
        scores = jnp.mean(scores, axis=0)
        ignore_mask = jnp.sum(ignore_mask, axis=0).astype(bool)

    if average in (AverageMethod.NONE, None):
        scores = jnp.where(ignore_mask, jnp.nan, scores)
    else:
        scores = jnp.sum(scores)

    return scores


def stat_scores(
    preds: Array,
    target: Array,
    reduce: str = "micro",
    mdmc_reduce: Optional[str] = None,
    num_classes: Optional[int] = None,
    top_k: Optional[int] = None,
    threshold: float = 0.5,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """Number of tp/fp/tn/fn/support (reference ``stat_scores.py:292-442``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds  = jnp.array([1, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> stat_scores(preds, target, reduce='micro')
        Array([2, 2, 6, 2, 4], dtype=int32)
    """
    if reduce not in ["micro", "macro", "samples"]:
        raise ValueError(f"The `reduce` {reduce} is not valid.")
    if mdmc_reduce not in [None, "samplewise", "global"]:
        raise ValueError(f"The `mdmc_reduce` {mdmc_reduce} is not valid.")
    if reduce == "macro" and (not num_classes or num_classes < 1):
        raise ValueError("When you set `reduce` as 'macro', you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_reduce,
        top_k=top_k,
        threshold=threshold,
        num_classes=num_classes,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _stat_scores_compute(tp, fp, tn, fn)

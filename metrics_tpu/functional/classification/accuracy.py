"""Accuracy kernels (reference
``src/torchmetrics/functional/classification/accuracy.py``, 420 LoC).

Mask-based (static-shape) reformulation of the reference's boolean-compression
reductions — see ``stat_scores._reduce_stat_scores`` for the sentinel
convention.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import _reduce_stat_scores, _stat_scores_update
from metrics_tpu.utilities.checks import _check_classification_inputs, _input_format_classification, _input_squeeze
from metrics_tpu.utilities.enums import AverageMethod, DataType, MDMCAverageMethod

Array = jax.Array


def _check_subset_validity(mode: DataType) -> bool:
    """Reference ``accuracy.py:24-26``."""
    return mode in (DataType.MULTILABEL, DataType.MULTIDIM_MULTICLASS)


def _mode(
    preds: Array,
    target: Array,
    threshold: float,
    top_k: Optional[int],
    num_classes: Optional[int],
    multiclass: Optional[bool],
    ignore_index: Optional[int] = None,
) -> DataType:
    """Resolve the input case (reference ``accuracy.py:29-68``). Static under
    tracing — the case depends only on shapes/dtypes."""
    return _check_classification_inputs(
        preds,
        target,
        threshold=threshold,
        top_k=top_k,
        num_classes=num_classes,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )


def _accuracy_update(
    preds: Array,
    target: Array,
    reduce: Optional[str],
    mdmc_reduce: Optional[str],
    threshold: float,
    num_classes: Optional[int],
    top_k: Optional[int],
    multiclass: Optional[bool],
    ignore_index: Optional[int],
    mode: DataType,
    valid: Optional[Array] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Reference ``accuracy.py:71-119``; ``valid`` row masks thread through
    to :func:`_stat_scores_update` (``_input_squeeze`` preserves the batch
    axis, so the mask stays row-aligned)."""
    if mode == DataType.MULTILABEL and top_k:
        raise ValueError("You can not use the `top_k` parameter to calculate accuracy for multi-label inputs.")
    preds, target = _input_squeeze(jnp.asarray(preds), jnp.asarray(target))
    return _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_reduce,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
        mode=mode,
        valid=valid,
    )


def _accuracy_compute(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    mdmc_average: Optional[str],
    mode: DataType,
) -> Array:
    """Reference ``accuracy.py:122-202``; class-dropping replaced by the -1
    ignore sentinel (weight-renormalization makes them equivalent)."""
    simple_average = (AverageMethod.MICRO, AverageMethod.SAMPLES)
    if (mode == DataType.BINARY and average in simple_average) or mode == DataType.MULTILABEL:
        numerator = tp + tn
        denominator = tp + tn + fp + fn
    else:
        numerator = tp
        denominator = tp + fn

    if mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        if average in (AverageMethod.MACRO, AverageMethod.NONE, None):
            # absent classes (no tp/fp/fn) are meaningless: drop for macro,
            # NaN for none — both via the ignore sentinel
            meaningless = (tp + fp + fn) == 0
            numerator = jnp.where(meaningless, -1, numerator)
            denominator = jnp.where(meaningless, -1, denominator)

    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != AverageMethod.WEIGHTED else tp + fn,
        average=average,
        mdmc_average=mdmc_average,
    )


def _subset_accuracy_update(
    preds: Array,
    target: Array,
    threshold: float,
    top_k: Optional[int],
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array]:
    """Exact-match counting (reference ``accuracy.py:205-244``)."""
    preds, target = _input_squeeze(jnp.asarray(preds), jnp.asarray(target))
    preds, target, mode = _input_format_classification(
        preds, target, threshold=threshold, top_k=top_k, ignore_index=ignore_index
    )

    if mode == DataType.MULTILABEL and top_k:
        raise ValueError("You can not use the `top_k` parameter to calculate accuracy for multi-label inputs.")

    if mode == DataType.MULTILABEL:
        correct = jnp.sum(jnp.all(preds == target, axis=1))
        total = jnp.asarray(target.shape[0])
    elif mode == DataType.MULTICLASS:
        correct = jnp.sum(preds * target)
        total = jnp.sum(target)
    elif mode == DataType.MULTIDIM_MULTICLASS:
        sample_correct = jnp.sum(preds * target, axis=(1, 2))
        correct = jnp.sum(sample_correct == target.shape[2])
        total = jnp.asarray(target.shape[0])
    else:
        correct, total = jnp.asarray(0), jnp.asarray(0)

    return correct.astype(jnp.int32), total.astype(jnp.int32)


def _subset_accuracy_compute(correct: Array, total: Array) -> Array:
    """Reference ``accuracy.py:247-255``."""
    return correct.astype(jnp.float32) / total


def accuracy(
    preds: Array,
    target: Array,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = "global",
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    subset_accuracy: bool = False,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """Accuracy (reference ``accuracy.py:258-420``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 2, 3])
        >>> preds = jnp.array([0, 2, 1, 3])
        >>> accuracy(preds, target)
        Array(0.5, dtype=float32)
    """
    allowed_average = (AverageMethod.MICRO, AverageMethod.MACRO, AverageMethod.WEIGHTED, AverageMethod.SAMPLES, AverageMethod.NONE, None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")

    if average in (AverageMethod.MACRO, AverageMethod.WEIGHTED, AverageMethod.NONE) and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")

    allowed_mdmc_average = (None, MDMCAverageMethod.SAMPLEWISE, MDMCAverageMethod.GLOBAL)
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")

    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

    preds, target = _input_squeeze(jnp.asarray(preds), jnp.asarray(target))
    mode = _mode(preds, target, threshold, top_k, num_classes, multiclass, ignore_index)
    reduce = "macro" if average in (AverageMethod.WEIGHTED, AverageMethod.NONE, None) else average

    if subset_accuracy and _check_subset_validity(mode):
        correct, total = _subset_accuracy_update(preds, target, threshold, top_k, ignore_index)
        return _subset_accuracy_compute(correct, total)
    tp, fp, tn, fn = _accuracy_update(
        preds, target, reduce, mdmc_average, threshold, num_classes, top_k, multiclass, ignore_index, mode
    )
    return _accuracy_compute(tp, fp, tn, fn, average, mdmc_average, mode)

"""Precision / Recall kernels (reference
``src/torchmetrics/functional/classification/precision_recall.py``, 552 LoC).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import _reduce_stat_scores, _stat_scores_update
from metrics_tpu.utilities.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


def _apply_meaningless_sentinel(
    numerator: Array, denominator: Array, tp: Array, fp: Array, fn: Array, average: Optional[str], mdmc_average: Optional[str]
) -> Tuple[Array, Array]:
    """Mark absent classes (no tp/fp/fn) with the -1 ignore sentinel — the
    static-shape replacement for the reference's ``x[~cond]`` dropping
    (``precision_recall.py:55-65``) / NaN indexing."""
    if average in (AverageMethod.MACRO, AverageMethod.NONE, None) and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        meaningless = (tp + fp + fn) == 0
        numerator = jnp.where(meaningless, -1, numerator)
        denominator = jnp.where(meaningless, -1, denominator)
    return numerator, denominator


def _precision_compute(
    tp: Array,
    fp: Array,
    fn: Array,
    average: Optional[str],
    mdmc_average: Optional[str],
) -> Array:
    """tp / (tp + fp) with averaging (reference ``precision_recall.py:24-73``)."""
    numerator, denominator = _apply_meaningless_sentinel(tp, tp + fp, tp, fp, fn, average, mdmc_average)
    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != AverageMethod.WEIGHTED else tp + fn,
        average=average,
        mdmc_average=mdmc_average,
    )


def _recall_compute(
    tp: Array,
    fp: Array,
    fn: Array,
    average: Optional[str],
    mdmc_average: Optional[str],
) -> Array:
    """tp / (tp + fn) with averaging (reference ``precision_recall.py:190-245``)."""
    numerator, denominator = _apply_meaningless_sentinel(tp, tp + fn, tp, fp, fn, average, mdmc_average)
    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != AverageMethod.WEIGHTED else tp + fn,
        average=average,
        mdmc_average=mdmc_average,
    )


def _check_average_arg(average: Optional[str], mdmc_average: Optional[str], num_classes: Optional[int], ignore_index: Optional[int]) -> None:
    allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    allowed_mdmc_average = (None, "samplewise", "global")
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
    if average in ("macro", "weighted", "none", None) and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")


def precision(
    preds: Array,
    target: Array,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """Precision = TP / (TP + FP) (reference ``precision_recall.py:76-187``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds  = jnp.array([2, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> precision(preds, target, average='micro')
        Array(0.25, dtype=float32)
    """
    _check_average_arg(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _precision_compute(tp, fp, fn, average, mdmc_average)


def recall(
    preds: Array,
    target: Array,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """Recall = TP / (TP + FN) (reference ``precision_recall.py:248-359``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds  = jnp.array([2, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> recall(preds, target, average='micro')
        Array(0.25, dtype=float32)
    """
    _check_average_arg(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _recall_compute(tp, fp, fn, average, mdmc_average)


def precision_recall(
    preds: Array,
    target: Array,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Tuple[Array, Array]:
    """Both precision and recall from one stat-scores pass
    (reference ``precision_recall.py:362-552``)."""
    _check_average_arg(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _precision_compute(tp, fp, fn, average, mdmc_average), _recall_compute(tp, fp, fn, average, mdmc_average)

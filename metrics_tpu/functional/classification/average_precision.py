"""Average precision kernels (reference
``src/torchmetrics/functional/classification/average_precision.py``, 234 LoC).
"""
import warnings
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.masked_common import masked_curve_prologue
from metrics_tpu.functional.classification.precision_recall_curve import (
    _precision_recall_curve_compute,
    _precision_recall_curve_update,
)
from metrics_tpu.utilities.data import _bincount

Array = jax.Array


def _binary_average_precision_masked(preds: Array, target: Array, mask: Array) -> Array:
    """Average precision of the masked rows — static-shape and jittable,
    for :class:`CatBuffer` ring states.

    Same value as the PR-curve step integral on the valid rows
    (reference ``average_precision.py:113-176`` / sklearn): scores sorted
    descending, ties grouped per unique threshold, ``AP = sum over
    threshold groups of precision_at_group_end * group_positive_mass /
    n_pos``. No positives -> NaN (the eager path warns and NaNs too).
    """
    parts = masked_curve_prologue(preds, target, mask)
    tps, boundary, n_pos = parts.tps, parts.boundary, parts.n_pos
    precision = tps / jnp.maximum(parts.kv, 1.0)

    # positives inside each group = tps at this boundary minus tps at the
    # previous one; tps is monotone, so a shifted cummax over
    # boundary-marked tps recovers the previous boundary's value
    marked = jnp.where(boundary, tps, 0.0)
    prev = jnp.concatenate([jnp.zeros((1,)), jax.lax.cummax(marked)[:-1]])
    group_pos = tps - prev

    ap = jnp.sum(jnp.where(boundary, precision * group_pos, 0.0)) / jnp.maximum(n_pos, 1.0)
    return jnp.where(n_pos > 0, ap, jnp.nan)


def _multiclass_average_precision_masked(
    preds: Array,
    target: Array,
    mask: Array,
    num_classes: int,
    average: Optional[str] = "macro",
) -> Union[Array, List[Array]]:
    """One-vs-rest masked AP over a ``(cap, C)`` score buffer (micro is
    rejected for multiclass input, as in the reference
    ``average_precision.py:47``)."""
    target = jnp.asarray(target)
    if average == "micro":
        raise ValueError("Cannot use `micro` average with multi-class input")
    per_class = jax.vmap(
        lambda c: _binary_average_precision_masked(preds[:, c], (target == c).astype(jnp.int32), mask)
    )(jnp.arange(num_classes))
    if average in (None, "none"):
        return per_class
    defined = ~jnp.isnan(per_class)
    safe = jnp.where(defined, per_class, 0.0)
    if average == "macro":
        return jnp.sum(safe) / jnp.maximum(jnp.sum(defined.astype(jnp.float32)), 1.0)
    if average == "weighted":
        # one O(cap) bincount (invalid rows routed to an extra dropped bin)
        # instead of a vmapped O(C * cap) comparison sweep
        counts = _bincount(
            jnp.where(jnp.asarray(mask), target, num_classes), minlength=num_classes + 1
        )[:num_classes].astype(jnp.float32)
        weights = jnp.where(defined, counts, 0.0)
        return jnp.sum(safe * weights / jnp.maximum(jnp.sum(weights), 1.0))
    raise ValueError(f"Average {average!r} is not supported in masked AP")


def _average_precision_update(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
) -> Tuple[Array, Array, int, Optional[int]]:
    """Reference ``average_precision.py:27-50``."""
    preds, target, num_classes, pos_label = _precision_recall_curve_update(preds, target, num_classes, pos_label)
    if average == "micro" and preds.ndim != target.ndim:
        raise ValueError("Cannot use `micro` average with multi-class input")
    return preds, target, num_classes, pos_label


def _average_precision_compute(
    preds: Array,
    target: Array,
    num_classes: int,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    sample_weights: Optional[Sequence] = None,
) -> Union[List[Array], Array]:
    """Reference ``average_precision.py:53-110``."""
    if average == "micro" and preds.ndim == target.ndim:
        preds = preds.reshape(-1)
        target = target.reshape(-1)
        num_classes = 1

    precision, recall, _ = _precision_recall_curve_compute(preds, target, num_classes, pos_label)
    if average == "weighted":
        if preds.ndim == target.ndim and target.ndim > 1:
            weights = target.sum(axis=0).astype(jnp.float32)
        else:
            weights = _bincount(target, minlength=num_classes).astype(jnp.float32)
        weights = weights / jnp.sum(weights)
    else:
        weights = None
    return _average_precision_compute_with_precision_recall(precision, recall, num_classes, average, weights)


def _average_precision_compute_with_precision_recall(
    precision: Union[Array, List[Array]],
    recall: Union[Array, List[Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Union[List[Array], Array]:
    """Step-function integral of the PR curve (reference
    ``average_precision.py:113-176``)."""
    if num_classes == 1:
        return -jnp.sum((recall[1:] - recall[:-1]) * precision[:-1])

    res = [-jnp.sum((r[1:] - r[:-1]) * p[:-1]) for p, r in zip(precision, recall)]

    if average in ("macro", "weighted"):
        res_arr = jnp.stack(res)
        nan_mask = jnp.isnan(res_arr)
        if bool(nan_mask.any()):
            warnings.warn(
                "Average precision score for one or more classes was `nan`. Ignoring these classes in average",
                UserWarning,
            )
        if average == "macro":
            return jnp.where(nan_mask, 0.0, res_arr).sum() / jnp.maximum((~nan_mask).sum(), 1)
        weights = jnp.ones_like(res_arr) if weights is None else weights
        return jnp.where(nan_mask, 0.0, res_arr * weights).sum()
    if average is None or average == "none":
        return res
    allowed_average = ("micro", "macro", "weighted", None)
    raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")


def average_precision(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    sample_weights: Optional[Sequence] = None,
) -> Union[List[Array], Array]:
    """Average precision score (reference ``average_precision.py:179-234``).

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0, 1, 2, 3])
        >>> target = jnp.array([0, 1, 1, 1])
        >>> average_precision(pred, target, pos_label=1)
        Array(1., dtype=float32)
    """
    preds, target, num_classes, pos_label = _average_precision_update(preds, target, num_classes, pos_label, average)
    return _average_precision_compute(preds, target, num_classes, pos_label, average, sample_weights)

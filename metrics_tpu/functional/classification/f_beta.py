"""F-beta / F1 kernels (reference
``src/torchmetrics/functional/classification/f_beta.py``, 354 LoC).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import _reduce_stat_scores, _stat_scores_update
from metrics_tpu.utilities.compute import _safe_divide
from metrics_tpu.utilities.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


def _masked_sum(x: Array, mask: Array) -> Array:
    return jnp.sum(jnp.where(mask, x, 0))


def _fbeta_compute(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    beta: float,
    ignore_index: Optional[int],
    average: Optional[str],
    mdmc_average: Optional[str],
) -> Array:
    """F-beta from stat scores (reference ``f_beta.py:30-108``); boolean
    compression replaced by masked sums / the -1 ignore sentinel."""
    tp = jnp.asarray(tp)
    fp = jnp.asarray(fp)
    fn = jnp.asarray(fn)
    if average == AverageMethod.MICRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        mask = tp >= 0  # drop classes carrying the macro ignore sentinel
        tp_s, fp_s, fn_s = _masked_sum(tp, mask), _masked_sum(fp, mask), _masked_sum(fn, mask)
        precision = _safe_divide(tp_s, tp_s + fp_s)
        recall = _safe_divide(tp_s, tp_s + fn_s)
    else:
        precision = _safe_divide(tp, tp + fp)
        recall = _safe_divide(tp, tp + fn)

    num = (1 + beta**2) * precision * recall
    denom = beta**2 * precision + recall
    denom = jnp.where(denom == 0.0, 1.0, denom)

    # classes absent from preds AND target are meaningless (reference ``:83-92``)
    sentinel = None
    if average == AverageMethod.NONE and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        sentinel = (tp + fp + fn) == 0
        if ignore_index is not None:
            sentinel = sentinel | (jnp.arange(tp.shape[-1]) == ignore_index)
    elif ignore_index is not None:
        if average not in (AverageMethod.MICRO, AverageMethod.SAMPLES):
            sentinel = jnp.arange(tp.shape[-1]) == ignore_index
            if mdmc_average == MDMCAverageMethod.SAMPLEWISE:
                sentinel = jnp.broadcast_to(sentinel, num.shape)

    if sentinel is not None:
        num = jnp.where(sentinel, -1, num)
        denom = jnp.where(sentinel, -1, denom)

    if average == AverageMethod.MACRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        cond = ((tp + fp + fn) == 0) | ((tp + fp + fn) == -3)
        num = jnp.where(cond, -1, num)
        denom = jnp.where(cond, -1, denom)

    return _reduce_stat_scores(
        numerator=num,
        denominator=denom,
        weights=None if average != AverageMethod.WEIGHTED else (tp + fn).astype(jnp.float32),
        average=average,
        mdmc_average=mdmc_average,
    )


def _check_fbeta_args(average, mdmc_average, num_classes, ignore_index):
    allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    allowed_mdmc_average = (None, "samplewise", "global")
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
    if average in ("macro", "weighted", "none", None) and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")


def fbeta_score(
    preds: Array,
    target: Array,
    beta: float = 1.0,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """F-beta score (reference ``f_beta.py:111-252``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.array([0, 2, 1, 0, 0, 1])
        >>> fbeta_score(preds, target, beta=0.5)
        Array(0.33333334, dtype=float32)
    """
    _check_fbeta_args(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _fbeta_compute(tp, fp, tn, fn, beta, ignore_index, average, mdmc_average)


def f1_score(
    preds: Array,
    target: Array,
    beta: float = 1.0,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """F1 = F-beta with beta=1 (reference ``f_beta.py:255-354``).

    ``beta`` is accepted (third positional, matching the reference's
    signature so positional call sites port unchanged) and ignored exactly
    as the reference ignores it — F1 always delegates with beta=1.0
    (reference ``f_beta.py:250,351-353``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.array([0, 2, 1, 0, 0, 1])
        >>> f1_score(preds, target)
        Array(0.33333334, dtype=float32)
    """
    return fbeta_score(preds, target, 1.0, average, mdmc_average, ignore_index, num_classes, threshold, top_k, multiclass)

"""Shared prologue for the masked (CatBuffer ring-state) curve kernels.

AUROC's rank statistic, average precision, ROC, and the PR curve all start
from the same static-shape construction over a ``(cap,)`` score buffer;
the subtle invariants live here exactly once:

- invalid rows are filled with ``-inf`` so they sort last, but valid
  ``-inf`` scores then tie with the fill — every count therefore comes
  from the VALID cumsum (``kv``), never the raw position index;
- targets binarize as ``== 1`` (capacity mode fixes ``pos_label`` to 1);
- a tie group's boundary is its last valid row, and the last valid row
  overall is always a boundary (its score can equal the ``-inf`` end
  sentinel).
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp

from metrics_tpu.ops import descending_order

Array = jax.Array


class MaskedCurveParts(NamedTuple):
    s: Array  # scores, descending, invalid rows filled with -inf
    rel: Array  # binarized positives in sorted order (float)
    valid: Array  # validity in sorted order (bool)
    tps: Array  # cumulative positives
    kv: Array  # cumulative valid count
    boundary: Array  # last valid row of each tie group
    n_valid: Array
    n_pos: Array


def masked_curve_prologue(preds: Array, target: Array, mask: Array) -> MaskedCurveParts:
    mask = jnp.asarray(mask, bool)
    rel = (mask & (jnp.asarray(target) == 1)).astype(jnp.float32)
    score = jnp.where(mask, jnp.asarray(preds, jnp.float32), -jnp.inf)

    # packed-radix replacement for jnp.argsort(-score): same permutation,
    # bitwise (ops/bucketed_rank.py) — the capacity-mode sort bound
    order = descending_order(score)
    s = score[order]
    r = rel[order]
    v = mask[order]

    tps = jnp.cumsum(r)
    kv = jnp.cumsum(v.astype(jnp.float32))
    n_valid = v.sum()
    n_pos = r.sum()

    next_s = jnp.concatenate([s[1:], jnp.full((1,), -jnp.inf, s.dtype)])
    boundary = v & ((s != next_s) | (kv == n_valid))
    return MaskedCurveParts(s, r, v, tps, kv, boundary, n_valid, n_pos)

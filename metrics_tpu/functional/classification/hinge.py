"""Hinge loss kernels (reference
``src/torchmetrics/functional/classification/hinge.py``, 231 LoC).

Boolean-mask scatter assignments from the reference are rewritten as
``where`` selects — same math, static shapes, fully jittable.
"""
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _input_squeeze
from metrics_tpu.utilities.data import to_onehot
from metrics_tpu.utilities.enums import DataType, EnumStr

Array = jax.Array


class MulticlassMode(EnumStr):
    """Reference ``hinge.py:24-32``."""

    CRAMMER_SINGER = "crammer-singer"
    ONE_VS_ALL = "one-vs-all"


def _check_shape_and_type_consistency_hinge(preds: Array, target: Array) -> DataType:
    """Reference ``hinge.py:35-72``."""
    if target.ndim > 1:
        raise ValueError(f"The `target` should be one dimensional, got `target` with shape={target.shape}.")
    if preds.ndim == 1:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        mode = DataType.BINARY
    elif preds.ndim == 2:
        if preds.shape[0] != target.shape[0]:
            raise ValueError(
                "The `preds` and `target` should have the same shape in the first dimension,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        mode = DataType.MULTICLASS
    else:
        raise ValueError(f"The `preds` should be one or two dimensional, got `preds` with shape={preds.shape}.")
    return mode


def _hinge_update(
    preds: Array,
    target: Array,
    squared: bool = False,
    multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
) -> Tuple[Array, Array]:
    """Reference ``hinge.py:75-124``."""
    preds, target = _input_squeeze(jnp.asarray(preds), jnp.asarray(target))
    mode = _check_shape_and_type_consistency_hinge(preds, target)

    if mode == DataType.MULTICLASS:
        target = to_onehot(target, max(2, preds.shape[1])).astype(bool)

    if mode == DataType.MULTICLASS and (multiclass_mode is None or multiclass_mode == MulticlassMode.CRAMMER_SINGER):
        # margin = true-class score - best wrong-class score, per row
        true_score = jnp.sum(jnp.where(target, preds, 0.0), axis=1)
        best_wrong = jnp.max(jnp.where(target, -jnp.inf, preds), axis=1)
        margin = true_score - best_wrong
    elif mode == DataType.BINARY or multiclass_mode == MulticlassMode.ONE_VS_ALL:
        target = target.astype(bool)
        margin = jnp.where(target, preds, -preds)
    else:
        raise ValueError(
            "The `multiclass_mode` should be either None / 'crammer-singer' / MulticlassMode.CRAMMER_SINGER"
            "(default) or 'one-vs-all' / MulticlassMode.ONE_VS_ALL,"
            f" got {multiclass_mode}."
        )

    measures = jnp.clip(1 - margin, 0, None)
    if squared:
        measures = measures**2

    total = jnp.asarray(target.shape[0])
    return measures.sum(axis=0), total


def _hinge_compute(measure: Array, total: Array) -> Array:
    """Reference ``hinge.py:127-158``."""
    return measure / total


def hinge_loss(
    preds: Array,
    target: Array,
    squared: bool = False,
    multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
) -> Array:
    """Mean hinge loss (reference ``hinge.py:161-231``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([0, 1, 1])
        >>> preds = jnp.array([-2.2, 2.4, 0.1])
        >>> print(f"{hinge_loss(preds, target):.4f}")
        0.3000
    """
    measure, total = _hinge_update(preds, target, squared=squared, multiclass_mode=multiclass_mode)
    return _hinge_compute(measure, total)

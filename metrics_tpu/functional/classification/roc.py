"""ROC kernels (reference
``src/torchmetrics/functional/classification/roc.py``, 282 LoC).

Eager (data-dependent shapes) — see ``precision_recall_curve.py`` header.
"""
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.masked_common import masked_curve_prologue
from metrics_tpu.functional.classification.precision_recall_curve import (
    _binary_clf_curve,
    _precision_recall_curve_update,
)
from metrics_tpu.ops import partition_order
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _roc_update(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
) -> Tuple[Array, Array, int, Optional[int]]:
    """Reference ``roc.py:26-45`` (same canonicalization as the PR curve)."""
    return _precision_recall_curve_update(preds, target, num_classes, pos_label)


def _roc_compute_single_class(
    preds: Array,
    target: Array,
    pos_label: int,
    sample_weights: Optional[Sequence] = None,
) -> Tuple[Array, Array, Array]:
    """Reference ``roc.py:48-96``."""
    fps, tps, thresholds = _binary_clf_curve(preds=preds, target=target, sample_weights=sample_weights, pos_label=pos_label)
    # extra threshold so the curve starts at (0, 0)
    tps = jnp.concatenate([jnp.zeros(1, tps.dtype), tps])
    fps = jnp.concatenate([jnp.zeros(1, fps.dtype), fps])
    thresholds = jnp.concatenate([thresholds[0][None] + 1, thresholds])

    if fps[-1] <= 0:
        rank_zero_warn(
            "No negative samples in targets, false positive value should be meaningless."
            " Returning zero tensor in false positive score",
            UserWarning,
        )
        fpr = jnp.zeros_like(thresholds, dtype=jnp.float32)
    else:
        fpr = fps / fps[-1]

    if tps[-1] <= 0:
        rank_zero_warn(
            "No positive samples in targets, true positive value should be meaningless."
            " Returning zero tensor in true positive score",
            UserWarning,
        )
        tpr = jnp.zeros_like(thresholds, dtype=jnp.float32)
    else:
        tpr = tps / tps[-1]

    return fpr, tpr, thresholds


def _binary_roc_masked(preds: Array, target: Array, mask: Array) -> Tuple[Array, Array, Array]:
    """Exact binary ROC over the masked rows — static ``(cap + 1,)`` outputs
    for :class:`CatBuffer` ring states.

    Point ``0`` is the reference's leading ``(0, 0, max_threshold + 1)``;
    the genuine curve points (one per unique valid threshold, descending)
    are compacted to the front; the tail repeats the terminal point
    ``(1, 1, min_threshold)``, so trapezoidal integration over the padded
    arrays equals integration over the true curve (zero-width segments).
    No negatives (or positives) zero out fpr (tpr) exactly like the eager
    path's warning branch.
    """
    cap = preds.shape[0]
    parts = masked_curve_prologue(preds, target, mask)
    s, tps, boundary = parts.s, parts.tps, parts.boundary
    fps = parts.kv - tps
    n_pos = parts.n_pos
    n_neg = parts.n_valid - n_pos

    # compact the boundary rows to the front, preserving descending order
    comp = partition_order(boundary)
    b_tps, b_fps, b_thr = tps[comp], fps[comp], s[comp]
    n_b = boundary.sum()
    i = jnp.arange(cap)

    last_thr = jnp.take(b_thr, jnp.maximum(n_b - 1, 0).astype(jnp.int32))
    tpr_body = jnp.where(i < n_b, b_tps, n_pos) / jnp.maximum(n_pos, 1.0)
    fpr_body = jnp.where(i < n_b, b_fps, n_neg) / jnp.maximum(n_neg, 1.0)
    thr_body = jnp.where(i < n_b, b_thr, last_thr)

    zero = jnp.zeros((1,), jnp.float32)
    fpr = jnp.concatenate([zero, fpr_body])
    tpr = jnp.concatenate([zero, tpr_body])
    thresholds = jnp.concatenate([jnp.take(b_thr, 0)[None] + 1, thr_body])
    return fpr, tpr, thresholds


def _multiclass_roc_masked(
    preds: Array, target: Array, mask: Array, num_classes: int
) -> Tuple[Array, Array, Array]:
    """One-vs-rest masked ROC: stacked ``(C, cap + 1)`` arrays (static shapes
    cannot carry per-class dynamic lengths, so capacity mode stacks what the
    eager path returns as lists)."""
    return jax.vmap(
        lambda c: _binary_roc_masked(preds[:, c], (jnp.asarray(target) == c).astype(jnp.int32), mask)
    )(jnp.arange(num_classes))


def _roc_compute_multi_class(
    preds: Array,
    target: Array,
    num_classes: int,
    sample_weights: Optional[Sequence] = None,
) -> Tuple[List[Array], List[Array], List[Array]]:
    """Reference ``roc.py:99-133``."""
    fpr, tpr, thresholds = [], [], []
    for cls in range(num_classes):
        if preds.shape == target.shape:
            target_cls = target[:, cls]
            pos_label = 1
        else:
            target_cls = target
            pos_label = cls
        res = roc(preds=preds[:, cls], target=target_cls, num_classes=1, pos_label=pos_label, sample_weights=sample_weights)
        fpr.append(res[0])
        tpr.append(res[1])
        thresholds.append(res[2])
    return fpr, tpr, thresholds


def _roc_compute(
    preds: Array,
    target: Array,
    num_classes: int,
    pos_label: Optional[int] = None,
    sample_weights: Optional[Sequence] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Reference ``roc.py:136-186``."""
    if num_classes == 1 and preds.ndim == 1:
        if pos_label is None:
            pos_label = 1
        return _roc_compute_single_class(preds, target, pos_label, sample_weights)
    return _roc_compute_multi_class(preds, target, num_classes, sample_weights)


def roc(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    sample_weights: Optional[Sequence] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Receiver operating characteristic (reference ``roc.py:189-282``).

    Example:
        >>> import jax.numpy as jnp
        >>> pred = jnp.array([0, 1, 2, 3])
        >>> target = jnp.array([0, 1, 1, 1])
        >>> fpr, tpr, thresholds = roc(pred, target, pos_label=1)
        >>> fpr
        Array([0., 0., 0., 0., 1.], dtype=float32)
        >>> tpr
        Array([0.        , 0.33333334, 0.6666667 , 1.        , 1.        ],      dtype=float32)
    """
    preds, target, num_classes, pos_label = _roc_update(preds, target, num_classes, pos_label)
    return _roc_compute(preds, target, num_classes, pos_label, sample_weights)

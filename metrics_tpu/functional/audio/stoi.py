"""STOI wrapper (reference ``src/torchmetrics/functional/audio/stoi.py``,
102 LoC).

Same explicit host boundary as PESQ: the ``pystoi`` reference implementation
runs on host numpy per clip; scores come back as a device array.
"""
import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.imports import _PYSTOI_AVAILABLE

Array = jax.Array

__doctest_skip__ = ["short_time_objective_intelligibility"]


def short_time_objective_intelligibility(
    preds: Array, target: Array, fs: int, extended: bool = False, use_device_implementation: bool = False
) -> Array:
    """STOI score per clip (reference ``stoi.py:28-102``).

    Args:
        preds: estimated signal ``[..., time]``.
        target: reference signal ``[..., time]``.
        fs: sampling frequency in Hz.
        extended: use the extended STOI variant.
        use_device_implementation: score with the native JAX implementation
            (``stoi_native.stoi_on_device``) — jittable spectral core,
            differentiable, no ``pystoi`` dependency. Default False keeps
            exact behavioral parity with the reference's pystoi wrapper.
    """
    if use_device_implementation:
        from metrics_tpu.functional.audio.stoi_native import stoi_on_device

        _check_same_shape(jnp.asarray(preds), jnp.asarray(target))
        return stoi_on_device(preds, target, fs=fs, extended=extended)
    if not _PYSTOI_AVAILABLE:
        raise ModuleNotFoundError(
            "STOI metric requires that the `pystoi` package is installed."
            " Install it with `pip install pystoi`, or pass"
            " `use_device_implementation=True` for the native JAX implementation."
        )
    from pystoi import stoi as stoi_backend

    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)

    preds_np = np.asarray(preds, dtype=np.float32)
    target_np = np.asarray(target, dtype=np.float32)
    if preds_np.ndim == 1:
        scores = np.float32(stoi_backend(target_np, preds_np, fs, extended))
    else:
        flat_p = preds_np.reshape(-1, preds_np.shape[-1])
        flat_t = target_np.reshape(-1, target_np.shape[-1])
        scores = np.asarray(
            [stoi_backend(t, p, fs, extended) for t, p in zip(flat_t, flat_p)], dtype=np.float32
        ).reshape(preds_np.shape[:-1])
    return jnp.asarray(scores)

"""Signal-to-distortion ratio kernels (reference
``src/torchmetrics/functional/audio/sdr.py``, 279 LoC).

TPU-first redesign of the BSS-eval SDR: the optimal distortion filter is
found from FFT auto/cross-correlations (XLA FFT on device), and the
``R h = b`` Toeplitz system is solved either by a dense batched
``jnp.linalg.solve`` (default; an L x L solve is cheap on the MXU for the
reference's L=512) or by an on-device conjugate-gradient loop whose matvec
uses circulant embedding — the role the reference delegates to the optional
``fast_bss_eval`` wheel. Everything runs in fp32: the reference upcasts to
fp64, which TPUs only emulate; the unit-norm pre-scaling keeps the system
well-conditioned and the dB-scale result agrees to ~1e-3.
"""
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


def _compute_autocorr_crosscorr(target: Array, preds: Array, corr_len: int):
    """FFT-based autocorrelation of ``target`` and cross-correlation with
    ``preds`` (reference ``sdr.py:71-116``), truncated to ``corr_len``."""
    n_fft = _next_pow2(preds.shape[-1] + target.shape[-1] - 1)
    t_fft = jnp.fft.rfft(target, n=n_fft, axis=-1)
    p_fft = jnp.fft.rfft(preds, n=n_fft, axis=-1)
    r_0 = jnp.fft.irfft(t_fft.real**2 + t_fft.imag**2, n=n_fft)[..., :corr_len]
    b = jnp.fft.irfft(jnp.conj(t_fft) * p_fft, n=n_fft, axis=-1)[..., :corr_len]
    return r_0, b


def _symmetric_toeplitz(vector: Array) -> Array:
    """Symmetric Toeplitz matrix ``M[..., i, j] = vector[..., |i-j|]``
    (reference ``sdr.py:44-68``) — built by one gather, no strided views."""
    length = vector.shape[-1]
    idx = jnp.abs(jnp.arange(length)[:, None] - jnp.arange(length)[None, :])
    return vector[..., idx]


def _toeplitz_matvec(r_0: Array, x: Array, n_fft: int) -> Array:
    """Multiply the symmetric Toeplitz matrix defined by first row ``r_0``
    with ``x`` via circulant embedding: one rfft/irfft pair instead of an
    L x L contraction."""
    length = r_0.shape[-1]
    pad = n_fft - (2 * length - 1)
    circ = jnp.concatenate(
        [r_0, jnp.zeros(r_0.shape[:-1] + (pad,), r_0.dtype), jnp.flip(r_0[..., 1:], axis=-1)], axis=-1
    )
    x_f = jnp.fft.rfft(x, n=n_fft, axis=-1)
    c_f = jnp.fft.rfft(circ, axis=-1)
    return jnp.fft.irfft(c_f * x_f, n=n_fft, axis=-1)[..., :length]


def _toeplitz_conjugate_gradient(r_0: Array, b: Array, n_iter: int) -> Array:
    """Plain CG on the SPD Toeplitz system ``R x = b`` with an FFT matvec —
    the on-device analogue of ``fast_bss_eval``'s solver the reference
    imports (``sdr.py:38-41``)."""
    length = r_0.shape[-1]
    n_fft = _next_pow2(2 * length - 1)
    eps = jnp.finfo(b.dtype).eps

    x0 = jnp.zeros_like(b)
    r = b - _toeplitz_matvec(r_0, x0, n_fft)
    p = r
    rs = jnp.sum(r * r, axis=-1, keepdims=True)

    def body(_, carry):
        x, r, p, rs = carry
        ap = _toeplitz_matvec(r_0, p, n_fft)
        alpha = rs / (jnp.sum(p * ap, axis=-1, keepdims=True) + eps)
        x = x + alpha * p
        r = r - alpha * ap
        rs_new = jnp.sum(r * r, axis=-1, keepdims=True)
        p = r + (rs_new / (rs + eps)) * p
        return x, r, p, rs_new

    x, _, _, _ = lax.fori_loop(0, n_iter, body, (x0, r, p, rs))
    return x


def signal_distortion_ratio(
    preds: Array,
    target: Array,
    use_cg_iter: Optional[int] = None,
    filter_length: int = 512,
    zero_mean: bool = False,
    load_diag: Optional[float] = None,
) -> Array:
    """SDR in dB over the last axis (reference ``sdr.py:119-240``).

    Args:
        preds: estimated signal ``[..., time]``.
        target: reference signal ``[..., time]``.
        use_cg_iter: if given, solve the filter system with that many
            conjugate-gradient iterations (on device) instead of the dense
            solve. ``10`` is typically enough.
        filter_length: length of the allowed distortion filter.
        zero_mean: subtract the per-signal mean first.
        load_diag: optional diagonal loading to stabilize near-singular
            autocorrelations (e.g. silent references).
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)

    dtype = preds.dtype if jnp.issubdtype(preds.dtype, jnp.floating) else jnp.float32
    preds = preds.astype(dtype)
    target = target.astype(dtype)

    if zero_mean:
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
        target = target - jnp.mean(target, axis=-1, keepdims=True)

    # unit-norm scaling keeps the Toeplitz system well conditioned in fp32
    target = target / jnp.clip(jnp.linalg.norm(target, axis=-1, keepdims=True), min=1e-6)
    preds = preds / jnp.clip(jnp.linalg.norm(preds, axis=-1, keepdims=True), min=1e-6)

    r_0, b = _compute_autocorr_crosscorr(target, preds, corr_len=filter_length)

    if load_diag is not None:
        r_0 = r_0.at[..., 0].add(load_diag)

    if use_cg_iter is not None:
        sol = _toeplitz_conjugate_gradient(r_0, b, n_iter=use_cg_iter)
    else:
        r = _symmetric_toeplitz(r_0)
        sol = jnp.linalg.solve(r, b[..., None])[..., 0]

    coh = jnp.einsum("...l,...l->...", b, sol)

    # The reference computes the distortion energy as 1 - coh (it runs in
    # fp64). In fp32 that difference cancels catastrophically above ~40 dB
    # (and coh can round past 1.0, NaN-ing the log). Instead evaluate the
    # projection residual ``preds - target (*) sol`` in the time domain —
    # a sum of small squares, accurate at any SDR, identical to 1 - coh in
    # exact arithmetic.
    time_len = preds.shape[-1]
    out_len = time_len + filter_length - 1
    n_full = _next_pow2(out_len)
    proj = jnp.fft.irfft(
        jnp.fft.rfft(target, n=n_full, axis=-1) * jnp.fft.rfft(sol, n=n_full, axis=-1), n=n_full, axis=-1
    )[..., :out_len]
    preds_pad = jnp.concatenate(
        [preds, jnp.zeros(preds.shape[:-1] + (out_len - time_len,), preds.dtype)], axis=-1
    )
    distortion = jnp.sum((preds_pad - proj) ** 2, axis=-1)

    ratio = coh / distortion
    return 10.0 * jnp.log10(ratio)


def scale_invariant_signal_distortion_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SI-SDR in dB over the last axis (reference ``sdr.py:243-279``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> print(f"{scale_invariant_signal_distortion_ratio(preds, target):.4f}")
        18.4030
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps

    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)

    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + eps) / (
        jnp.sum(target**2, axis=-1, keepdims=True) + eps
    )
    target_scaled = alpha * target
    noise = target_scaled - preds
    val = (jnp.sum(target_scaled**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(val)

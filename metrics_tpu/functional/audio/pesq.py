"""PESQ wrapper (reference ``src/torchmetrics/functional/audio/pesq.py``,
101 LoC).

PESQ is an ITU-T P.862 C implementation — inherently host-side, like the
reference's use of the ``pesq`` wheel. This is an explicit host boundary
(SURVEY.md §7 hard part #4): inputs are pulled to host numpy, scored per
clip, and the scores returned as a device array. Gated on the optional
``pesq`` package.
"""
import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.imports import _PESQ_AVAILABLE

Array = jax.Array

__doctest_skip__ = ["perceptual_evaluation_speech_quality"]


def perceptual_evaluation_speech_quality(preds: Array, target: Array, fs: int, mode: str) -> Array:
    """PESQ score per clip (reference ``pesq.py:30-101``).

    Args:
        preds: estimated signal ``[..., time]``.
        target: reference signal ``[..., time]``.
        fs: sampling frequency — 8000 or 16000 Hz.
        mode: ``'wb'`` (wide-band, 16 kHz only) or ``'nb'`` (narrow-band).
    """
    if not _PESQ_AVAILABLE:
        raise ModuleNotFoundError(
            "PESQ metric requires that the `pesq` package is installed."
            " Install it with `pip install pesq`."
        )
    import pesq as pesq_backend

    if fs not in (8000, 16000):
        raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
    if mode not in ("wb", "nb"):
        raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")

    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)

    preds_np = np.asarray(preds, dtype=np.float32)
    target_np = np.asarray(target, dtype=np.float32)
    if preds_np.ndim == 1:
        scores = np.float32(pesq_backend.pesq(fs, target_np, preds_np, mode))
    else:
        flat_p = preds_np.reshape(-1, preds_np.shape[-1])
        flat_t = target_np.reshape(-1, target_np.shape[-1])
        scores = np.asarray(
            [pesq_backend.pesq(fs, t, p, mode) for t, p in zip(flat_t, flat_p)], dtype=np.float32
        ).reshape(preds_np.shape[:-1])
    return jnp.asarray(scores)

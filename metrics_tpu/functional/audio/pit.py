"""Permutation-invariant training kernels (reference
``src/torchmetrics/functional/audio/pit.py``, 181 LoC).

TPU-first redesign: the best permutation is found by a single vectorized
gather over the static ``(S!, S)`` permutation table — no scipy
``linear_sum_assignment`` host call, no permutation cache keyed by device.
The whole search jits: ``metric_mtx`` is ``(batch, S, S)``, the per-
permutation scores are one ``take_along_axis`` + mean, and argmax picks the
winner. Exhaustive search is exact for the small speaker counts PIT is used
with (S! = 720 at S=6 is still trivial on device).
"""
from itertools import permutations
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _permutation_table(spk_num: int) -> Array:
    """Static ``(S!, S)`` table of all speaker permutations."""
    return jnp.asarray(list(permutations(range(spk_num))), dtype=jnp.int32)


def permutation_invariant_training(
    preds: Array, target: Array, metric_func: Callable, eval_func: str = "max", **kwargs: Any
) -> Tuple[Array, Array]:
    """PIT (reference ``pit.py:96-166``): evaluate ``metric_func`` for every
    (target speaker, predicted speaker) pair and pick the permutation with
    the best mean metric.

    Args:
        preds: ``[batch, spk, ...]`` estimates.
        target: ``[batch, spk, ...]`` references.
        metric_func: batch metric, called as ``metric_func(preds[:, i],
            target[:, j], **kwargs) -> [batch]``.
        eval_func: ``"max"`` or ``"min"`` — whether larger is better.

    Returns:
        ``(best_metric [batch], best_perm [batch, spk])``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.audio import scale_invariant_signal_distortion_ratio
        >>> preds = jnp.asarray([[[-0.0579, 0.3560, -0.9604], [-0.1719, 0.3205, 0.2951]]])
        >>> target = jnp.asarray([[[1.0958, -0.1648, 0.5228], [-0.4100, 1.1942, -0.5103]]])
        >>> best_metric, best_perm = permutation_invariant_training(
        ...     preds, target, scale_invariant_signal_distortion_ratio, 'max')
        >>> print(f"{best_metric[0]:.4f}", best_perm[0])
        -5.1091 [0 1]
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.shape[0:2] != target.shape[0:2]:
        raise RuntimeError(
            "Predictions and targets are expected to have the same shape at the batch and speaker dimensions"
        )
    if eval_func not in ("max", "min"):
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if target.ndim < 2:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    spk_num = target.shape[1]
    # metric matrix: rows = target speaker, cols = predicted speaker.
    # The S*S metric_func calls unroll at trace time (S is static and small);
    # each call stays batched over the leading axis.
    rows = [
        jnp.stack(
            [metric_func(preds[:, p_idx, ...], target[:, t_idx, ...], **kwargs) for p_idx in range(spk_num)],
            axis=-1,
        )
        for t_idx in range(spk_num)
    ]
    metric_mtx = jnp.stack(rows, axis=-2)  # (batch, spk_t, spk_p)

    perms = _permutation_table(spk_num)  # (P, S)
    # score of permutation k = mean_j metric_mtx[:, j, perms[k, j]]
    gathered = jnp.take_along_axis(metric_mtx, perms.T[None, :, :], axis=2)
    # gathered: (batch, S, P) — entry [b, j, k] = metric_mtx[b, j, perms[k, j]]
    metric_of_ps = gathered.mean(axis=1)  # (batch, P)

    if eval_func == "max":
        best_idx = jnp.argmax(metric_of_ps, axis=-1)
        best_metric = jnp.max(metric_of_ps, axis=-1)
    else:
        best_idx = jnp.argmin(metric_of_ps, axis=-1)
        best_metric = jnp.min(metric_of_ps, axis=-1)
    best_perm = perms[best_idx]
    return best_metric, best_perm


def pit_permutate(preds: Array, perm: Array) -> Array:
    """Reorder ``preds`` speakers by ``perm`` (reference ``pit.py:169-181``)."""
    preds = jnp.asarray(preds)
    perm = jnp.asarray(perm)
    idx = perm.reshape(perm.shape + (1,) * (preds.ndim - 2))
    return jnp.take_along_axis(preds, idx, axis=1)

"""Signal-to-noise ratio kernels (reference
``src/torchmetrics/functional/audio/snr.py``, 90 LoC).

Pure elementwise/reduction math over the trailing time axis — jittable,
vmappable, and shardable over any leading batch axes as-is.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio
from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SNR in dB over the last axis (reference ``snr.py:22-66``).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> print(f"{signal_noise_ratio(preds, target):.4f}")
        16.1805
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps

    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)

    noise = target - preds
    snr_value = (jnp.sum(target**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(snr_value)


def scale_invariant_signal_noise_ratio(preds: Array, target: Array) -> Array:
    """SI-SNR (reference ``snr.py:69-90``): SI-SDR with zero-mean inputs.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> print(f"{scale_invariant_signal_noise_ratio(preds, target):.4f}")
        15.0918
    """
    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=True)

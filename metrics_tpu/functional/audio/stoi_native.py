"""On-device STOI (Short-Time Objective Intelligibility) in pure JAX.

The reference only *wraps* the host-side ``pystoi`` package
(``/root/reference/src/torchmetrics/functional/audio/stoi.py:1-102``, a
C-free numpy implementation executed clip-by-clip on CPU). This module
implements the published algorithm (Taal, Hendriks, Heusdens, Jensen,
"An Algorithm for Intelligibility Prediction of Time-Frequency Weighted
Noisy Speech", IEEE TASLP 2011; extended variant Jensen & Taal 2016)
directly in JAX:

- the spectral core (STFT, third-octave band grouping, segment
  normalization/clipping, correlation) is jittable, vmappable, and
  **differentiable** — usable as a training objective, which the pystoi
  wrapper can never be;
- silent-frame removal (the one inherently data-dependent-shape step) runs
  host-side in numpy exactly like pystoi's ``remove_silent_frames``
  (windowed framing, 40 dB energy gate relative to the loudest clean
  frame, overlap-add reconstruction), and can be disabled for fully
  compiled use on pre-voiced segments.

Constants follow the published spec: 10 kHz sample rate, 256-sample frames
with 50% overlap, 512-point FFT, 15 one-third octave bands from 150 Hz,
N = 30-frame (384 ms) segments, -15 dB signal-to-distortion clipping.
"""
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

FS = 10_000
N_FRAME = 256
NFFT = 512
NUM_BANDS = 15
MIN_FREQ = 150.0
SEG_LEN = 30  # frames per segment (384 ms)
BETA = -15.0  # clipping threshold, dB
DYN_RANGE = 40.0  # VAD dynamic range, dB
_EPS = np.finfo(np.float32).eps


def _hann(framelen: int) -> np.ndarray:
    # the spec's window: hanning without the zero endpoints
    return np.hanning(framelen + 2)[1:-1].astype(np.float32)


def third_octave_matrix(
    fs: int = FS, nfft: int = NFFT, num_bands: int = NUM_BANDS, min_freq: float = MIN_FREQ
) -> np.ndarray:
    """``(num_bands, nfft//2 + 1)`` 0/1 matrix grouping FFT bins into
    one-third octave bands with nearest-bin edges."""
    f = np.linspace(0, fs, nfft + 1)[: nfft // 2 + 1]
    k = np.arange(num_bands, dtype=np.float64)
    cf = (2.0 ** (k / 3.0)) * min_freq
    freq_low = cf / (2.0 ** (1.0 / 6.0))
    freq_high = cf * (2.0 ** (1.0 / 6.0))
    obm = np.zeros((num_bands, f.size), np.float32)
    for i in range(num_bands):
        lo = int(np.argmin((f - freq_low[i]) ** 2))
        hi = int(np.argmin((f - freq_high[i]) ** 2))
        obm[i, lo:hi] = 1.0
    return obm


def remove_silent_frames(
    x: np.ndarray, y: np.ndarray, dyn_range: float = DYN_RANGE, framelen: int = N_FRAME, hop: int = N_FRAME // 2
) -> Tuple[np.ndarray, np.ndarray]:
    """Drop frames of the *clean* signal more than ``dyn_range`` dB below its
    loudest frame, applying the same mask to both signals, and overlap-add
    the kept windowed frames back into time series.

    Host-side by necessity: the kept-frame count is data-dependent, which has
    no static-shape formulation. Pass ``vad=False`` to the scorer for a fully
    compiled path on pre-voiced material.
    """
    w = _hann(framelen)
    starts = range(0, max(len(x) - framelen + 1, 0), hop)
    x_frames = np.stack([w * x[i : i + framelen] for i in starts]) if len(x) >= framelen else np.zeros((0, framelen))
    y_frames = np.stack([w * y[i : i + framelen] for i in starts]) if len(y) >= framelen else np.zeros((0, framelen))
    energies = 20.0 * np.log10(np.linalg.norm(x_frames, axis=1) + _EPS)
    mask = energies > energies.max(initial=-np.inf) - dyn_range
    x_frames, y_frames = x_frames[mask], y_frames[mask]
    n_kept = x_frames.shape[0]
    out_len = (n_kept - 1) * hop + framelen if n_kept else 0
    x_sil = np.zeros(out_len, np.float32)
    y_sil = np.zeros(out_len, np.float32)
    for i in range(n_kept):  # overlap-add (50% hann overlap sums to ~1)
        x_sil[i * hop : i * hop + framelen] += x_frames[i]
        y_sil[i * hop : i * hop + framelen] += y_frames[i]
    return x_sil, y_sil


def _band_spectrogram(sig: Array, obm: Array) -> Array:
    """``(num_bands, frames)`` third-octave band magnitudes of a 1-d signal."""
    n_frames = (sig.shape[-1] - N_FRAME) // (N_FRAME // 2) + 1
    idx = jnp.arange(n_frames)[:, None] * (N_FRAME // 2) + jnp.arange(N_FRAME)[None, :]
    frames = sig[idx] * jnp.asarray(_hann(N_FRAME))
    spec = jnp.fft.rfft(frames, NFFT, axis=-1)  # (frames, nfft//2+1)
    power = jnp.abs(spec) ** 2
    return jnp.sqrt(
        jnp.matmul(power, obm.T, precision=jax.lax.Precision.HIGHEST).T + _EPS
    )  # (bands, frames)


def _segments(bands: Array) -> Array:
    """Sliding ``SEG_LEN``-frame segments: ``(n_segs, num_bands, SEG_LEN)``."""
    n_frames = bands.shape[-1]
    n_segs = n_frames - SEG_LEN + 1
    idx = jnp.arange(n_segs)[:, None] + jnp.arange(SEG_LEN)[None, :]
    return jnp.moveaxis(bands[:, idx], 0, 1)


def _stoi_from_bands(x_bands: Array, y_bands: Array) -> Array:
    """Classic STOI: per-band segment normalization + clipping + correlation."""
    x = _segments(x_bands)  # (M, J, N): M segments, J bands, N frames
    y = _segments(y_bands)
    norm_x = jnp.linalg.norm(x, axis=-1, keepdims=True)
    norm_y = jnp.linalg.norm(y, axis=-1, keepdims=True)
    y_n = y * (norm_x / (norm_y + _EPS))
    clip = 10.0 ** (-BETA / 20.0)
    y_c = jnp.minimum(y_n, x * (1.0 + clip))
    xm = x - x.mean(-1, keepdims=True)
    ym = y_c - y_c.mean(-1, keepdims=True)
    corr = (xm * ym).sum(-1) / (
        jnp.linalg.norm(xm, axis=-1) * jnp.linalg.norm(ym, axis=-1) + _EPS
    )
    return corr.mean()


def _estoi_from_bands(x_bands: Array, y_bands: Array) -> Array:
    """Extended STOI: row- then column-normalized segment correlation."""
    x = _segments(x_bands)
    y = _segments(y_bands)

    def _rowcol_normalize(s):
        s = s - s.mean(-1, keepdims=True)
        s = s / (jnp.linalg.norm(s, axis=-1, keepdims=True) + _EPS)
        s = s - s.mean(-2, keepdims=True)
        return s / (jnp.linalg.norm(s, axis=-2, keepdims=True) + _EPS)

    xn = _rowcol_normalize(x)
    yn = _rowcol_normalize(y)
    return (xn * yn).sum((-2, -1)).mean() / SEG_LEN


@partial(jax.jit, static_argnames=("extended",))
def stoi_core(target: Array, preds: Array, extended: bool = False) -> Array:
    """Jittable, differentiable STOI of a (voiced) 10 kHz signal pair."""
    obm = jnp.asarray(third_octave_matrix())
    x_bands = _band_spectrogram(jnp.asarray(target, jnp.float32), obm)
    y_bands = _band_spectrogram(jnp.asarray(preds, jnp.float32), obm)
    return (_estoi_from_bands if extended else _stoi_from_bands)(x_bands, y_bands)


def stoi_on_device(
    preds: Array,
    target: Array,
    fs: int = FS,
    extended: bool = False,
    vad: bool = True,
) -> Array:
    """STOI per clip, computed by the native JAX core.

    Args:
        preds: degraded/processed signal ``[..., time]``.
        target: clean reference signal ``[..., time]``.
        fs: input sample rate; anything other than 10 kHz is polyphase-
            resampled on host (scipy) first, exactly as the pystoi backend
            does internally.
        extended: compute the extended (ESTOI) variant.
        vad: apply silent-frame removal (host-side, data-dependent shape).
            Disable for a fully compiled call on pre-voiced segments.

    Returns:
        score array of shape ``preds.shape[:-1]``.
    """
    preds_np = np.asarray(jnp.asarray(preds), np.float32)
    target_np = np.asarray(jnp.asarray(target), np.float32)
    if preds_np.shape != target_np.shape:
        raise ValueError(
            f"`preds` and `target` must have the same shape, got {preds_np.shape} vs {target_np.shape}"
        )
    if fs != FS:
        from scipy.signal import resample_poly

        g = int(np.gcd(int(fs), FS))
        preds_np = resample_poly(preds_np, FS // g, fs // g, axis=-1).astype(np.float32)
        target_np = resample_poly(target_np, FS // g, fs // g, axis=-1).astype(np.float32)

    flat_p = preds_np.reshape(-1, preds_np.shape[-1])
    flat_t = target_np.reshape(-1, target_np.shape[-1])
    scores = []
    for t, p in zip(flat_t, flat_p):
        if vad:
            t, p = remove_silent_frames(t, p)
        n_frames = (len(t) - N_FRAME) // (N_FRAME // 2) + 1 if len(t) >= N_FRAME else 0
        if n_frames < SEG_LEN:
            # the published algorithm is undefined on < one segment of
            # voiced audio; mirror pystoi's tiny-score convention
            scores.append(np.float32(1e-5))
            continue
        scores.append(np.asarray(stoi_core(jnp.asarray(t), jnp.asarray(p), extended=extended)))
    return jnp.asarray(np.asarray(scores, np.float32).reshape(preds_np.shape[:-1]))

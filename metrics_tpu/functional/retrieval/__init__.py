"""Functional retrieval API (reference
``src/torchmetrics/functional/retrieval/__init__.py``).

Every kernel operates on one query's 1-d ``(preds, target)`` pair; the module
metrics (``metrics_tpu/retrieval``) group by query id and average these over
queries. All kernels are sort + slice + reduce — static shapes given a static
query length.
"""
from metrics_tpu.functional.retrieval.kernels import (  # noqa: F401
    retrieval_average_precision,
    retrieval_fall_out,
    retrieval_hit_rate,
    retrieval_normalized_dcg,
    retrieval_precision,
    retrieval_precision_recall_curve,
    retrieval_r_precision,
    retrieval_recall,
    retrieval_reciprocal_rank,
)

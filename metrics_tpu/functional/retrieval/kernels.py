"""Per-query retrieval kernels (reference
``src/torchmetrics/functional/retrieval/*.py``).

Boolean-index gathers from the reference (e.g. ``positions[target > 0]``)
are rewritten as masked reductions so each kernel is a fixed sequence of
sort/cumsum/where ops. ``r_precision``'s data-dependent top-R slice needs a
concrete relevant-count and stays eager.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.ops import descending_order
from metrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def _sort_target_by_preds(preds: Array, target: Array) -> Array:
    return target[descending_order(preds)]


def retrieval_average_precision(preds: Array, target: Array) -> Array:
    """AP of one query (reference ``retrieval/average_precision.py:22-49``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([0.2, 0.3, 0.5])
        >>> target = jnp.array([True, False, True])
        >>> retrieval_average_precision(preds, target).round(4)
        Array(0.8333, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    sorted_target = _sort_target_by_preds(preds, target)
    ranks = jnp.arange(1, target.size + 1, dtype=jnp.float32)
    precision_at_hit = jnp.cumsum(sorted_target, axis=0) / ranks
    total = jnp.sum(sorted_target)
    return jnp.where(total == 0, 0.0, jnp.sum(precision_at_hit * sorted_target) / jnp.maximum(total, 1))


def retrieval_reciprocal_rank(preds: Array, target: Array) -> Array:
    """RR of one query (reference ``retrieval/reciprocal_rank.py:20-49``).

    Example:
        >>> import jax.numpy as jnp
        >>> retrieval_reciprocal_rank(jnp.array([0.2, 0.3, 0.5]), jnp.array([False, False, True]))
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    sorted_target = _sort_target_by_preds(preds, target)
    ranks = jnp.arange(1, target.size + 1, dtype=jnp.float32)
    first_pos = jnp.min(jnp.where(sorted_target > 0, ranks, jnp.inf))
    return jnp.where(jnp.sum(sorted_target) == 0, 0.0, 1.0 / first_pos)


def retrieval_precision(preds: Array, target: Array, k: Optional[int] = None, adaptive_k: bool = False) -> Array:
    """Precision@k of one query (reference ``retrieval/precision.py:22-65``).

    Example:
        >>> import jax.numpy as jnp
        >>> retrieval_precision(jnp.array([0.2, 0.3, 0.5]), jnp.array([True, False, True]), k=2)
        Array(0.5, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    if k is None or (adaptive_k and k > preds.shape[-1]):
        k = preds.shape[-1]
    if not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")
    relevant = jnp.sum(_sort_target_by_preds(preds, target)[: min(k, preds.shape[-1])]).astype(jnp.float32)
    return jnp.where(jnp.sum(target) == 0, 0.0, relevant / k)


def retrieval_recall(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Recall@k of one query (reference ``retrieval/recall.py:22-61``).

    Example:
        >>> import jax.numpy as jnp
        >>> retrieval_recall(jnp.array([0.2, 0.3, 0.5]), jnp.array([True, False, True]), k=2)
        Array(0.5, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if k is None:
        k = preds.shape[-1]
    if not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")
    relevant = jnp.sum(_sort_target_by_preds(preds, target)[:k]).astype(jnp.float32)
    total = jnp.sum(target)
    return jnp.where(total == 0, 0.0, relevant / jnp.maximum(total, 1))


def retrieval_fall_out(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Fall-out@k of one query (reference ``retrieval/fall_out.py:22-62``).

    Example:
        >>> import jax.numpy as jnp
        >>> retrieval_fall_out(jnp.array([0.2, 0.3, 0.5]), jnp.array([True, False, True]), k=2)
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if k is None:
        k = preds.shape[-1]
    if not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")
    target = 1 - target
    relevant = jnp.sum(_sort_target_by_preds(preds, target)[:k]).astype(jnp.float32)
    total = jnp.sum(target)
    return jnp.where(total == 0, 0.0, relevant / jnp.maximum(total, 1))


def retrieval_hit_rate(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """HitRate@k of one query (reference ``retrieval/hit_rate.py:22-57``).

    Example:
        >>> import jax.numpy as jnp
        >>> retrieval_hit_rate(jnp.array([0.2, 0.3, 0.5]), jnp.array([True, False, True]), k=2)
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if k is None:
        k = preds.shape[-1]
    if not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")
    relevant = jnp.sum(_sort_target_by_preds(preds, target)[:k])
    return (relevant > 0).astype(jnp.float32)


def retrieval_r_precision(preds: Array, target: Array) -> Array:
    """R-precision of one query (reference ``retrieval/r_precision.py:20-49``).

    The top-R slice depends on the relevant count → concrete inputs only
    (the module metrics compute eagerly on gathered state anyway).

    Example:
        >>> import jax.numpy as jnp
        >>> retrieval_r_precision(jnp.array([0.2, 0.3, 0.5]), jnp.array([True, False, True]))
        Array(0.5, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    relevant_number = int(jnp.sum(target))
    if not relevant_number:
        return jnp.asarray(0.0)
    relevant = jnp.sum(_sort_target_by_preds(preds, target)[:relevant_number]).astype(jnp.float32)
    return relevant / relevant_number


def _dcg(target: Array) -> Array:
    """Reference ``retrieval/ndcg.py:20-22``."""
    denom = jnp.log2(jnp.arange(target.shape[-1], dtype=jnp.float32) + 2.0)
    return (target / denom).sum(axis=-1)


def retrieval_normalized_dcg(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """nDCG@k of one query (reference ``retrieval/ndcg.py:25-71``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([.1, .2, .3, 4, 70])
        >>> target = jnp.array([10, 0, 0, 1, 5])
        >>> retrieval_normalized_dcg(preds, target).round(4)
        Array(0.6957, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target, allow_non_binary_target=True)
    k = preds.shape[-1] if k is None else k
    if not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")

    sorted_target = _sort_target_by_preds(preds, target)[:k]
    ideal_target = jnp.sort(target)[::-1][:k]

    ideal_dcg = _dcg(ideal_target)
    target_dcg = _dcg(sorted_target)
    return jnp.where(ideal_dcg == 0, 0.0, target_dcg / jnp.where(ideal_dcg == 0, 1.0, ideal_dcg))


def retrieval_precision_recall_curve(
    preds: Array, target: Array, max_k: Optional[int] = None, adaptive_k: bool = False
) -> Tuple[Array, Array, Array]:
    """Precision/recall at every k of one query
    (reference ``retrieval/precision_recall_curve.py:22-97``).

    Example:
        >>> import jax.numpy as jnp
        >>> p, r, k = retrieval_precision_recall_curve(jnp.array([0.2, 0.3, 0.5]), jnp.array([True, False, True]), max_k=2)
        >>> p, r, k
        (Array([1. , 0.5], dtype=float32), Array([0.5, 0.5], dtype=float32), Array([1, 2], dtype=int32))
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    if max_k is None:
        max_k = preds.shape[-1]
    if not (isinstance(max_k, int) and max_k > 0):
        raise ValueError("`max_k` has to be a positive integer or None")

    n = preds.shape[-1]
    if adaptive_k and max_k > n:
        topk = jnp.concatenate([jnp.arange(1, n + 1), jnp.full((max_k - n,), n)])
    else:
        topk = jnp.arange(1, max_k + 1)

    sorted_target = _sort_target_by_preds(preds, target)[: min(max_k, n)].astype(jnp.float32)
    relevant = jnp.cumsum(jnp.pad(sorted_target, (0, max(0, max_k - sorted_target.shape[0]))), axis=0)
    total = jnp.sum(target)
    recall = jnp.where(total == 0, 0.0, relevant / jnp.maximum(total, 1))
    precision = jnp.where(total == 0, 0.0, relevant / topk)
    return precision, recall, topk.astype(jnp.int32)


# --------------------------------------------------------------------------
# Masked row kernels — the vectorized per-query form (SURVEY.md §7 step 5)
#
# Each takes one (L,) padded row plus a validity mask and is vmapped over a
# (Q, L) bucket of queries by `RetrievalMetric.compute`, replacing the
# reference's per-query Python loop (`retrieval/base.py:110-139`,
# `utilities/data.py:210`) with O(#size-buckets) device dispatches. Padding
# rows sort last (preds forced to -inf) and carry zero target weight.
# --------------------------------------------------------------------------


def _masked_sort(preds: Array, target: Array, mask: Array) -> Tuple[Array, Array]:
    """Target and mask reordered by descending score, padding last."""
    order = descending_order(jnp.where(mask, preds, -jnp.inf))
    return (target * mask)[order].astype(jnp.float32), mask[order]


def _masked_average_precision(preds: Array, target: Array, mask: Array) -> Array:
    st, _ = _masked_sort(preds, target, mask)
    ranks = jnp.arange(1, preds.shape[-1] + 1, dtype=jnp.float32)
    pah = jnp.cumsum(st) / ranks
    total = jnp.sum(st)
    return jnp.where(total == 0, 0.0, jnp.sum(pah * st) / jnp.maximum(total, 1))


def _masked_reciprocal_rank(preds: Array, target: Array, mask: Array) -> Array:
    st, _ = _masked_sort(preds, target, mask)
    ranks = jnp.arange(1, preds.shape[-1] + 1, dtype=jnp.float32)
    first = jnp.min(jnp.where(st > 0, ranks, jnp.inf))
    return jnp.where(jnp.sum(st) == 0, 0.0, 1.0 / first)


def _masked_precision(preds: Array, target: Array, mask: Array, k: Optional[int], adaptive_k: bool) -> Array:
    st, _ = _masked_sort(preds, target, mask)
    length = preds.shape[-1]
    n = jnp.sum(mask.astype(jnp.float32))
    if k is None:
        k_eff = n
    elif adaptive_k:
        k_eff = jnp.where(k > n, n, float(k))
    else:
        k_eff = jnp.asarray(float(k))
    ranks = jnp.arange(1, length + 1, dtype=jnp.float32)
    relevant = jnp.sum(st * (ranks <= k_eff))
    return jnp.where(jnp.sum(st) == 0, 0.0, relevant / k_eff)


def _masked_recall(preds: Array, target: Array, mask: Array, k: Optional[int]) -> Array:
    st, _ = _masked_sort(preds, target, mask)
    length = preds.shape[-1]
    n = jnp.sum(mask.astype(jnp.float32))
    k_eff = n if k is None else jnp.asarray(float(k))
    ranks = jnp.arange(1, length + 1, dtype=jnp.float32)
    relevant = jnp.sum(st * (ranks <= k_eff))
    total = jnp.sum(st)
    return jnp.where(total == 0, 0.0, relevant / jnp.maximum(total, 1))


def _masked_fall_out(preds: Array, target: Array, mask: Array, k: Optional[int]) -> Array:
    neg = jnp.where(mask, 1.0 - target.astype(jnp.float32), 0.0)
    sn, _ = _masked_sort(preds, neg, mask)
    length = preds.shape[-1]
    n = jnp.sum(mask.astype(jnp.float32))
    k_eff = n if k is None else jnp.asarray(float(k))
    ranks = jnp.arange(1, length + 1, dtype=jnp.float32)
    retrieved_neg = jnp.sum(sn * (ranks <= k_eff))
    total_neg = jnp.sum(neg)
    return jnp.where(total_neg == 0, 0.0, retrieved_neg / jnp.maximum(total_neg, 1))


def _masked_hit_rate(preds: Array, target: Array, mask: Array, k: Optional[int]) -> Array:
    st, _ = _masked_sort(preds, target, mask)
    length = preds.shape[-1]
    n = jnp.sum(mask.astype(jnp.float32))
    k_eff = n if k is None else jnp.asarray(float(k))
    ranks = jnp.arange(1, length + 1, dtype=jnp.float32)
    return (jnp.sum(st * (ranks <= k_eff)) > 0).astype(jnp.float32)


def _masked_r_precision(preds: Array, target: Array, mask: Array) -> Array:
    st, _ = _masked_sort(preds, target, mask)
    ranks = jnp.arange(1, preds.shape[-1] + 1, dtype=jnp.float32)
    r = jnp.sum(st)
    relevant = jnp.sum(st * (ranks <= r))
    return jnp.where(r == 0, 0.0, relevant / jnp.maximum(r, 1))


def _masked_normalized_dcg(preds: Array, target: Array, mask: Array, k: Optional[int]) -> Array:
    st, _ = _masked_sort(preds, target, mask)
    length = preds.shape[-1]
    it = jnp.sort(jnp.where(mask, target.astype(jnp.float32), -jnp.inf))[::-1]
    it = jnp.where(jnp.isfinite(it), it, 0.0)
    n = jnp.sum(mask.astype(jnp.float32))
    k_eff = n if k is None else jnp.asarray(float(k))
    ranks = jnp.arange(1, length + 1, dtype=jnp.float32)
    discount = (ranks <= k_eff) / jnp.log2(ranks + 1.0)
    dcg = jnp.sum(st * discount)
    ideal = jnp.sum(it * discount)
    return jnp.where(ideal == 0, 0.0, dcg / jnp.where(ideal == 0, 1.0, ideal))


def _masked_precision_recall_curve(
    preds: Array, target: Array, mask: Array, max_k: int, adaptive_k: bool
) -> Tuple[Array, Array]:
    st, _ = _masked_sort(preds, target, mask)
    length = preds.shape[-1]
    n = jnp.sum(mask.astype(jnp.float32))
    ks = jnp.arange(1, max_k + 1, dtype=jnp.float32)
    topk = jnp.where(adaptive_k & (ks > n), jnp.maximum(n, 1.0), ks) if adaptive_k else ks
    ranks = jnp.arange(1, length + 1, dtype=jnp.float32)
    rel_at_k = jnp.sum(st[None, :] * (ranks[None, :] <= ks[:, None]), axis=1)
    total = jnp.sum(st)
    recall = jnp.where(total == 0, 0.0, rel_at_k / jnp.maximum(total, 1))
    precision = jnp.where(total == 0, 0.0, rel_at_k / topk)
    return precision, recall

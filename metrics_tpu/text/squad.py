"""SQuAD module (reference ``text/squad.py:24-115``)."""
from typing import Any, Dict

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.squad import (
    PREDS_TYPE,
    TARGETS_TYPE,
    _squad_compute,
    _squad_input_check,
    _squad_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class SQuAD(Metric):
    """SQuAD exact-match / F1 with three scalar ``sum`` states.

    Example:
        >>> from metrics_tpu import SQuAD
        >>> metric = SQuAD()
        >>> preds = [{"prediction_text": "the cat", "id": "1"}]
        >>> target = [{"answers": {"text": ["the cat"], "answer_start": [0]}, "id": "1"}]
        >>> out = metric(preds, target)
        >>> float(out["exact_match"]), float(out["f1"])
        (100.0, 100.0)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    jittable_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("f1_score", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("exact_match", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: PREDS_TYPE, target: TARGETS_TYPE) -> None:
        preds_dict, target_dict = _squad_input_check(preds, target)
        f1, exact_match, total = _squad_update(preds_dict, target_dict)
        self.f1_score += f1
        self.exact_match += exact_match
        self.total += total

    def compute(self) -> Dict[str, Array]:
        return _squad_compute(self.f1_score, self.exact_match, self.total)

"""BERTScore module (reference ``text/bert.py:41-215``).

The reference tokenizes on update and stores ``input_ids``/``attention_mask``
cat lists, running the model at compute (``text/bert.py:170-173``). Here the
injected encoder runs on update and the module accumulates embedding/mask/id
arrays as cat states — sync is the standard ragged pad-gather, and compute is
the jittable matching kernel (IDF needs the full reference corpus, hence
compute-time weighting).
"""
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.bert import (
    _bert_score_from_embeddings,
    _encode,
    _idf_scale,
    _idf_weights,
    _pad_to,
    _strip_special_tokens,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class BERTScore(Metric):
    """Accumulating BERTScore.

    With ``encoder=None`` the bundled :class:`~metrics_tpu.functional.text.
    bert.HashTextEncoder` runs — deterministic hash-vocab embeddings, NOT a
    pretrained language model: scores are self-consistent (identity = 1.0,
    related > unrelated) but not comparable to published BERTScore numbers,
    and a warning says so once. Inject ``encoder=`` wrapping a local HF
    model for calibrated scores.

    Example (bundled encoder; identical pairs score 1.0 by construction):
        >>> import warnings
        >>> from metrics_tpu import BERTScore
        >>> with warnings.catch_warnings():
        ...     warnings.simplefilter("ignore")
        ...     metric = BERTScore()
        ...     metric.update(["the cat sat on the mat"], ["the cat sat on the mat"])
        >>> {k: round(float(v.mean()), 4) for k, v in metric.compute().items()}
        {'f1': 1.0, 'precision': 1.0, 'recall': 1.0}
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    jittable_update = False

    def __init__(
        self,
        encoder: Optional[Callable[[List[str]], Tuple[np.ndarray, np.ndarray, np.ndarray]]] = None,
        idf: bool = False,
        max_length: int = 512,
        rescale_with_baseline: bool = False,
        baseline: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.encoder = encoder
        self.idf = idf
        self.max_length = max_length
        if rescale_with_baseline and baseline is None:
            raise ValueError(
                "`rescale_with_baseline` requires the `baseline` argument (no baseline files are bundled)."
            )
        self.rescale_with_baseline = rescale_with_baseline
        self.baseline = baseline

        for name in (
            "pred_embeddings", "pred_masks", "pred_ids",
            "target_embeddings", "target_masks", "target_ids",
        ):
            self.add_state(name, default=[], dist_reduce_fx="cat")

    def update(
        self,
        preds: Union[Sequence[str], Dict[str, Any]],
        target: Union[Sequence[str], Dict[str, Any]],
    ) -> None:
        pred_emb, pred_mask, pred_ids = _encode(preds, self.encoder, self.max_length)
        target_emb, target_mask, target_ids = _encode(target, self.encoder, self.max_length)
        if pred_emb.shape[0] != target_emb.shape[0]:
            raise ValueError("Expected the same number of predicted and reference sentences.")
        self.pred_embeddings.append(jnp.asarray(pred_emb))
        self.pred_masks.append(jnp.asarray(pred_mask))
        self.pred_ids.append(jnp.asarray(pred_ids))
        self.target_embeddings.append(jnp.asarray(target_emb))
        self.target_masks.append(jnp.asarray(target_mask))
        self.target_ids.append(jnp.asarray(target_ids))

    def compute(self) -> Dict[str, Array]:
        length = max(
            max(e.shape[1] for e in self.pred_embeddings),
            max(e.shape[1] for e in self.target_embeddings),
        )

        def gather(chunks, pad_len):
            return np.concatenate([_pad_to(np.asarray(c), pad_len) for c in chunks])

        pred_emb = gather(self.pred_embeddings, length)
        pred_mask = gather(self.pred_masks, length)
        pred_ids = gather(self.pred_ids, length)
        target_emb = gather(self.target_embeddings, length)
        target_mask = gather(self.target_masks, length)
        target_ids = gather(self.target_ids, length)

        pred_mask_j = _strip_special_tokens(jnp.asarray(pred_mask))
        target_mask_j = _strip_special_tokens(jnp.asarray(target_mask))
        idf_map = _idf_weights(target_ids, target_mask) if self.idf else None
        pred_scale = jnp.asarray(_idf_scale(pred_ids, np.asarray(pred_mask_j), idf_map))
        target_scale = jnp.asarray(_idf_scale(target_ids, np.asarray(target_mask_j), idf_map))

        precision, recall, f1 = _bert_score_from_embeddings(
            jnp.asarray(pred_emb), pred_mask_j, pred_scale,
            jnp.asarray(target_emb), target_mask_j, target_scale,
        )
        if self.rescale_with_baseline:
            b_p, b_r, b_f = (jnp.asarray(b, jnp.float32) for b in self.baseline)
            precision = (precision - b_p) / (1.0 - b_p)
            recall = (recall - b_r) / (1.0 - b_r)
            f1 = (f1 - b_f) / (1.0 - b_f)
        return {"precision": precision, "recall": recall, "f1": f1}

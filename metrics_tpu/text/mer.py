"""MatchErrorRate module (reference ``text/mer.py:22-77``)."""
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.mer import _mer_compute, _mer_update
from metrics_tpu.metric import Metric

Array = jax.Array


class MatchErrorRate(Metric):
    """Match error rate over accumulated transcript pairs.

    Example:
        >>> from metrics_tpu import MatchErrorRate
        >>> metric = MatchErrorRate()
        >>> metric.update(["the cat sat"], ["the cat sat down"])
        >>> round(float(metric.compute()), 4)
        0.25
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    jittable_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _mer_update(preds, target)
        self.errors += errors
        self.total += total

    def compute(self) -> Array:
        return _mer_compute(self.errors, self.total)

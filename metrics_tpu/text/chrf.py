"""CHRFScore module (reference ``text/chrf.py:30-168``)."""
from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.chrf import _chrf_score_update, _fscore_from_counts
from metrics_tpu.metric import Metric

Array = jax.Array


class CHRFScore(Metric):
    """Corpus chrF/chrF++ with six per-order ``sum`` count states.

    Example:
        >>> from metrics_tpu import CHRFScore
        >>> metric = CHRFScore()
        >>> metric.update(["the cat"], [["the cat"]])
        >>> round(float(metric.compute()), 4)
        1.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    jittable_update = False

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
        if not isinstance(n_word_order, int) or n_word_order < 0:
            raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
        if beta < 0:
            raise ValueError("Expected argument `beta` to be greater than 0.")
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.n_order = float(n_char_order + n_word_order)
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("matching_char", default=jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("matching_word", default=jnp.zeros(n_word_order), dist_reduce_fx="sum")
        self.add_state("pred_char", default=jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("pred_word", default=jnp.zeros(n_word_order), dist_reduce_fx="sum")
        self.add_state("target_char", default=jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("target_word", default=jnp.zeros(n_word_order), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_chrf_score", default=[], dist_reduce_fx="cat")

    def update(
        self, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]
    ) -> None:
        m_char, m_word, p_char, p_word, t_char, t_word, sentence_scores = _chrf_score_update(
            preds, target, self.n_char_order, self.n_word_order, self.beta,
            self.lowercase, self.whitespace,
            collect_sentence_scores=self.return_sentence_level_score,
        )
        self.matching_char += m_char
        self.matching_word += m_word
        self.pred_char += p_char
        self.pred_word += p_word
        self.target_char += t_char
        self.target_word += t_word
        if self.return_sentence_level_score:
            self.sentence_chrf_score.extend(sentence_scores)

    def compute(self):
        score = _fscore_from_counts(
            self.matching_char, self.matching_word, self.pred_char, self.pred_word,
            self.target_char, self.target_word, self.n_order, self.beta,
        )
        if self.return_sentence_level_score:
            return score, jnp.concatenate(self.sentence_chrf_score) if self.sentence_chrf_score else jnp.zeros(0)
        return score

"""WordErrorRate module (reference ``text/wer.py:23-81``)."""
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.wer import _wer_compute, _wer_update
from metrics_tpu.metric import Metric

Array = jax.Array


class WordErrorRate(Metric):
    """Word error rate over accumulated (preds, target) transcript pairs.

    Update takes strings (host tokenization → device wavefront DP), so the
    update itself is not jit-staged; the two scalar ``sum`` states still sync
    with a single fused collective.

    Example:
        >>> from metrics_tpu import WordErrorRate
        >>> metric = WordErrorRate()
        >>> metric.update(["the cat sat"], ["the cat sat down"])
        >>> round(float(metric.compute()), 4)
        0.25
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    jittable_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _wer_update(preds, target)
        self.errors += errors
        self.total += total

    def compute(self) -> Array:
        return _wer_compute(self.errors, self.total)

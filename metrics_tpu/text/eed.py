"""ExtendedEditDistance module (reference ``text/eed.py:25-125``).

Redesign: the reference keeps every sentence score in an unbounded list; here
the default state is a running (sum, count) pair — constant memory, one fused
collective — with the list kept only when sentence-level scores are requested.
"""
from typing import Any, Sequence, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.eed import _eed_update
from metrics_tpu.metric import Metric

Array = jax.Array


class ExtendedEditDistance(Metric):
    """Corpus EED over accumulated (preds, references) pairs.

    Example:
        >>> from metrics_tpu import ExtendedEditDistance
        >>> metric = ExtendedEditDistance()
        >>> metric.update(["the cat sat"], [["the cat sat down"]])
        >>> round(float(metric.compute()), 4)
        0.3434
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    jittable_update = False

    def __init__(
        self,
        language: str = "en",
        return_sentence_level_score: bool = False,
        alpha: float = 2.0,
        rho: float = 0.3,
        deletion: float = 0.2,
        insertion: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        for name, value in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
            if not isinstance(value, float) or value < 0:
                raise ValueError(f"Parameter `{name}` is expected to be a non-negative float.")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion

        self.add_state("score_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sentence_count", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_eed", default=[], dist_reduce_fx="cat")

    def update(
        self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]
    ) -> None:
        scores = _eed_update(
            preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion
        )
        self.score_sum += sum(scores) if scores else 0.0
        self.sentence_count += len(scores)
        if self.return_sentence_level_score:
            self.sentence_eed.extend(jnp.atleast_1d(s) for s in scores)

    def compute(self):
        average = self.score_sum / jnp.maximum(self.sentence_count, 1.0)
        if self.return_sentence_level_score:
            return average, jnp.concatenate(self.sentence_eed) if self.sentence_eed else jnp.zeros(0)
        return average

"""BLEUScore module (reference ``text/bleu.py:26-120``)."""
from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update
from metrics_tpu.metric import Metric

Array = jax.Array


class BLEUScore(Metric):
    """Corpus BLEU accumulated over batches of (preds, references).

    State is four tiny ``sum``-reduced count tensors — the n-gram counting
    itself is host work (strings), so updates run eagerly; sync and the final
    formula are device math.

    Example:
        >>> from metrics_tpu import BLEUScore
        >>> metric = BLEUScore()
        >>> metric.update(["the cat is on the mat"], [["the cat is on the mat"]])
        >>> round(float(metric.compute()), 4)
        1.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    jittable_update = False

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights if weights is not None else [1.0 / n_gram] * n_gram

        self.add_state("preds_len", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_len", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("numerator", default=jnp.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", default=jnp.zeros(n_gram), dist_reduce_fx="sum")

    def update(self, preds: Sequence[str], target: Sequence[Union[str, Sequence[str]]]) -> None:
        preds_list = [preds] if isinstance(preds, str) else preds
        target_list = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
        if len(preds_list) != len(target_list):
            raise ValueError(f"Corpus has different size {len(preds_list)} != {len(target_list)}")
        numerator, denominator, preds_len, target_len = _bleu_score_update(
            preds_list, target_list, self.n_gram
        )
        self.numerator += numerator
        self.denominator += denominator
        self.preds_len += preds_len
        self.target_len += target_len

    def compute(self) -> Array:
        return _bleu_score_compute(
            self.preds_len, self.target_len, self.numerator, self.denominator,
            self.n_gram, self.weights, self.smooth,
        )

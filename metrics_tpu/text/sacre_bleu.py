"""SacreBLEUScore module (reference ``text/sacre_bleu.py:28-110``)."""
from typing import Any, Optional, Sequence

import jax

from metrics_tpu.functional.text.bleu import _bleu_score_update
from metrics_tpu.functional.text.sacre_bleu import _SacreBLEUTokenizer
from metrics_tpu.text.bleu import BLEUScore

Array = jax.Array


class SacreBLEUScore(BLEUScore):
    """BLEU with the standardized sacrebleu tokenization pipeline.

    Example:
        >>> from metrics_tpu import SacreBLEUScore
        >>> metric = SacreBLEUScore()
        >>> metric.update(["the cat is on the mat"], [["the cat is on the mat"]])
        >>> round(float(metric.compute()), 4)
        1.0
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        self.tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)

    def update(self, preds: Sequence[str], target: Sequence[Sequence[str]]) -> None:
        target_list = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
        if len(preds) != len(target_list):
            raise ValueError(f"Corpus has different size {len(preds)} != {len(target_list)}")
        numerator, denominator, preds_len, target_len = _bleu_score_update(
            preds, target_list, self.n_gram, self.tokenizer
        )
        self.numerator += numerator
        self.denominator += denominator
        self.preds_len += preds_len
        self.target_len += target_len

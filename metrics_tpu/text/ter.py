"""TranslationEditRate module (reference ``text/ter.py:25-128``)."""
from typing import Any, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.ter import _TercomTokenizer, _ter_compute, _ter_update
from metrics_tpu.metric import Metric

Array = jax.Array


class TranslationEditRate(Metric):
    """Corpus TER with two scalar ``sum`` states (edits, reference length).

    Example:
        >>> from metrics_tpu import TranslationEditRate
        >>> metric = TranslationEditRate()
        >>> metric.update(["the cat sat"], [["the cat sat down"]])
        >>> round(float(metric.compute()), 4)
        0.25
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    jittable_update = False

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        for name, value in (
            ("normalize", normalize),
            ("no_punctuation", no_punctuation),
            ("lowercase", lowercase),
            ("asian_support", asian_support),
        ):
            if not isinstance(value, bool):
                raise ValueError(f"Expected argument `{name}` to be of type boolean but got {value}.")
        self.tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("total_num_edits", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total_tgt_length", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_ter", default=[], dist_reduce_fx="cat")

    def update(
        self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]
    ) -> None:
        num_edits, tgt_length, sentence_scores = _ter_update(
            preds, target, self.tokenizer, collect_sentence_scores=self.return_sentence_level_score
        )
        self.total_num_edits += num_edits
        self.total_tgt_length += tgt_length
        if self.return_sentence_level_score:
            self.sentence_ter.extend(sentence_scores)

    def compute(self):
        score = _ter_compute(self.total_num_edits, self.total_tgt_length)
        if self.return_sentence_level_score:
            return score, jnp.concatenate(self.sentence_ter) if self.sentence_ter else jnp.zeros(0)
        return score

"""WordInfoPreserved module (reference ``text/wip.py:22-79``)."""
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.wip import _wip_compute, _wip_update
from metrics_tpu.metric import Metric

Array = jax.Array


class WordInfoPreserved(Metric):
    """Word information preserved over accumulated transcript pairs.

    .. note::
        ``higher_is_better`` is **True** here; the reference flags it False.
        Preserved information is a similarity — higher is better — so the
        reference flag reads as a bug (PARITY.md "Class behavior-flag
        divergences"). ``MetricTracker.best_metric`` users porting reference
        code: this build's default direction is maximize.

    Example:
        >>> from metrics_tpu import WordInfoPreserved
        >>> metric = WordInfoPreserved()
        >>> metric.update(["the cat sat"], ["the cat sat down"])
        >>> round(float(metric.compute()), 4)
        0.75
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    jittable_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("preds_total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, target_total, preds_total = _wip_update(preds, target)
        self.errors += errors
        self.target_total += target_total
        self.preds_total += preds_total

    def compute(self) -> Array:
        return _wip_compute(self.errors, self.target_total, self.preds_total)

"""ROUGEScore module (reference ``text/rouge.py:31-159``).

Redesign: the reference keeps one unbounded list state per (key, stat) and
averages at compute; here each (key, stat) is a scalar running ``sum`` plus a
shared sentence count — constant memory, one fused collective to sync.
"""
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax.numpy as jnp

from metrics_tpu.functional.text.rouge import (
    ALLOWED_ACCUMULATE_VALUES,
    ALLOWED_ROUGE_KEYS,
    _STATS,
    _rouge_score_update,
)
from metrics_tpu.metric import Metric


class ROUGEScore(Metric):
    """Corpus ROUGE over accumulated (pred, references) pairs.

    Example:
        >>> from metrics_tpu import ROUGEScore
        >>> metric = ROUGEScore()
        >>> out = metric(["the cat sat"], ["the cat sat down"])
        >>> round(float(out["rouge1_fmeasure"]), 4)
        0.8571
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    jittable_update = False

    def __init__(
        self,
        use_stemmer: bool = False,
        normalizer: Optional[Callable[[str], str]] = None,
        tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
        accumulate: str = "best",
        rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if use_stemmer:
            from nltk.stem.porter import PorterStemmer

            self.stemmer = PorterStemmer()
        else:
            self.stemmer = None
        if accumulate not in ALLOWED_ACCUMULATE_VALUES:
            raise ValueError(
                f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
            )
        if isinstance(rouge_keys, str):
            rouge_keys = (rouge_keys,)
        for key in rouge_keys:
            if key not in ALLOWED_ROUGE_KEYS:
                raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS)}")
        self.rouge_keys = rouge_keys
        self.rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]
        self.normalizer = normalizer
        self.tokenizer = tokenizer
        self.accumulate = accumulate

        for key in rouge_keys:
            for stat in _STATS:
                self.add_state(f"{key}_{stat}", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sentence_count", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(
        self,
        preds: Union[str, Sequence[str]],
        target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    ) -> None:
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [[target]]
        else:
            target = [[tgt] if isinstance(tgt, str) else list(tgt) for tgt in target]
        if len(preds) != len(target):
            raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")

        results = _rouge_score_update(
            preds, target, self.rouge_keys_values, self.accumulate,
            self.stemmer, self.normalizer, self.tokenizer,
        )
        batch_sentences = 0
        for key_name, key_value in zip(self.rouge_keys, self.rouge_keys_values):
            scores = results[key_value]
            batch_sentences = len(scores)
            for stat in _STATS:
                name = f"{key_name}_{stat}"
                setattr(self, name, getattr(self, name) + sum(s[stat] for s in scores))
        self.sentence_count += batch_sentences

    def compute(self):
        count = jnp.maximum(self.sentence_count, 1.0)
        return {
            f"{key}_{stat}": getattr(self, f"{key}_{stat}") / count
            for key in self.rouge_keys
            for stat in _STATS
        }

"""Sliced multi-tenant metrics: per-cohort values via segment-reduce in
one compiled update (see ``slicing.py`` for the state layout, quarantine
semantics, and the label-cardinality cap)."""
from metrics_tpu.sliced.slicing import (
    SlicedMetric,
    SlicedValue,
    reset_sliced_state,
    slices_max_labels,
)

__all__ = ["SlicedMetric", "SlicedValue", "slices_max_labels", "reset_sliced_state"]

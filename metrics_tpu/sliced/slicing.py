"""Sliced multi-tenant metrics: per-cohort values from ONE compiled update.

"Millions of users" means per-cohort / per-segment / per-model-version
metrics, not one global scalar. The naive answer — K independent metric
instances and a host-side demux loop — costs K traced updates per batch
and K separate sync payloads. :class:`SlicedMetric` instead threads a
``(K,)`` slice axis through the wrapped metric's state: every update takes
a ``slice_ids`` row vector alongside the normal arguments and folds ALL
slices in one compiled graph via segment-reduce.

**State layout.** For each wrapped state ``name`` the wrapper registers a
``sl__{name}`` ring with a ``(K+2,)`` leading axis:

- rows ``0..K-1`` — the real slices;
- row ``K`` — the **quarantine** slice: valid rows whose ``slice_ids``
  entry is out of ``[0, K)`` land here (and are counted), so a corrupt id
  stream degrades into a visible bucket instead of corrupting a cohort;
- row ``K+1`` — the **discard** slice: rows masked invalid (``valid``
  False — e.g. the padding ladder's pad rows) land here, which makes pad
  rows invisible to every slice even when the wrapped metric itself cannot
  consume a ``valid`` mask.

**Update path.** The wrapped metric's update is applied per row (a
``vmap`` over batch-of-1 state deltas — the same state-swap delta trick
the streaming wrappers use, guard included), and the per-row deltas are
segment-reduced into the rings: ``jax.ops.segment_sum`` for sum/mean/
fault states, scatter-max/min for max/min states. Work is O(batch),
independent of K — the ``sliced`` bench phase pins update wall at K=256
within 3x of K=1.

**Supported states.** Fixed-shape arrays reduced by sum/mean/max/min,
:class:`FaultCounters` (the fault channel becomes per-slice), and the
*elementwise-mergeable* sketches (CountMin: sum; HyperLogLog: max) —
their inserts are linear/max-mergeable, so per-slice sketch state is
bit-equal to K demuxed instances. KLL quantile sketches are refused:
their merge is compaction (a shape-specific gather-merge lane in
``parallel/sync.py``), not an elementwise reduce, and has no ``(K,)``
ring form. ``CatBuffer``/list states are refused for the same reason.

Because every ring is a plain sum/max/min-reduced array state, a
``SlicedMetric`` rides the whole substrate unchanged: ``functionalize`` /
``overlapped_functionalize`` (trace-safe wrapper branch), ``fused_sync``
dtype buckets (a guarded stat-scores collection stays at <=2 all-reduces
per cycle — the ``sliced_fused_step`` audit pins it), snapshots, the int8
fleet wire and delta publishing (one ``(K+2,)`` leaf is ONE dirty-leaf
path, so steady-state delta payload is near-constant in K), and
``WindowedMetric`` composition — ``WindowedMetric(SlicedMetric(m))``
gives per-slice values over the trailing window via ``(B, K+2, ...)``
rings. Compose in that order; ``SlicedMetric(WindowedMetric(m))`` is
refused (the inner ring bookkeeping has no per-row delta form).

**Serving scrape.** :meth:`SlicedMetric.scrape_slices` returns bounded-
cardinality per-slice rows for the Prometheus surface: top-N slices by
traffic plus an aggregate ``other`` bucket, N capped by
``METRICS_TPU_SLICES_MAX_LABELS`` (default 8) — the fleet tier's
bounded-label stance applied to cohorts.
"""
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import _TRACE_ERRORS, Metric
from metrics_tpu.ops._envtools import EnvParse, WarnOnce
from metrics_tpu.ops.padding import SLICE_STATE_PREFIX
from metrics_tpu.streaming.windowed import _StreamingWrapper
from metrics_tpu.utilities.exceptions import MetricsTPUUserError

Array = jax.Array

__all__ = ["SlicedMetric", "SlicedValue", "slices_max_labels", "reset_sliced_state"]

_MAX_LABELS_VAR = "METRICS_TPU_SLICES_MAX_LABELS"
_MAX_LABELS_DEFAULT = 8

_warn_once = WarnOnce()


def _parse_max_labels(raw: str) -> int:
    try:
        n = int(raw)
        if n < 1:
            raise ValueError
        return n
    except ValueError:
        _warn_once(
            ("max-labels-malformed", raw),
            f"{_MAX_LABELS_VAR}={raw!r} is malformed (expected a positive integer); "
            f"falling back to the default cap of {_MAX_LABELS_DEFAULT}",
        )
        return _MAX_LABELS_DEFAULT


_max_labels_env: "EnvParse[int]" = EnvParse(_MAX_LABELS_VAR, _parse_max_labels, _MAX_LABELS_DEFAULT)


def slices_max_labels() -> int:
    """The hard per-metric label-cardinality cap for per-slice scrape rows
    (``METRICS_TPU_SLICES_MAX_LABELS``, default 8). Malformed values warn
    once and fall back — a bad env var degrades scrape detail, never
    correctness."""
    return _max_labels_env()


def reset_sliced_state() -> None:
    """Clear the warn-once memory and the memoized env parse (test
    isolation — same contract as ``padding.reset_padding_state``)."""
    _warn_once.reset()
    _max_labels_env.reset()


class SlicedValue(NamedTuple):
    """The computed value of a :class:`SlicedMetric`: the wrapped metric's
    value with a ``(K,)`` leading axis, the count-weighted global rollup
    over the real slices, and the quarantined-row count. A NamedTuple (not
    a dict) so ``MetricCollection``'s one-level result flattening keeps it
    under its member key."""

    per_slice: Any
    global_value: Any
    quarantined_rows: Any


class SlicedMetric(_StreamingWrapper):
    """Per-slice view of a metric: one segment-reduce update over K cohorts.

    ``update`` takes a ``slice_ids`` int row vector (one id per row)
    alongside the wrapped metric's normal arguments; ``compute`` returns a
    :class:`SlicedValue` — the wrapped metric's value with a ``(K,)``
    leading axis, the count-weighted global rollup over the real slices,
    and the quarantined-row count. An empty slice computes the same value
    as a freshly-initialized instance of the wrapped metric.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SlicedMetric, SumMetric
        >>> m = SlicedMetric(SumMetric(), num_slices=2)
        >>> m.update(jnp.asarray([1.0, 2.0, 4.0]), slice_ids=jnp.asarray([0, 1, 1]))
        >>> out = m.compute()
        >>> [float(v) for v in out.per_slice], float(out.global_value)
        ([1.0, 6.0], 7.0)
    """

    _KIND_NAME = "sliced"
    # the wrapper consumes `valid` itself: masked rows route to the discard
    # slice, so pad rows are provably invisible even when the wrapped metric
    # cannot consume a row mask (`ops/padding.py::supports_row_mask`)
    _valid_mask_always = True

    def __init__(self, metric: Metric, num_slices: int, **kwargs: Any) -> None:
        super().__init__(metric, **kwargs)
        if not (isinstance(num_slices, int) and num_slices >= 1):
            raise ValueError(f"`num_slices` must be a positive int, got {num_slices}")
        if getattr(metric, "_wrapper_trace_safe", False):
            raise ValueError(
                f"SlicedMetric cannot wrap {type(metric).__name__}: the inner wrapper's ring "
                "bookkeeping (bucket heads, fill counters) has no per-row delta form. Compose "
                "the other way — e.g. WindowedMetric(SlicedMetric(m), ...) windows every slice."
            )
        self.num_slices = num_slices
        self._specs = self._sliced_state_specs()

        from metrics_tpu.utilities.guard import NUM_FAULT_CLASSES

        R = num_slices + 2  # real slices + quarantine + discard
        for name, kind in self._specs.items():
            if kind == "faults":
                identity = jnp.zeros((NUM_FAULT_CLASSES,), jnp.uint32)
                fx = "sum"
            elif kind in ("sketch_sum", "sketch_max"):
                identity = jax.tree_util.tree_leaves(self.wrapped._defaults[name])[0]
                fx = "sum" if kind == "sketch_sum" else "max"
            else:
                identity = jnp.asarray(self.wrapped._defaults[name])
                # mean rings hold SUMS of per-row deltas (divided by the
                # per-slice row count at read), so they psum exactly —
                # cross-device means need no update-count bookkeeping
                fx = {"sum": "sum", "mean": "sum", "max": "max", "min": "min"}[kind]
            ring = jnp.broadcast_to(identity[None], (R,) + identity.shape) + jnp.zeros_like(
                identity
            )
            self.add_state(f"{SLICE_STATE_PREFIX}{name}", default=ring, dist_reduce_fx=fx)
        self.add_state(
            f"{SLICE_STATE_PREFIX}rows", default=jnp.zeros((R,), jnp.int32), dist_reduce_fx="sum"
        )

    # ------------------------------------------------------------------
    # state specs
    # ------------------------------------------------------------------

    def _sliced_state_specs(self) -> Dict[str, str]:
        """``{state_name: kind}`` with kind in sum/mean/max/min/faults/
        sketch_sum/sketch_max; raises for states with no segment-reduce
        form (KLL sketches, cat/list states)."""
        from metrics_tpu.utilities.guard import FaultCounters
        from metrics_tpu.utilities.ringbuffer import CatBuffer

        specs: Dict[str, str] = {}
        for name, default in self.wrapped._defaults.items():
            fx = self.wrapped._reductions[name]
            child = type(self.wrapped).__name__
            if isinstance(default, FaultCounters):
                specs[name] = "faults"
            elif getattr(type(default), "is_sketch_state", False):
                er = getattr(type(default), "elementwise_reduction", None)
                if er not in ("sum", "max"):
                    raise ValueError(
                        f"SlicedMetric cannot wrap {child}: state {name!r} is a "
                        f"{type(default).__name__} whose merge is compaction, not an "
                        "elementwise reduce — it has no (K,)-ring form. Slice the "
                        "elementwise sketches (CountMinSketch, HyperLogLog) or keep "
                        "quantile sketches unsliced."
                    )
                if len(jax.tree_util.tree_leaves(default)) != 1:
                    raise ValueError(
                        f"SlicedMetric cannot wrap {child}: sketch state {name!r} has "
                        "multiple leaves; only single-leaf elementwise sketches slice."
                    )
                specs[name] = f"sketch_{er}"
            elif isinstance(default, (list, CatBuffer)):
                raise ValueError(
                    f"SlicedMetric cannot wrap {child}: state {name!r} is a per-row "
                    "cat/list state with no per-slice segment-reduce form. Construct "
                    "the metric in a binned/fixed-shape variant to slice it."
                )
            elif fx in ("sum", "mean", "max", "min"):
                specs[name] = fx
            else:
                raise ValueError(
                    f"SlicedMetric cannot wrap {child}: state {name!r} has "
                    f"dist_reduce_fx={fx!r}, which has no segment-reduce rule."
                )
        return specs

    # ------------------------------------------------------------------
    # update: per-row deltas -> segment-reduce into the rings
    # ------------------------------------------------------------------

    def update(
        self,
        *args: Any,
        slice_ids: Optional[Array] = None,
        valid: Optional[Array] = None,
        **kwargs: Any,
    ) -> None:
        if slice_ids is None:
            raise MetricsTPUUserError(
                f"SlicedMetric({type(self.wrapped).__name__}).update needs a `slice_ids` "
                "keyword argument: an int array with one slice id per batch row."
            )
        K = self.num_slices
        ids = jnp.asarray(slice_ids).reshape(-1).astype(jnp.int32)
        n = int(ids.shape[0])
        vmask = (
            jnp.asarray(valid, bool).reshape(-1)
            if valid is not None
            else jnp.ones((n,), bool)
        )
        # routing: invalid rows -> discard (K+1), out-of-range ids ->
        # quarantine (K), everything else -> its slice
        in_range = (ids >= 0) & (ids < K)
        tgt = jnp.where(~vmask, jnp.int32(K + 1), jnp.where(in_range, ids, jnp.int32(K)))

        if valid is not None:
            kwargs = {**kwargs, "valid": valid}
        child_kwargs = self.wrapped._filter_kwargs(**kwargs)

        def _aligned(v: Any) -> bool:
            shape = getattr(v, "shape", None)
            return shape is not None and len(shape) >= 1 and shape[0] == n

        row_arg_idx = [i for i, a in enumerate(args) if _aligned(a)]
        row_kw_keys = [k for k, v in child_kwargs.items() if _aligned(v)]
        mapped: List[Any] = [jnp.asarray(args[i]) for i in row_arg_idx]
        mapped += [jnp.asarray(child_kwargs[k]) for k in row_kw_keys]
        mapped.append(jnp.arange(n))  # always >=1 mapped operand

        def per_row(*rows: Any) -> Dict[str, Any]:
            a = list(args)
            for i, v in zip(row_arg_idx, rows):
                a[i] = v[None]
            kw = dict(child_kwargs)
            for k, v in zip(row_kw_keys, rows[len(row_arg_idx):]):
                kw[k] = v[None]
            return self._delta_state(tuple(a), kw)

        deltas = jax.vmap(per_row)(*mapped)

        for name, kind in self._specs.items():
            ring_name = f"{SLICE_STATE_PREFIX}{name}"
            ring = getattr(self, ring_name)
            d = deltas[name]
            if kind == "faults":
                leaf = d.counts
            elif kind in ("sketch_sum", "sketch_max"):
                leaf = jax.tree_util.tree_leaves(d)[0]
            else:
                leaf = jnp.asarray(d)
            if kind in ("sum", "mean", "faults", "sketch_sum"):
                ring = ring + jax.ops.segment_sum(leaf, tgt, num_segments=K + 2)
            elif kind in ("max", "sketch_max"):
                ring = ring.at[tgt].max(leaf)
            else:  # min
                ring = ring.at[tgt].min(leaf)
            setattr(self, ring_name, ring)
        rows_name = f"{SLICE_STATE_PREFIX}rows"
        setattr(
            self,
            rows_name,
            getattr(self, rows_name)
            + jax.ops.segment_sum(jnp.ones((n,), jnp.int32), tgt, num_segments=K + 2),
        )

    # ------------------------------------------------------------------
    # compute: per-slice child states + the count-weighted global rollup
    # ------------------------------------------------------------------

    def _child_state_from_raw(self, raw: Dict[str, Any]) -> Dict[str, Any]:
        """Rebuild a child state dict from raw ring rows (FaultCounters and
        sketch structs re-wrapped around their single leaf)."""
        from metrics_tpu.utilities.guard import FaultCounters

        state: Dict[str, Any] = {}
        for name, kind in self._specs.items():
            v = raw[name]
            if kind == "faults":
                state[name] = FaultCounters(counts=v)
            elif kind in ("sketch_sum", "sketch_max"):
                _, treedef = jax.tree_util.tree_flatten(self.wrapped._defaults[name])
                state[name] = jax.tree_util.tree_unflatten(treedef, [v])
            else:
                state[name] = v
        return state

    def _per_slice_raw(self) -> Dict[str, Array]:
        """Raw per-slice child-state leaves, each with a ``(K,)`` leading
        axis (quarantine and discard rows excluded)."""
        K = self.num_slices
        rows = getattr(self, f"{SLICE_STATE_PREFIX}rows")[:K]
        raw: Dict[str, Array] = {}
        for name, kind in self._specs.items():
            ring = getattr(self, f"{SLICE_STATE_PREFIX}{name}")[:K]
            if kind == "mean":
                denom = jnp.maximum(rows, 1).astype(jnp.float32)
                raw[name] = ring / denom.reshape((K,) + (1,) * (ring.ndim - 1))
            else:
                raw[name] = ring
        return raw

    def _rollup_raw(self) -> Dict[str, Array]:
        """The global child state: the associative form of the framework's
        ``_reduce_states`` merge rules applied across the real slices (sums
        add, means re-weight by per-slice rows, max/min reduce). Quarantined
        rows are deliberately EXCLUDED — their cohort is unknown, so they
        are surfaced as a count, never folded into the global value."""
        K = self.num_slices
        total = jnp.maximum(
            getattr(self, f"{SLICE_STATE_PREFIX}rows")[:K].sum(), 1
        ).astype(jnp.float32)
        raw: Dict[str, Array] = {}
        for name, kind in self._specs.items():
            ring = getattr(self, f"{SLICE_STATE_PREFIX}{name}")[:K]
            if kind in ("sum", "faults", "sketch_sum"):
                raw[name] = ring.sum(axis=0)
            elif kind == "mean":
                raw[name] = ring.sum(axis=0) / total
            elif kind in ("max", "sketch_max"):
                raw[name] = ring.max(axis=0)
            else:  # min
                raw[name] = ring.min(axis=0)
        return raw

    def compute(self) -> SlicedValue:
        run: Callable[[Dict[str, Any]], Any] = lambda raw: self._run_child_compute(
            self._child_state_from_raw(raw)
        )
        return SlicedValue(
            per_slice=jax.vmap(run)(self._per_slice_raw()),
            global_value=run(self._rollup_raw()),
            quarantined_rows=getattr(self, f"{SLICE_STATE_PREFIX}rows")[self.num_slices],
        )

    # ------------------------------------------------------------------
    # host-side bookkeeping + bounded-cardinality scrape
    # ------------------------------------------------------------------

    @property
    def slice_rows(self) -> Optional[np.ndarray]:
        """Rows folded per real slice, host-side (None while traced)."""
        try:
            return np.asarray(getattr(self, f"{SLICE_STATE_PREFIX}rows")[: self.num_slices])
        except _TRACE_ERRORS:
            return None

    @property
    def quarantined_rows(self) -> Optional[int]:
        """Valid rows whose slice id was out of ``[0, num_slices)``
        (host-side; None while traced)."""
        try:
            return int(getattr(self, f"{SLICE_STATE_PREFIX}rows")[self.num_slices])
        except _TRACE_ERRORS:
            return None

    @property
    def discarded_rows(self) -> Optional[int]:
        """Rows masked invalid (pad rows included; None while traced)."""
        try:
            return int(getattr(self, f"{SLICE_STATE_PREFIX}rows")[self.num_slices + 1])
        except _TRACE_ERRORS:
            return None

    def _aggregated_fault_counts(self) -> Optional[Array]:
        ring = self._state.get(f"{SLICE_STATE_PREFIX}_faults")
        # evidence from EVERY row, quarantine and discard included — faults
        # must not vanish with their slice routing
        return None if ring is None else ring.sum(axis=0)

    def scrape_slices(self, max_labels: Optional[int] = None) -> Dict[str, Any]:
        """Bounded-cardinality per-slice scrape rows: the top ``max_labels``
        slices by traffic (rows folded), each with its scalar computed
        values, plus an aggregate ``other`` bucket for the tail — the hard
        label-cardinality cap the serving tier exports under
        (``METRICS_TPU_SLICES_MAX_LABELS``; the fleet tier's bounded-label
        stance applied to cohorts). Host-side only."""
        cap = slices_max_labels() if max_labels is None else int(max_labels)
        if cap < 1:
            raise ValueError(f"`max_labels` must be >= 1, got {max_labels}")
        K = self.num_slices
        out: Dict[str, Any] = {
            "num_slices": K,
            "max_labels": cap,
            "top": [],
            "other": {"slices": 0, "rows": 0},
            "quarantined_rows": 0,
            "discarded_rows": 0,
        }
        rows = self.slice_rows
        if rows is None:
            return out
        out["quarantined_rows"] = self.quarantined_rows or 0
        out["discarded_rows"] = self.discarded_rows or 0
        # gate on row evidence, not _update_called: a serving reporter gets
        # its rings by snapshot FOLD, never by calling update itself
        if int(rows.sum()) == 0:
            return out
        # scalar per-slice leaves of the computed value, keyed by tree path
        per_slice = self.compute().per_slice
        leaves: List[Tuple[str, np.ndarray]] = []
        for path, leaf in jax.tree_util.tree_flatten_with_path(per_slice)[0]:
            arr = np.asarray(leaf)
            if arr.shape == (K,):
                name = "/".join(str(getattr(e, "key", e)) for e in path) or "value"
                leaves.append((name, arr))
        order = np.argsort(-rows, kind="stable")
        top = [int(k) for k in order[:cap] if rows[k] > 0]
        for k in top:
            out["top"].append(
                {
                    "slice": k,
                    "rows": int(rows[k]),
                    "values": {name: float(arr[k]) for name, arr in leaves},
                }
            )
        tail = [int(k) for k in order[cap:] if rows[k] > 0]
        out["other"] = {"slices": len(tail), "rows": int(sum(rows[k] for k in tail))}
        return out

"""Mergeable streaming sketches: quantiles, frequencies, distinct counts.

Every accumulator in the framework so far answers *exact* questions over a
since-reset epoch. Online monitoring asks different questions — "p99 score
quantile right now", "how often has this id been seen", "how many distinct
users" — whose exact answers need per-row storage. The sketches here answer
them approximately in **fixed-size, pure pytree state**, with a merge that
is associative + commutative, so they ride every channel the framework
already has:

- **state registry**: each sketch state is a NamedTuple pytree a metric
  registers via ``add_state`` (like :class:`FaultCounters`), recognized
  structurally via the ``is_sketch_state`` class marker — no import cycles;
- **distributed sync**: CountMin counts fold into ``fused_sync``'s uint32
  *sum* bucket, HyperLogLog registers into the *max* bucket — a guarded
  collection gains frequency/distinct monitoring for zero extra
  collectives; the quantile sketch packs into ONE fused gather-merge
  payload (its merge is compaction, not elementwise) — the same fused
  computation-collective stance as EQuARX-style compressed all-reduce
  payloads (PAPERS.md): fixed sketch bytes move, never raw rows;
- **persistence**: ``to_primitives``/``from_primitives`` give the
  ``state_dict`` primitive forms, and ``SnapshotManager``'s elastic
  restore re-merges per-rank sketches through ``sketch_merge`` (8→4→1
  parity like CatBuffer);
- **fault channel**: the metric classes mask non-finite rows in-graph and
  report them through :class:`FaultCounters` under ``on_invalid='drop'``.

Error contracts: :class:`QuantileSketch` rank error ``<= eps * n``
(see ``ops/compactor.py`` for the accounting); :class:`CountMinSketch`
overestimates by at most ``2n/width`` with probability ``1 - 2**-depth``;
:class:`HyperLogLog` relative error ``~1.04 / sqrt(2**precision)``.
"""
import math
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import _TRACE_ERRORS, Metric
from metrics_tpu.utilities.exceptions import MetricsTPUUserError
from metrics_tpu.utilities.prints import rank_zero_warn
from metrics_tpu.ops.compactor import (
    fold_cascade,
    precompact_batch,
    weighted_cdf,
    weighted_quantiles,
    weighted_rank,
)

Array = jax.Array

__all__ = [
    "QuantileSketchState",
    "CountMinState",
    "HllState",
    "QuantileSketch",
    "CountMinSketch",
    "HyperLogLog",
]


def is_sketch_state(value: Any) -> bool:
    """Structural test every integration point uses (no streaming import)."""
    return getattr(type(value), "is_sketch_state", False)


def _hash_keys(values: Array) -> Array:
    """Canonical uint32 keys for hashing: floats bitcast (with ``-0.0``
    collapsed onto ``+0.0`` so equal values hash equally), ints truncated."""
    x = jnp.asarray(values).reshape(-1)
    if jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(jnp.float32) + jnp.float32(0.0)  # -0.0 -> +0.0
        return jax.lax.bitcast_convert_type(x, jnp.uint32)
    return x.astype(jnp.uint32)


def _fmix32(h: Array) -> Array:
    """murmur3 finalizer: avalanche mix of a uint32 lane."""
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> jnp.uint32(16))


# --------------------------------------------------------------------------
# QuantileSketch — KLL/compactor levels (ops/compactor.py kernels)
# --------------------------------------------------------------------------


class QuantileSketchState(NamedTuple):
    """Compactor quantile sketch: ``(L, k)`` sorted level buffers (item at
    level ``l`` = ``2**l`` rows; ``+inf`` past each level's ``counts``
    prefix) plus the exact inserted-row counter. Fixed shape, jittable,
    merge bitwise-commutative (see ``ops/compactor.py``)."""

    items: Array  # (L, k) float32
    counts: Array  # (L,) int32
    n_seen: Array  # () int32 — exact rows inserted (diagnostics; quantile
    #                normalization uses the level weights, which drift from
    #                n_seen by at most the documented eps-term)

    is_sketch_state = True
    # merge is compaction, not elementwise: syncs via ONE fused gather-merge
    # payload (parallel/sync.py), not a psum/pmax bucket
    elementwise_reduction = None

    @classmethod
    def create(
        cls,
        eps: float = 0.01,
        max_items: int = 1 << 30,
        k: Optional[int] = None,
        levels: Optional[int] = None,
    ) -> "QuantileSketchState":
        if not (0 < eps < 1):
            raise ValueError(f"`eps` must be in (0, 1), got {eps}")
        if k is None:
            # worst-case rank error ~ 2 * (L + 1) * n / k (ops/compactor.py)
            guess_levels = max(4, int(math.ceil(math.log2(max(max_items, 2)))) + 2)
            k = int(math.ceil(2.0 * (guess_levels + 1) / eps))
        k = max(8, k + (k % 2))  # even, so pair compaction has no odd tail bias
        if levels is None:
            levels = max(4, int(math.ceil(math.log2(max(max_items / k, 2.0)))) + 2)
        return cls(
            items=jnp.full((levels, k), jnp.inf, jnp.float32),
            counts=jnp.zeros((levels,), jnp.int32),
            n_seen=jnp.zeros((), jnp.int32),
        )

    # -- streaming ------------------------------------------------------

    def insert(self, values: Array, valid: Optional[Array] = None) -> "QuantileSketchState":
        """Fold one batch in (non-finite rows always excluded). Fully
        jittable; the cascade depth is static in the batch size.

        The batch pre-compaction is the dispatched ``sketch_precompact``
        kernel (``ops/dispatch.py``): the default ``binned`` impl bins by
        ``bucketed_rank``'s orderable-key grid instead of running the
        full float sort (~6x on 1M-row CPU batches, bit-identical state
        up to ``-0.0``/denormal canonicalization — ``ops/binning.py``),
        and the fold cascade ``lax.cond``-skips every level the promotion
        does not reach, so small (sub-``k``) batches pay one fold, not
        ``L`` (``ops/compactor.py``)."""
        x = jnp.asarray(values, jnp.float32).reshape(-1)
        v = jnp.ones(x.shape, bool) if valid is None else jnp.asarray(valid, bool).reshape(-1)
        L, k = self.items.shape
        # predict the pre-compaction level WITHOUT running the kernel —
        # shared with the halving map itself, so prediction and the
        # kernel's actual level can never diverge
        from metrics_tpu.ops.binning import halving_level

        level = halving_level(x.shape[0], k)
        if level >= L:
            # a single batch so large its pre-compaction would promote PAST
            # the top level (> k * 2**(L-1) rows, i.e. max_items was
            # configured below one batch's size): fold_cascade would drop
            # the whole increment on the floor. Split into the smallest
            # chunk count that lands within the cascade instead — a static
            # python loop, so jit-compatible, decided BEFORE any kernel
            # runs; the eps contract still degrades per
            # `_check_cat_overflow`, but the rows are never silently lost.
            # (`valid` may be a broadcastable scalar/length-1 on the normal
            # path — materialize it to x's shape so the slices pair up.)
            v = jnp.broadcast_to(v, x.shape)
            chunks = 1 << (level - (L - 1))
            step = -(-x.shape[0] // chunks)
            state = self
            for i in range(0, x.shape[0], step):
                state = state.insert(x[i : i + step], v[i : i + step])
            return state
        inc, inc_count, level = precompact_batch(x, v, k)
        items, counts = fold_cascade(self.items, self.counts, inc, inc_count, level)
        n = jnp.sum((v & jnp.isfinite(x)).astype(jnp.int32))
        return QuantileSketchState(items=items, counts=counts, n_seen=self.n_seen + n)

    def sketch_merge(self, other: "QuantileSketchState") -> "QuantileSketchState":
        """Associative-within-eps, bitwise-commutative union."""
        if self.items.shape != other.items.shape:
            raise ValueError(
                f"cannot merge QuantileSketchState of shape {self.items.shape} with "
                f"{other.items.shape}; construct both with the same eps/k/levels"
            )
        L, k = self.items.shape
        items, counts = self.items, self.counts
        carry = jnp.full((2 * k,), jnp.inf, jnp.float32)
        carry_count = jnp.zeros((), jnp.int32)
        rows, cnts = [], []
        from metrics_tpu.ops.compactor import fold_level

        for lvl in range(L):
            inc = jnp.concatenate([other.items[lvl], carry])  # (3k,), sorted below
            inc_count = other.counts[lvl] + carry_count
            if lvl == L - 1:
                combined = jnp.sort(jnp.concatenate([items[lvl], inc]))
                c = jnp.minimum(counts[lvl] + inc_count, k)
                rows.append(jnp.where(jnp.arange(k) < c, combined[:k], jnp.inf))
                cnts.append(c)
                break
            ni, nc, carry, carry_count = fold_level(items[lvl], counts[lvl], inc, inc_count)
            rows.append(ni)
            cnts.append(nc)
        return QuantileSketchState(
            items=jnp.stack(rows),
            counts=jnp.stack(cnts).astype(jnp.int32),
            n_seen=self.n_seen + other.n_seen,
        )

    # -- queries --------------------------------------------------------

    def quantile(self, qs: Any) -> Array:
        return weighted_quantiles(self.items, self.counts, jnp.atleast_1d(jnp.asarray(qs)))

    def rank(self, v: Any) -> Array:
        """Estimated rows ``<= v`` (error ``<= eps * n``)."""
        return weighted_rank(self.items, self.counts, v)

    def cdf(self, points: Any) -> Array:
        """Estimated CDF at many probe points in one vectorized pass:
        ``cdf(points)[i]`` is the fraction of inserted rows ``<= points[i]``,
        each off by at most the sketch's rank-error fraction (``eps_bound``;
        ``eps`` as constructed) — the many-point form of :meth:`rank` that
        drift scoring (``obs/drift.py``) and any CDF-distance consumer
        needs, instead of hand-rolling a per-point rank loop. An empty
        sketch answers NaN everywhere."""
        return weighted_cdf(self.items, self.counts, points)

    @property
    def eps_bound(self) -> float:
        """Worst-case rank-error fraction of this geometry."""
        L, k = self.items.shape
        return 2.0 * (L + 1) / k

    # -- serialization / transport --------------------------------------

    def to_primitives(self) -> Dict[str, np.ndarray]:
        return {
            "items": np.asarray(self.items),
            "counts": np.asarray(self.counts),
            "n_seen": np.asarray(self.n_seen),
        }

    @classmethod
    def from_primitives(cls, prim: Any, like: "QuantileSketchState") -> "QuantileSketchState":
        if isinstance(prim, cls):
            prim = prim.to_primitives()
        if not isinstance(prim, dict) or not {"items", "counts"} <= set(prim):
            raise ValueError(
                "QuantileSketchState loads from an {'items', 'counts', 'n_seen'} mapping, "
                f"got {type(prim).__name__}"
            )
        items = np.asarray(prim["items"])
        if items.shape != tuple(like.items.shape):
            raise ValueError(
                f"QuantileSketchState items shape {items.shape} != expected "
                f"{tuple(like.items.shape)} (eps/k/levels config mismatch?)"
            )
        counts = np.asarray(prim["counts"]).reshape(-1)
        if counts.shape[0] != like.counts.shape[0]:
            raise ValueError(
                f"QuantileSketchState counts length {counts.shape[0]} != expected "
                f"{like.counts.shape[0]}"
            )
        return cls(
            items=jnp.asarray(items, jnp.float32),
            counts=jnp.asarray(counts, jnp.int32),
            n_seen=jnp.asarray(prim.get("n_seen", 0), jnp.int32).reshape(()),
        )

    def pack(self) -> Array:
        """One flat float32 vector for the fused gather-merge sync payload.
        ``counts`` entries are ``<= k < 2**24``, exact in f32; ``n_seen``
        is an unbounded int32, so it rides as TWO 12-bit-split lanes
        (``hi*4096 + lo``, each ``< 2**19`` — exact in f32 for the whole
        int32 range, preserving the counter's exactness contract)."""
        n = self.n_seen.astype(jnp.int32)
        return jnp.concatenate(
            [
                self.items.ravel(),
                self.counts.astype(jnp.float32),
                (n // 4096).astype(jnp.float32)[None],
                (n % 4096).astype(jnp.float32)[None],
            ]
        )

    @classmethod
    def unpack_like(cls, flat: Array, like: "QuantileSketchState") -> "QuantileSketchState":
        L, k = like.items.shape
        n = flat[L * k + L].astype(jnp.int32) * 4096 + flat[L * k + L + 1].astype(jnp.int32)
        return cls(
            items=flat[: L * k].reshape(L, k),
            counts=flat[L * k : L * k + L].astype(jnp.int32),
            n_seen=n,
        )

    @property
    def packed_size(self) -> int:
        L, k = self.items.shape
        return L * k + L + 2


# --------------------------------------------------------------------------
# CountMinSketch — frequency estimates, psum-mergeable
# --------------------------------------------------------------------------

_CM_SEED = 0x9E3779B9


def _cm_hash_params(depth: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic per-row multiply-shift constants — a pure function of
    ``depth``, so equal-shape sketches are merge-compatible by
    construction (no seeds in state)."""
    rng = np.random.default_rng(_CM_SEED)
    a = (rng.integers(0, 1 << 32, depth, dtype=np.uint64).astype(np.uint32)) | np.uint32(1)
    b = rng.integers(0, 1 << 32, depth, dtype=np.uint64).astype(np.uint32)
    return a, b


class CountMinState(NamedTuple):
    """Count–min frequency sketch: ``(depth, width)`` uint32 counters whose
    merge is elementwise **sum** — it rides ``fused_sync``'s uint32 sum
    bucket (with the fault counters) for zero extra collectives."""

    counts: Array  # (depth, width) uint32

    is_sketch_state = True
    elementwise_reduction = "sum"

    @classmethod
    def create(cls, depth: int = 4, width: int = 2048) -> "CountMinState":
        if width & (width - 1) or width < 2:
            raise ValueError(f"`width` must be a power of two >= 2, got {width}")
        if depth < 1:
            raise ValueError(f"`depth` must be >= 1, got {depth}")
        return cls(counts=jnp.zeros((depth, width), jnp.uint32))

    def _indices(self, values: Array) -> Array:
        depth, width = self.counts.shape
        a, b = _cm_hash_params(depth)
        keys = _hash_keys(values)  # (n,)
        h = _fmix32(keys[None, :] * jnp.asarray(a)[:, None] + jnp.asarray(b)[:, None])
        return (h & jnp.uint32(width - 1)).astype(jnp.int32)  # (depth, n)

    def insert(self, values: Array, valid: Optional[Array] = None) -> "CountMinState":
        idx = self._indices(values)
        inc = jnp.ones(idx.shape[1], jnp.uint32)
        if valid is not None:
            inc = jnp.where(jnp.asarray(valid, bool).reshape(-1), inc, jnp.uint32(0))
        rows = jnp.broadcast_to(jnp.arange(idx.shape[0])[:, None], idx.shape)
        counts = self.counts.at[rows, idx].add(jnp.broadcast_to(inc, idx.shape))
        return CountMinState(counts=counts)

    def query(self, values: Array) -> Array:
        """Estimated occurrence counts (never under-counts)."""
        idx = self._indices(values)
        rows = jnp.broadcast_to(jnp.arange(idx.shape[0])[:, None], idx.shape)
        return jnp.min(self.counts[rows, idx], axis=0)

    def sketch_merge(self, other: "CountMinState") -> "CountMinState":
        if self.counts.shape != other.counts.shape:
            raise ValueError(
                f"cannot merge CountMinState of shape {self.counts.shape} with "
                f"{other.counts.shape}; construct both with the same depth/width"
            )
        return CountMinState(counts=self.counts + other.counts)

    def to_primitives(self) -> Dict[str, np.ndarray]:
        return {"counts": np.asarray(self.counts)}

    @classmethod
    def from_primitives(cls, prim: Any, like: "CountMinState") -> "CountMinState":
        if isinstance(prim, cls):
            prim = prim.to_primitives()
        if not isinstance(prim, dict) or "counts" not in prim:
            raise ValueError(
                f"CountMinState loads from a {{'counts'}} mapping, got {type(prim).__name__}"
            )
        counts = np.asarray(prim["counts"])
        if counts.shape != tuple(like.counts.shape):
            raise ValueError(
                f"CountMinState counts shape {counts.shape} != expected "
                f"{tuple(like.counts.shape)} (depth/width config mismatch?)"
            )
        return cls(counts=jnp.asarray(counts, jnp.uint32))


# --------------------------------------------------------------------------
# HyperLogLog — distinct counts, pmax-mergeable
# --------------------------------------------------------------------------


class HllState(NamedTuple):
    """HyperLogLog registers: ``(2**precision,)`` int32 whose merge is
    elementwise **max** — it rides ``fused_sync``'s max bucket."""

    registers: Array  # (m,) int32

    is_sketch_state = True
    elementwise_reduction = "max"

    @classmethod
    def create(cls, precision: int = 11) -> "HllState":
        if not (4 <= precision <= 18):
            raise ValueError(f"`precision` must be in [4, 18], got {precision}")
        return cls(registers=jnp.zeros((1 << precision,), jnp.int32))

    @property
    def precision(self) -> int:
        return int(self.registers.shape[0]).bit_length() - 1

    def insert(self, values: Array, valid: Optional[Array] = None) -> "HllState":
        p = self.precision
        h = _fmix32(_hash_keys(values))
        idx = (h >> jnp.uint32(32 - p)).astype(jnp.int32)
        w = h << jnp.uint32(p)
        rho = jnp.where(w == 0, jnp.int32(32 - p + 1), jax.lax.clz(w).astype(jnp.int32) + 1)
        if valid is not None:
            v = jnp.asarray(valid, bool).reshape(-1)
            rho = jnp.where(v, rho, 0)  # max with 0 = no-op
            idx = jnp.where(v, idx, 0)
        return HllState(registers=self.registers.at[idx].max(rho))

    def estimate(self) -> Array:
        """Distinct-count estimate with the standard small/large-range
        corrections (32-bit hash)."""
        m = self.registers.shape[0]
        alpha = 0.7213 / (1.0 + 1.079 / m) if m >= 128 else {16: 0.673, 32: 0.697, 64: 0.709}.get(m, 0.7213 / (1.0 + 1.079 / m))
        reg = self.registers.astype(jnp.float32)
        raw = alpha * m * m / jnp.sum(jnp.exp2(-reg))
        zeros = jnp.sum(self.registers == 0).astype(jnp.float32)
        linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
        est = jnp.where((raw <= 2.5 * m) & (zeros > 0), linear, raw)
        two32 = jnp.float32(2.0**32)
        est = jnp.where(est > two32 / 30.0, -two32 * jnp.log1p(-est / two32), est)
        return est

    def sketch_merge(self, other: "HllState") -> "HllState":
        if self.registers.shape != other.registers.shape:
            raise ValueError(
                f"cannot merge HllState with {self.registers.shape[0]} registers and "
                f"{other.registers.shape[0]}; construct both with the same precision"
            )
        return HllState(registers=jnp.maximum(self.registers, other.registers))

    def to_primitives(self) -> Dict[str, np.ndarray]:
        return {"registers": np.asarray(self.registers)}

    @classmethod
    def from_primitives(cls, prim: Any, like: "HllState") -> "HllState":
        if isinstance(prim, cls):
            prim = prim.to_primitives()
        if not isinstance(prim, dict) or "registers" not in prim:
            raise ValueError(
                f"HllState loads from a {{'registers'}} mapping, got {type(prim).__name__}"
            )
        registers = np.asarray(prim["registers"]).reshape(-1)
        if registers.shape != tuple(like.registers.shape):
            raise ValueError(
                f"HllState registers shape {registers.shape} != expected "
                f"{tuple(like.registers.shape)} (precision config mismatch?)"
            )
        return cls(registers=jnp.asarray(registers, jnp.int32))


# --------------------------------------------------------------------------
# Metric shells — the sketches as ordinary metrics (guarded, synced,
# snapshot-able, functionalize-able)
# --------------------------------------------------------------------------


class _SketchMetric(Metric):
    """Shared shell: one sketch state, non-finite rows masked in-graph
    (counted as ``dropped_rows`` by the fault channel when guarded)."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    # the update body itself neutralizes invalid rows (validity masks into
    # the sketch insert), so the guard's drop policy only counts
    _guard_handles_drop = True
    nan_strategy = "ignore"  # read by guard._body_neutralizes; sketches
    #                           always mask, there is nothing to configure

    def _valid_rows(self, values: Array) -> Array:
        x = jnp.asarray(values).reshape(-1)
        if jnp.issubdtype(x.dtype, jnp.floating):
            return jnp.isfinite(x)
        return jnp.ones(x.shape, bool)


class QuantileSketch(_SketchMetric):
    """Streaming quantiles over a value stream at fixed state size.

    ``compute()`` returns the configured ``quantiles`` of everything seen
    since reset, with rank error at most ``eps * n`` — including after
    distributed sync and elastic snapshot restore (the sketch merge is what
    both channels run). Values stream in through ``update(values)``; no
    per-row storage exists anywhere.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import QuantileSketch
        >>> m = QuantileSketch(eps=0.05, max_items=4096, quantiles=(0.5,))
        >>> m.update(jnp.arange(1000.0))
        >>> bool(abs(float(m.compute()) - 500.0) <= 0.05 * 1000)
        True
    """

    def __init__(
        self,
        eps: float = 0.01,
        max_items: int = 1 << 30,
        quantiles: Sequence[float] = (0.5, 0.9, 0.99),
        k: Optional[int] = None,
        levels: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.eps = float(eps)
        self.quantiles = tuple(float(q) for q in quantiles)
        if not self.quantiles or not all(0.0 <= q <= 1.0 for q in self.quantiles):
            raise ValueError(f"`quantiles` must be fractions in [0, 1], got {quantiles}")
        self.add_state(
            "sketch",
            default=QuantileSketchState.create(eps=eps, max_items=max_items, k=k, levels=levels),
            dist_reduce_fx="cat",  # documentary: every sync/merge path
            #                        special-cases sketch states structurally
        )

    def update(self, values: Array) -> None:
        x = jnp.asarray(values, jnp.float32).reshape(-1)
        self.sketch = self.sketch.insert(x, self._valid_rows(x))

    def compute(self) -> Array:
        from metrics_tpu.utilities.data import _squeeze_if_scalar

        return _squeeze_if_scalar(self.sketch.quantile(jnp.asarray(self.quantiles)))

    def _check_cat_overflow(self) -> None:
        """Saturation is never silent (the sketch analogue of ring-buffer
        overflow, same ``on_overflow`` policy): past ``k * (2**L - 1)``
        rows the top level clamps and the eps contract degrades — which
        only happens when ``max_items`` was configured below the actual
        stream length."""
        if self.on_overflow == "ignore":
            return
        st = self._state.get("sketch")
        if st is None:
            return
        try:
            n = int(np.asarray(st.n_seen))
        except _TRACE_ERRORS:
            return  # traced compute: the eager caller re-checks
        L, k = st.items.shape
        capacity = k * ((1 << L) - 1)  # total representable row weight
        if n <= capacity:
            return
        msg = (
            f"{type(self).__name__}: the stream ({n} rows) exceeded this sketch's "
            f"~{capacity}-row design capacity (max_items was configured too small); the top "
            "compactor level has saturated and rank error can exceed the eps contract. "
            "Construct with a larger `max_items`, or pass `on_overflow='ignore'` to silence "
            "this."
        )
        if self.on_overflow == "error":
            raise MetricsTPUUserError(msg)
        if not self.__dict__.get("_saturation_warned"):
            object.__setattr__(self, "_saturation_warned", True)
            rank_zero_warn(msg, UserWarning)

    def quantile(self, qs: Any) -> Array:
        """Ad-hoc quantile query against the current (local) state."""
        from metrics_tpu.utilities.data import _squeeze_if_scalar

        return _squeeze_if_scalar(self.sketch.quantile(qs))

    def cdf(self, points: Any) -> Array:
        """Ad-hoc vectorized CDF query against the current (local) state
        (see :meth:`QuantileSketchState.cdf`)."""
        return self.sketch.cdf(points)


class CountMinSketch(_SketchMetric):
    """Streaming per-item frequency estimates (count–min).

    ``update(values)`` hashes each row into ``depth`` counter rows;
    ``query(values)`` returns occurrence estimates that never under-count
    and over-count by at most ``2n/width`` with probability
    ``1 - 2**-depth``. The counter matrix merges by elementwise sum, so a
    distributed sync costs no collective beyond the shared sum bucket.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CountMinSketch
        >>> m = CountMinSketch(depth=4, width=256)
        >>> m.update(jnp.asarray([7, 7, 7, 3]))
        >>> int(m.query(jnp.asarray([7]))[0])
        3
    """

    def __init__(self, depth: int = 4, width: int = 2048, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.depth = int(depth)
        self.width = int(width)
        self.add_state("sketch", default=CountMinState.create(depth, width), dist_reduce_fx="sum")

    def update(self, values: Array) -> None:
        self.sketch = self.sketch.insert(values, self._valid_rows(values))

    def compute(self) -> Array:
        """The (synced) counter matrix — feed it to ``CountMinState.query``
        via :meth:`query` for per-item estimates."""
        return self.sketch.counts

    def query(self, values: Array) -> Array:
        return self.sketch.query(values)


class HyperLogLog(_SketchMetric):
    """Streaming distinct-count estimate (HyperLogLog).

    ``compute()`` estimates the number of distinct values seen since reset
    with relative error ``~1.04 / sqrt(2**precision)`` from ``2**precision``
    int32 registers. Registers merge by elementwise max, so sync rides the
    fused max bucket.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import HyperLogLog
        >>> m = HyperLogLog(precision=11)
        >>> m.update(jnp.arange(5000) % 1000)
        >>> bool(abs(float(m.compute()) - 1000) / 1000 < 0.1)
        True
    """

    def __init__(self, precision: int = 11, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.precision = int(precision)
        self.add_state("sketch", default=HllState.create(precision), dist_reduce_fx="max")

    def update(self, values: Array) -> None:
        self.sketch = self.sketch.insert(values, self._valid_rows(values))

    def compute(self) -> Array:
        return self.sketch.estimate()

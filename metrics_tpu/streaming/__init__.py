"""Streaming subsystem: windowed/decayed metric views + mergeable sketches.

The online-monitoring layer over the epoch accumulators (see
``windowed.py`` and ``sketches.py`` module docstrings, and the streaming
section of DESIGN.md): "accuracy over the last 10k requests", "p99 score
quantile right now", "distinct users today" — all from fixed-size, pure,
jittable pytree state that rides the existing fused sync, snapshot, and
fault channels.
"""
from metrics_tpu.streaming.sketches import (  # noqa: F401
    CountMinSketch,
    CountMinState,
    HllState,
    HyperLogLog,
    QuantileSketch,
    QuantileSketchState,
)
from metrics_tpu.streaming.windowed import DecayedMetric, WindowedMetric  # noqa: F401

__all__ = [
    "CountMinSketch",
    "CountMinState",
    "DecayedMetric",
    "HllState",
    "HyperLogLog",
    "QuantileSketch",
    "QuantileSketchState",
    "WindowedMetric",
]

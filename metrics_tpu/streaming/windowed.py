"""Sliding-window and exponentially-decayed views of any accumulating metric.

Every metric in the framework accumulates since-reset; production
monitoring asks "accuracy over the last 10k requests" — a question the
epoch accumulators cannot answer without per-row storage. These wrappers
answer it with **fixed-size time-bucketed sub-accumulator rings**: each
update's state *delta* (the wrapped metric's update run on a fresh default
state — the same state-swap trick ``functionalize`` uses) is folded into
the current bucket of a ``(buckets, *leaf)`` ring, and old buckets expire
whole. No per-row storage, fully jittable, donate-friendly (fixed input →
output shapes), and the rings are plain sum/max/min-reduced array states
that ride ``fused_sync``'s existing buckets and ``SnapshotManager``'s
elastic merge unchanged.

Window semantics (:class:`WindowedMetric`): the window holds ``buckets``
buckets of ``window // buckets`` rows each; a bucket rotates out (lazily,
at the start of the next update) once it has absorbed its row quota. Rows
are attributed at *update-call* granularity — every row of one update
lands in the bucket current at call start — so the covered span is exactly
the trailing ``window`` rows whenever update batches align with bucket
boundaries (``bucket_len % batch == 0``), and quantizes to
``max(bucket_len, batch)`` granularity otherwise. In particular a batch
LARGER than ``bucket_len`` fills a whole bucket by itself, growing the
covered span toward ``buckets * batch`` rows — the wrapper warns once when
it sees one (size ``buckets`` so ``window / buckets`` is at least your
batch size, or pass ``buckets=1`` for whole-batch buckets);
``window_rows`` always reports the span actually covered. Supported
wrapped states: fixed-shape arrays reduced by
``sum``/``mean``/``max``/``min``, plus :class:`FaultCounters` (summed per
bucket, so the fault channel is windowed too). ``CatBuffer`` rings, list
states, and sketch states are refused — they have no per-bucket identity
to expire.

Decay semantics (:class:`DecayedMetric`): sum-reduced accumulators (and
the mean numerator/denominator pair) are scaled by ``2**(-n / halflife)``
before each ``n``-row update folds in, giving every past row weight
``2**(-age_rows / halflife)`` (rows within one update share an age).
Decayed accumulators are kept in float32 regardless of the wrapped state's
dtype — a decayed count is fractional by construction; every ratio-style
compute handles that, exact-count consumers should window instead.
``max``/``min`` states cannot decay without per-row storage and are
refused; fault counters are deliberately NOT decayed (evidence of faults
should not fade).
"""
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import _TRACE_ERRORS, Metric
from metrics_tpu.utilities.checks import _is_concrete
from metrics_tpu.utilities.exceptions import MetricsTPUUserError
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

__all__ = ["WindowedMetric", "DecayedMetric"]


def _leading_rows(args: tuple, kwargs: dict) -> int:
    """Rows contributed by one update call: the leading dim of the first
    array-like argument (static under trace), 1 for scalar updates."""
    for a in list(args) + list(kwargs.values()):
        if isinstance(a, (jax.Array, np.ndarray)) and getattr(a, "ndim", 0) >= 1:
            return int(a.shape[0])
    return 1


class _StreamingWrapper(Metric):
    """Shared machinery: child state-delta extraction, spec validation,
    child compute on a rebuilt state, windowed fault-channel surfacing."""

    is_differentiable = False
    full_state_update = True  # batch-vs-global merge has no ring-aware rule
    _wrapper_trace_safe = True  # functionalize swaps the whole tree as state

    _KIND_NAME = "streaming wrapper"

    def __init__(self, metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(metric, Metric):
            raise ValueError(
                f"Expected the wrapped metric to be a `metrics_tpu.Metric`, got {metric!r}"
            )
        self.wrapped = metric

    def _child_state_specs(self, allow_minmax: bool) -> Dict[str, str]:
        """``{state_name: kind}`` with kind in sum/mean/max/min/faults;
        raises for states with no bucket/decay semantics."""
        from metrics_tpu.utilities.guard import FaultCounters
        from metrics_tpu.utilities.ringbuffer import CatBuffer

        specs: Dict[str, str] = {}
        for name, default in self.wrapped._defaults.items():
            fx = self.wrapped._reductions[name]
            child = type(self.wrapped).__name__
            if isinstance(default, FaultCounters):
                specs[name] = "faults"
            elif isinstance(default, (list, CatBuffer)) or getattr(
                type(default), "is_sketch_state", False
            ):
                raise ValueError(
                    f"{type(self).__name__} cannot wrap {child}: state {name!r} is a "
                    "per-row/list/sketch state with no per-bucket identity to expire. "
                    "Wrap sum/mean/max/min-reduced metrics (use the standalone sketches "
                    "for windowed distributional views)."
                )
            elif fx == "sum":
                specs[name] = "sum"
            elif fx == "mean":
                specs[name] = "mean"
            elif fx in ("max", "min") and allow_minmax:
                specs[name] = fx
            else:
                raise ValueError(
                    f"{type(self).__name__} cannot wrap {child}: state {name!r} has "
                    f"dist_reduce_fx={fx!r}, which has no {self._KIND_NAME} rule."
                )
        return specs

    def _delta_state(self, args: tuple, kwargs: dict) -> Dict[str, Any]:
        """The wrapped metric's update applied to a fresh default state —
        the batch's state contribution, guard included (its fault counters
        land in the delta's ``_faults``)."""
        child = self.wrapped
        prev = child.__dict__["_state"]
        object.__setattr__(child, "_state", dict(child._defaults))
        try:
            child._original_update(*args, **kwargs)
            return dict(child.__dict__["_state"])
        finally:
            object.__setattr__(child, "_state", prev)

    def _run_child_compute(self, state: Dict[str, Any]) -> Any:
        child = self.wrapped
        prev = child.__dict__["_state"]
        object.__setattr__(child, "_state", state)
        try:
            return child._original_compute()
        finally:
            object.__setattr__(child, "_state", prev)

    # -- fault channel over the wrapper's aggregated counters -----------

    def _aggregated_fault_counts(self) -> Optional[Array]:
        raise NotImplementedError

    @property
    def fault_counts(self) -> Optional[Dict[str, int]]:
        """The wrapped metric's fault counters under this wrapper's
        aggregation (windowed counters expire with their bucket; decayed
        counters never decay), plus the wrapper's OWN counters when it has
        any (``pad_batches=True`` records ``padded_rows`` at the wrapper
        level — pads never expire, they are bookkeeping, not stream
        evidence). ``None`` when neither channel exists or the state is
        traced — same contract as ``Metric.fault_counts``."""
        from metrics_tpu.utilities.guard import FAULT_CLASSES, INFORMATIONAL_FAULT_CLASSES

        counts = self._aggregated_fault_counts()
        own = self._state.get("_faults")
        if counts is None and own is None:
            return None
        try:
            host = np.zeros(len(FAULT_CLASSES), np.int64)
            if counts is not None:
                host += np.asarray(counts).astype(np.int64)
            if own is not None:
                own_host = np.asarray(own.counts).astype(np.int64)
                if counts is not None and self.on_invalid in ("warn", "error"):
                    # a counting-only wrapper guard saw the same rows the
                    # propagated child guard counted into the ring — adding
                    # its validator classes would double-count every fault.
                    # Only the wrapper-level pad bookkeeping is unique to
                    # `own` here. (Under 'drop' the wrapper guard CONSUMES
                    # the faulty rows — the child sees clean data, the ring
                    # stays empty, and `own` is the authoritative channel.)
                    keep = np.array(
                        [name in INFORMATIONAL_FAULT_CLASSES for name in FAULT_CLASSES]
                    )
                    own_host = np.where(keep, own_host, 0)
                host += own_host
        except _TRACE_ERRORS:
            return None
        return {name: int(host[i]) for i, name in enumerate(FAULT_CLASSES)}

    def _check_faults(self) -> None:
        """Apply the CHILD's ``on_invalid`` policy at this wrapper's eager
        compute boundary, from the aggregated counters."""
        policy = getattr(self.wrapped, "on_invalid", "ignore")
        if policy in ("ignore", "drop"):
            return
        counts = self._aggregated_fault_counts()
        if counts is None:
            return
        try:
            host = np.asarray(counts).astype(np.int64)
        except _TRACE_ERRORS:
            return
        from metrics_tpu.utilities.guard import actionable_fault_total, format_fault_report

        total = actionable_fault_total(host)
        owner = f"{type(self).__name__}({type(self.wrapped).__name__})"
        if policy == "error":
            if total > 0:
                raise MetricsTPUUserError(format_fault_report(host, owner))
            return
        if total <= self._faults_reported:
            return
        self._faults_reported = total
        rank_zero_warn(format_fault_report(host, owner), UserWarning)

    def reset(self) -> None:
        super().reset()
        self.wrapped.reset()

    def __setstate__(self, state: Dict[str, Any]) -> None:
        super().__setstate__(state)
        # pickles from builds with fewer fault classes: Metric.__setstate__
        # widens the raw ``win___faults``/``dec___faults`` state rings, but
        # the windowed per-state identity rows live in a plain attribute and
        # must widen with them or the first bucket rotation shape-mismatches
        from metrics_tpu.utilities.guard import NUM_FAULT_CLASSES

        idents = self.__dict__.get("_identities")
        if idents:
            for name, kind in self._specs.items():
                v = idents.get(name)
                if kind == "faults" and v is not None and v.shape[-1] < NUM_FAULT_CLASSES:
                    pad = jnp.zeros((NUM_FAULT_CLASSES - v.shape[-1],), v.dtype)
                    idents[name] = jnp.concatenate([v, pad])

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.wrapped!r})"


class WindowedMetric(_StreamingWrapper):
    """Sliding-window view of a sum/mean/max/min-reduced metric.

    ``WindowedMetric(Accuracy(), window=8192, buckets=8)`` reports accuracy
    over (at most) the trailing 8192 rows from eight 1024-row
    sub-accumulator buckets — exactly the trailing 8192 whenever update
    batches align with bucket boundaries (see the module docstring for the
    attribution rule). State is ``buckets`` copies of the wrapped metric's
    fixed-shape states; update and compute are one fused XLA program each.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SumMetric, WindowedMetric
        >>> m = WindowedMetric(SumMetric(), window=4, buckets=2)
        >>> for v in (1.0, 2.0, 3.0, 4.0):
        ...     m.update(jnp.asarray([v, v]))
        >>> float(m.compute())  # last 4 rows: two 2-row updates of 3s, 4s
        14.0
    """

    def __init__(self, metric: Metric, window: int, buckets: int = 8, **kwargs: Any) -> None:
        super().__init__(metric, **kwargs)
        if not (isinstance(window, int) and window >= 1):
            raise ValueError(f"`window` must be a positive number of rows, got {window}")
        if not (isinstance(buckets, int) and 1 <= buckets <= window):
            raise ValueError(f"`buckets` must be an int in [1, window], got {buckets}")
        if window % buckets:
            raise ValueError(
                f"`window` ({window}) must be divisible by `buckets` ({buckets}) so every "
                "bucket covers the same row quota"
            )
        self.window = window
        self.buckets = buckets
        self.bucket_len = window // buckets
        self._specs = self._child_state_specs(allow_minmax=True)
        self._identities: Dict[str, Array] = {}

        from metrics_tpu.utilities.guard import NUM_FAULT_CLASSES

        B = buckets
        for name, kind in self._specs.items():
            if kind == "faults":
                identity = jnp.zeros((NUM_FAULT_CLASSES,), jnp.uint32)
                fx = "sum"
            else:
                identity = jnp.asarray(self.wrapped._defaults[name])
                fx = {"sum": "sum", "mean": "sum", "max": "max", "min": "min"}[kind]
            self._identities[name] = identity
            ring = jnp.broadcast_to(identity[None], (B,) + identity.shape) + jnp.zeros_like(
                identity
            )
            self.add_state(f"win__{name}", default=ring, dist_reduce_fx=fx)
        # bucket bookkeeping: head/fill are SPMD-replicated (max = identity
        # across equal ranks); per-bucket update/row tallies sum globally
        self.add_state("win__head", default=jnp.zeros((), jnp.int32), dist_reduce_fx="max")
        self.add_state("win__fill", default=jnp.zeros((), jnp.int32), dist_reduce_fx="max")
        self.add_state("win__n_updates", default=jnp.zeros((B,), jnp.int32), dist_reduce_fx="sum")
        self.add_state("win__rows", default=jnp.zeros((B,), jnp.int32), dist_reduce_fx="sum")

    def update(self, *args: Any, **kwargs: Any) -> None:
        n = _leading_rows(args, kwargs)
        # the span warning judges REAL rows: under the padding ladder (or an
        # explicit mask) a 70-row request padded to a 128-row tier consumes
        # 70 rows of quota, and warning on 128 would be false. A traced mask
        # has no concrete popcount — skip the warning rather than guess. The
        # popcount is a blocking host read, so it only runs while the warning
        # can still fire: n bounds n_real from above, and warn-once means
        # a fired warning ends the check for the metric's lifetime.
        if (
            n is not None
            and n > self.bucket_len
            and not self.__dict__.get("_batch_span_warned")
        ):
            n_real: Optional[int] = n
            valid_in = kwargs.get("valid")
            if valid_in is not None:
                if _is_concrete(valid_in):
                    n_real = int(np.asarray(valid_in).astype(bool).sum())
                else:
                    n_real = None
            if n_real is not None and n_real > self.bucket_len:
                # n_real is concrete, so this fires at trace/call time, once:
                # oversized batches make the covered span buckets*batch
                # instead of `window` — defined behavior, but never silent
                object.__setattr__(self, "_batch_span_warned", True)
                rank_zero_warn(
                    f"{type(self).__name__}({type(self.wrapped).__name__}): update batches of "
                    f"{n_real} rows exceed the {self.bucket_len}-row bucket quota (window={self.window}, "
                    f"buckets={self.buckets}); each batch fills a whole bucket, so the covered span "
                    f"grows toward {self.buckets * n_real} rows instead of {self.window}. Size `buckets` "
                    "so window/buckets is at least the batch size (check `window_rows` for the span "
                    "actually covered).",
                    UserWarning,
                )
        delta = self._delta_state(args, kwargs)
        B = self.buckets
        head = self.win__head
        fill = self.win__fill
        # lazy rotation: the bucket that reached its quota stays readable
        # until the next update needs a slot (so a just-filled window
        # computes over ALL buckets, i.e. exactly `window` rows)
        rotate = fill >= self.bucket_len
        head = jnp.where(rotate, (head + 1) % B, head)
        onehot = jnp.arange(B) == head

        def roll(ring: Array, identity: Array, add: Callable[[Array, Array], Array], leaf: Array) -> Array:
            mask = (rotate & onehot).reshape((B,) + (1,) * (ring.ndim - 1))
            ring = jnp.where(mask, identity, ring)  # expire the reused slot
            return add(ring, leaf)

        for name, kind in self._specs.items():
            ring_name = f"win__{name}"
            leaf = delta[name].counts if kind == "faults" else jnp.asarray(delta[name])
            if kind == "max":
                add = lambda r, v: r.at[head].max(v)
            elif kind == "min":
                add = lambda r, v: r.at[head].min(v)
            else:
                add = lambda r, v: r.at[head].add(v)
            setattr(self, ring_name, roll(getattr(self, ring_name), self._identities[name], add, leaf))
        # row accounting counts REAL rows: under the padding ladder (or an
        # explicit `valid` mask) pad/masked rows contribute no delta, so
        # they must not consume window quota either
        valid = kwargs.get("valid")
        rows = jnp.asarray(valid, bool).sum().astype(jnp.int32) if valid is not None else jnp.int32(n)
        self.win__n_updates = roll(
            self.win__n_updates, jnp.zeros((), jnp.int32), lambda r, v: r.at[head].add(v), jnp.int32(1)
        )
        self.win__rows = roll(
            self.win__rows, jnp.zeros((), jnp.int32), lambda r, v: r.at[head].add(v), rows
        )
        self.win__fill = jnp.where(rotate, 0, fill) + rows
        self.win__head = head

    def _window_child_state(self) -> Dict[str, Any]:
        from metrics_tpu.utilities.guard import FaultCounters

        state: Dict[str, Any] = {}
        for name, kind in self._specs.items():
            ring = getattr(self, f"win__{name}")
            if kind == "sum":
                state[name] = ring.sum(axis=0)
            elif kind == "mean":
                total = jnp.maximum(self.win__n_updates.sum(), 1)
                state[name] = ring.sum(axis=0) / total
            elif kind == "max":
                state[name] = ring.max(axis=0)
            elif kind == "min":
                state[name] = ring.min(axis=0)
            else:  # faults
                state[name] = FaultCounters(counts=ring.sum(axis=0))
        return state

    def compute(self) -> Any:
        return self._run_child_compute(self._window_child_state())

    @property
    def window_rows(self) -> Optional[int]:
        """Rows currently covered by the window (None while traced)."""
        try:
            return int(self.win__rows.sum())
        except _TRACE_ERRORS:
            return None

    def _aggregated_fault_counts(self) -> Optional[Array]:
        ring = self._state.get("win___faults")
        return None if ring is None else ring.sum(axis=0)


class DecayedMetric(_StreamingWrapper):
    """Exponentially-decayed view of a sum/mean-reduced metric.

    Each accumulated row's weight halves every ``halflife`` rows, so the
    value tracks the recent stream with smooth forgetting — the
    infinite-window complement of :class:`WindowedMetric`'s hard cutoff.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import DecayedMetric, MeanMetric
        >>> m = DecayedMetric(MeanMetric(nan_strategy="ignore"), halflife=1.0)
        >>> for v in (0.0, 0.0, 1.0):
        ...     m.update(jnp.asarray([v]))
        >>> round(float(m.compute()), 4)  # weights 2^-2, 2^-1, 1 -> 4/7
        0.5714

    """

    _KIND_NAME = "decay"

    def __init__(self, metric: Metric, halflife: float, **kwargs: Any) -> None:
        super().__init__(metric, **kwargs)
        if not (float(halflife) > 0):
            raise ValueError(f"`halflife` must be a positive number of rows, got {halflife}")
        self.halflife = float(halflife)
        self._specs = self._child_state_specs(allow_minmax=False)

        from metrics_tpu.utilities.guard import NUM_FAULT_CLASSES

        for name, kind in self._specs.items():
            if kind == "faults":
                default = jnp.zeros((NUM_FAULT_CLASSES,), jnp.uint32)
            else:
                # decayed accumulators are fractional by construction
                default = jnp.zeros(jnp.shape(self.wrapped._defaults[name]), jnp.float32)
            self.add_state(f"dec__{name}", default=default, dist_reduce_fx="sum")
        self.add_state("dec__n_updates", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    def update(self, *args: Any, **kwargs: Any) -> None:
        n = _leading_rows(args, kwargs)
        delta = self._delta_state(args, kwargs)
        # decay judges REAL rows: under the padding ladder (or an explicit
        # `valid` mask) pad/masked rows contribute no delta, so they must
        # not age the accumulated history either — a 5-row request padded
        # to a 128-row tier decays by 5 rows, not 128
        valid = kwargs.get("valid")
        if valid is not None:
            rows = jnp.asarray(valid, bool).sum().astype(jnp.float32)
            factor = jnp.exp2(-rows / jnp.float32(self.halflife))
        else:
            factor = jnp.float32(2.0 ** (-n / self.halflife))  # n is static
        for name, kind in self._specs.items():
            dec_name = f"dec__{name}"
            if kind == "faults":
                # fault evidence does not fade
                setattr(self, dec_name, getattr(self, dec_name) + delta[name].counts)
            else:
                setattr(
                    self,
                    dec_name,
                    getattr(self, dec_name) * factor + jnp.asarray(delta[name], jnp.float32),
                )
        self.dec__n_updates = self.dec__n_updates * factor + 1.0

    def _decayed_child_state(self) -> Dict[str, Any]:
        from metrics_tpu.utilities.guard import FaultCounters

        state: Dict[str, Any] = {}
        for name, kind in self._specs.items():
            dec = getattr(self, f"dec__{name}")
            if kind == "faults":
                state[name] = FaultCounters(counts=dec)
            elif kind == "mean":
                state[name] = dec / jnp.maximum(self.dec__n_updates, jnp.float32(1e-30))
            else:
                state[name] = dec
        return state

    def compute(self) -> Any:
        return self._run_child_compute(self._decayed_child_state())

    def _aggregated_fault_counts(self) -> Optional[Array]:
        return self._state.get("dec___faults")

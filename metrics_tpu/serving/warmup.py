"""AOT warmup engine + persistent compile cache: zero-trace serving cold start.

The padding ladder (``ops/padding.py``) bounds how MANY graphs ragged
traffic compiles, but every tier still traces on its FIRST live request —
the one serving latency wall steady-state numbers never show: a request
that lands on a cold tier pays trace + lower + XLA compile (hundreds of
milliseconds on this box) instead of the ~2 ms warm path. This module moves
that cost off the request path, the same stance T3 takes with collectives
(PAPERS.md): do the expensive work ahead of time and overlap it with live
serving.

Three layers:

1. **AOT precompilation** (:class:`WarmupEngine`). The warmup matrix —
   padding-ladder tiers x the served metric tree's update graphs, plus each
   member's compute graph (the graph ServeLoop's AsyncSyncScheduler reduce
   runs per cycle) — is enumerated from a caller-provided example batch
   (:class:`Warmup`) and precompiled via ``jit(...).lower(avals).compile()``
   against ``ShapeDtypeStruct`` avals: no real data, no device steps, on a
   background thread, largest tier first (the most expensive miss wins
   first). Compiled executables land in shared tables consulted by
   :class:`AOTDispatcher` — installed as the replicas' ``_update_jit`` /
   ``_compute_jit`` slots — so a warmed tier's first live request calls a
   ready executable: **zero traces, zero compiles**. The engine traces on an
   isolated clone (never a live replica: two concurrent traces through one
   instance's state-swap would tear), and executables are shared across
   every replica AND every reporter clone the reduce cycle builds — the
   per-clone re-trace the reporter path used to pay per reduce is gone too.

2. **Persistent compile cache** (:func:`configure_compile_cache`).
   ``METRICS_TPU_COMPILE_CACHE_DIR`` points jax's persistent compilation
   cache at a directory on the shared ``_envtools`` warn-once contract: a
   restarted host's warmup finds every executable the previous process
   compiled already serialized and pays deserialization only — a warm
   restart compiles **0 graphs**. An unwritable/uncreatable path warns once
   and degrades to normal in-process compilation; a corrupt cache ENTRY is
   jax's own miss path (it recompiles) — a bad cache can cost compile time,
   never correctness.

3. **Observability.** Warmup state (``pending/running/done/failed``) rides
   ``ServeLoop.health()["serving"]["warmup"]``; ``serve_warmup_seconds`` /
   ``serve_warmup_graphs`` gauges and the always-on
   ``metric_jit_retrace_total`` counter (``obs/runtime_metrics.py``) make
   "zero traces after warmup" scrapeable in production; ``serve_warmup_done``
   (informational — never flips ``degraded``) and ``serve_warmup_error``
   (loud) land in the :class:`HealthRegistry`. A warmup failure NEVER blocks
   or degrades serving: the untraced path still works, per the
   dispatch-layer fallback stance.

**Static-config safety.** A compiled executable is only valid for the
instance configuration it was traced under. Aval keys cover the dynamic
side (state/argument shapes+dtypes); the data-inferred side — Accuracy's
input ``mode``, AUROC's ``num_classes``, everything in ``_snapshot_attrs``
— is folded into the table key as a *static key* read from the live
instance at call time, so an example batch that implied a different input
mode than real traffic can never serve a wrong executable: the key misses
and the normal jit path takes over (correctness by fallback, the
``ops/dispatch.py`` rule).

Module import performs python work only (no jax calls, no device arrays —
the hang-proof bootstrap contract, ``utilities/backend.py``); jax loads
lazily at the first compile/aval build.

The enforcement story lives in ``analysis/registry.py``'s
``warmed_ladder_serving`` entry: ``audit_recompilation``'s warmed-sweep
budget proves a ladder precompiled tier-by-tier serves the 13-size ragged
sweep with 0 new traces, and a seeded warmup-matrix gap fails the audit.
"""
import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from metrics_tpu.ops._envtools import EnvParse, WarnOnce, bool_token

__all__ = [
    "Warmup",
    "WarmupEngine",
    "AOTDispatcher",
    "configure_compile_cache",
    "warmup_enabled",
    "reset_warmup_state",
]

_CACHE_ENV = "METRICS_TPU_COMPILE_CACHE_DIR"
_WARMUP_ENV = "METRICS_TPU_WARMUP"

_warn_once = WarnOnce()


def _parse_warmup(raw: str) -> bool:
    value = bool_token(raw)
    if value is None:
        _warn_once(
            ("warmup", raw),
            f"{_WARMUP_ENV}={raw!r} is not a boolean token (1/0/true/false/on/off/"
            "yes/no); warmup stays enabled (a bad env var degrades nothing here).",
        )
        return True
    return value


_ENV_WARMUP: "EnvParse[bool]" = EnvParse(_WARMUP_ENV, _parse_warmup, True)

# the cache-dir var carries a path, not a token: the "parse" is the
# side-effecting application in configure_compile_cache (makedirs + probe +
# jax config write, memoized on the raw value there) — the EnvParse here is
# identity, existing so the READ rides the shared env contract
_ENV_CACHE_DIR: "EnvParse[str]" = EnvParse(_CACHE_ENV, lambda raw: raw, "")


def warmup_enabled() -> bool:
    """Is AOT warmup allowed? ``METRICS_TPU_WARMUP=0`` is the operator
    escape hatch (skip precompilation, serve with on-demand tracing —
    degraded cold-start perf, identical correctness); default on."""
    return _ENV_WARMUP()


# -- persistent compile cache ----------------------------------------------

# memoized application: (raw env value, active dir or None) — the jax
# config write happens once per distinct value, not per warmup run
_cache_applied: Optional[Tuple[str, Optional[str]]] = None


def configure_compile_cache() -> Optional[str]:
    """Point jax's persistent compilation cache at
    ``METRICS_TPU_COMPILE_CACHE_DIR`` (creating it if needed).

    Returns the active cache directory, or ``None`` when the var is unset
    or the path is unusable (not creatable / not writable / jax rejected
    it) — each failure warns ONCE and degrades to normal in-process
    compilation, never an error (the shared env contract). The entry-size
    and min-compile-time floors are dropped to zero so every serving graph
    is cached: the default jax floors (1 s compile time) would silently
    skip exactly the small per-tier graphs a restarted host wants back.
    """
    global _cache_applied
    raw = _ENV_CACHE_DIR()
    if _cache_applied is not None and _cache_applied[0] == raw:
        return _cache_applied[1]
    if not raw:
        _cache_applied = (raw, None)
        return None
    active: Optional[str] = None
    try:
        os.makedirs(raw, exist_ok=True)
        probe = os.path.join(raw, f".metrics_tpu_probe_{os.getpid()}")
        # writability probe, removed immediately: torn-write durability is
        # meaningless here — tearing IS an acceptable probe outcome
        with open(probe, "w") as f:  # graft-lint: disable=GL502
            f.write("probe")
        os.remove(probe)
    except OSError as err:
        _warn_once(
            ("cache-dir", raw),
            f"{_CACHE_ENV}={raw!r} is not a usable directory ({type(err).__name__}: "
            f"{err}); persistent compile cache disabled — cold starts pay normal "
            "tracing (correctness unaffected)",
        )
        _cache_applied = (raw, None)
        return None
    try:
        import jax
        from jax.experimental.compilation_cache import compilation_cache as _cc

        jax.config.update("jax_compilation_cache_dir", raw)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # jax initializes its cache singleton AT MOST ONCE per process — a
        # compile that ran before this call (or against a previous dir)
        # already burned that once; reset so the next compile re-reads the
        # (new) dir
        _cc.reset_cache()
        active = raw
    except Exception as err:  # noqa: BLE001 - a cache is perf, never correctness
        _warn_once(
            ("cache-config", raw),
            f"jax rejected the persistent compile cache at {raw!r} "
            f"({type(err).__name__}: {err}); continuing without it",
        )
        active = None
    _cache_applied = (raw, active)
    return active


# -- aval keys --------------------------------------------------------------


def _aval_key(tree: Any) -> Any:
    """Hashable structural key of a pytree of arrays: treedef + per-leaf
    (shape, dtype). Non-array leaves (python scalars a caller passed raw)
    key by type — they can never match a table entry built from avals, so
    they fall back to the normal jit path by construction."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            sig.append(("py", type(leaf)))
        else:
            sig.append((tuple(shape), str(dtype)))
    return treedef, tuple(sig)


def _leading_rows(call_args: tuple) -> Optional[int]:
    """The padded request's row count (= its ladder tier): leading axis of
    the first >=1-dim array leaf of the call's ARGUMENT trees — position 0
    is the state dict, whose leading axes are state geometry, not tiers
    (a compute call has no argument tree and reports None)."""
    from metrics_tpu.ops.padding import leading_rows

    return leading_rows(call_args[1:])


def _avals_of(tree: Any) -> Any:
    """The tree with every array leaf replaced by its ``ShapeDtypeStruct``
    (no data, no device buffers) — what ``jit(...).lower`` traces against."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(tuple(x.shape), x.dtype), tree
    )


def _example_aval(value: Any, rows: Optional[int]) -> Any:
    """A ``ShapeDtypeStruct`` for one example-batch leaf, its leading axis
    replaced by ``rows`` (None = keep). The dtype is canonicalized exactly
    as the padding path's ``jnp.asarray`` would (float64 -> float32 under
    the default x64-off config), so the warmed aval matches the live one."""
    import jax
    import numpy as np

    arr = value if hasattr(value, "shape") and hasattr(value, "dtype") else np.asarray(value)
    dtype = jax.dtypes.canonicalize_dtype(arr.dtype)
    shape = tuple(arr.shape)
    if rows is not None and len(shape) >= 1:
        shape = (rows,) + shape[1:]
    return jax.ShapeDtypeStruct(shape, dtype)


# -- the dispatcher ---------------------------------------------------------

# sentinel: no config verified yet (None is a legal verified value when the
# dispatcher has no owner-side statics to compare)
_UNVERIFIED = object()

# memoized lazy import (serving/loop.py imports this module at class-build
# time; the reverse import must stay function-local)
_apply_attrs_fn: Optional[Callable] = None


def _apply_attrs(owner: Any, attrs: Any) -> None:
    global _apply_attrs_fn
    if _apply_attrs_fn is None:
        from metrics_tpu.serving.loop import _apply_inferred_attrs

        _apply_attrs_fn = _apply_inferred_attrs
    _apply_attrs_fn(owner, attrs)


class _TableEntry:
    """One warmed executable plus the configuration it was traced under:
    ``static`` is the template member's ``_snapshot_attrs`` snapshot AFTER
    the trace, ``attrs`` the dotted-path attr dict a serving hit applies to
    its owner (the same values the live trace at these avals would have
    inferred — under trace, data-inferred config is a deterministic
    function of the avals, since tracers have no values to branch on)."""

    __slots__ = ("exe", "static", "attrs")

    def __init__(self, exe: Any, static: Any, attrs: Any) -> None:
        self.exe = exe
        self.static = static
        self.attrs = attrs


def _static_compatible(live: Any, warmed: Any) -> bool:
    """May the live instance use an executable traced under ``warmed``
    config? Every live slot must be still-uninferred (``None`` — the trace
    at these avals would infer exactly the warmed value) or equal; any
    diverged non-None slot disqualifies."""
    if live is warmed or live == warmed:
        return True
    if not (isinstance(live, tuple) and isinstance(warmed, tuple) and len(live) == len(warmed)):
        return False
    for (slot_l, val_l), (slot_w, val_w) in zip(live, warmed):
        if slot_l != slot_w:
            return False
        if val_l is not None and val_l != val_w:
            return False
    return True


class AOTDispatcher:
    """Callable drop-in for a metric's ``_update_jit`` / ``_compute_jit``
    slot with a shared table of AOT-compiled executables in front.

    A call whose aval key is in the table — and whose owner's data-inferred
    config is compatible with the entry's (every ``_snapshot_attrs`` slot
    still-``None`` or equal) — runs the ready executable: zero traces, zero
    compiles, the warmed fast path. Serving a hit also applies the entry's
    inferred attrs to the owner (first-non-None-wins, the serving fold's
    rule): the executable path performs no trace, so the attr inference the
    trace would have done rides the entry instead — sound because inference
    under trace is a deterministic function of the avals the entry is keyed
    on. A miss (unwarmed shape, caller-passed python scalar, DIVERGED
    config — e.g. live traffic inferred a different input mode than the
    warmup example implied) falls through to the lazily-built underlying
    jit: exactly yesterday's behavior, so warmup can only ever remove
    latency, never change what is computed. An executable that rejects its
    arguments at call time is dropped from the table and the jit path
    answers — correctness by fallback, never by trust.

    The table dict is shared across every replica/reporter clone of one
    served prototype (executables are pure state-in/state-out functions,
    instance-independent once compiled); entries are installed by the
    :class:`WarmupEngine` thread via atomic dict assignment.
    """

    def __init__(
        self,
        make_jit: Callable[[], Callable],
        table: Dict[Any, "_TableEntry"],
        owner: Optional[Any] = None,
        exact_static: bool = False,
        kind: str = "update",
    ) -> None:
        self._make_jit = make_jit
        self._jit: Optional[Callable] = None
        self.table = table
        # wall-time tap name (obs/profile.py's live join): serve_aot_update
        # / serve_aot_compute, plus the per-ladder-tier _t{rows} histogram
        self._tap_kind = f"serve_aot_{kind}"
        # weakly held: the dispatcher lives ON the owner metric
        self._owner = weakref.ref(owner) if owner is not None else None
        # exact_static: require the owner's data-inferred slots to EQUAL the
        # entry's (no still-None wildcard). The wildcard is sound only for
        # UPDATE entries, whose trace would infer the slots from these very
        # avals; a COMPUTE trace performs no inference — a mode-None
        # instance's cold compute raises "determine mode first", and a
        # warmed one must do exactly the same, not fabricate a value
        self._exact_static = exact_static
        # the static config the owner was last verified (and attr-synced)
        # against: a serving hit walks the owner's metric tree once, then
        # this memo short-circuits every later hit — sound under the
        # infer-once-then-keep contract. The ONE in-library violation of
        # that contract is the serve worker's poison-request rollback
        # (loop.py restores attr cells, possibly back to None), which calls
        # :meth:`reset_verified` on both slots to re-arm the full check
        self._verified_static: Any = _UNVERIFIED
        self.aot_hits = 0
        self.aot_misses = 0

    def _underlying(self) -> Callable:
        if self._jit is None:
            self._jit = self._make_jit()
        return self._jit

    def _compatible(self, owner: Any, entry: "_TableEntry") -> bool:
        live = _static_key(owner)
        if self._exact_static:
            return live == entry.static
        return _static_compatible(live, entry.static)

    def __call__(self, *args: Any) -> Any:
        from metrics_tpu.obs.trace import tracing_enabled

        if tracing_enabled():
            # the profiler's live join (obs/profile.py): dispatch wall time
            # per warmed graph and per padding tier — priced only while
            # tracing is on, so the warmed hot path stays untouched by
            # default (the cost of this check is one amortized env read)
            t0 = time.perf_counter()
            out = self._dispatch(*args)
            dur_ms = (time.perf_counter() - t0) * 1e3
            from metrics_tpu.obs.runtime_metrics import observe_jit_wall

            observe_jit_wall(self._tap_kind, _leading_rows(args), dur_ms)
            return out
        return self._dispatch(*args)

    def _dispatch(self, *args: Any) -> Any:
        key = _aval_key(args)
        entry = self.table.get(key)
        if entry is not None:
            owner = self._owner() if self._owner is not None else None
            verified = owner is None or entry.static == self._verified_static
            if verified or self._compatible(owner, entry):
                try:
                    out = entry.exe(*args)
                except Exception as err:  # noqa: BLE001 - fall back to the jit, never fail the request
                    # an executable the key matched but the runtime rejected
                    # (committed-device / layout mismatch): evict so every
                    # later call goes straight to the jit, not a re-fail —
                    # LOUDLY: the table is shared by every replica and
                    # future reporter clone, so the whole process just lost
                    # this tier's warmed path for good
                    self.table.pop(key, None)
                    self._note_evicted(err)
                else:
                    if not verified:
                        # first hit against this config: sync the owner's
                        # data-inferred attrs (exactly the writes the trace
                        # this executable replaced would have made — after
                        # which owner static == entry.static, so the memo
                        # spares every later hit the tree walk)
                        if entry.attrs:
                            _apply_attrs(owner, entry.attrs)
                        self._verified_static = entry.static
                    self.aot_hits += 1
                    return out
        self.aot_misses += 1
        return self._underlying()(*args)

    def reset_verified(self) -> None:
        """Re-arm the full compatibility check + attr sync (called by the
        serve worker's poison-request rollback, which may have un-set the
        owner's data-inferred attrs the memo assumed stable)."""
        self._verified_static = _UNVERIFIED

    def _note_evicted(self, err: BaseException) -> None:
        from metrics_tpu.obs.runtime_metrics import registry as _runtime
        from metrics_tpu.resilience.health import record_degradation

        owner = self._owner() if self._owner is not None else None
        _runtime.counter("serve_aot_evicted_total").inc()
        record_degradation(
            "serve_aot_evicted",
            f"warmed executable rejected its arguments at call time and was "
            f"evicted ({type(err).__name__}: {err}); this shape serves through "
            "the normal jit path for the rest of the process",
            metric=type(owner).__name__ if owner is not None else "<unowned>",
        )

    # -- delegation: audits and benches poke the underlying jit -----------

    def lower(self, *args: Any, **kwargs: Any) -> Any:
        return self._underlying().lower(*args, **kwargs)

    def _cache_size(self) -> int:
        jit = self._jit
        return jit._cache_size() if jit is not None else 0


# -- the warmup matrix ------------------------------------------------------


class Warmup:
    """Specification of the warmup matrix for one served metric tree.

    ``example_args`` / ``example_kwargs`` describe ONE representative
    request — shapes and dtypes only, never data (numpy arrays,
    ``ShapeDtypeStruct``\\ s, or anything with ``shape``/``dtype`` all
    work). Every row-aligned leading axis is re-shaped to each padding
    tier; the tier set comes from ``ladder`` (explicit), else the live
    ``METRICS_TPU_PAD_LADDER`` resolution via
    :func:`~metrics_tpu.ops.padding.ladder_tiers`, bounded by ``max_rows``
    (default: the example's own row count — serve bigger batches, raise
    it). ``compute=False`` skips the per-member compute graphs (the
    scheduler-reduce graphs) when only update latency matters.

    The example should look like REAL traffic: data-inferred member config
    (e.g. Accuracy's input ``mode``) is inferred from these avals during
    warmup tracing, exactly as the first live request would infer it — a
    mismatched example costs the warmed fast path (static-key miss, normal
    tracing), never correctness.
    """

    def __init__(
        self,
        example_args: Sequence[Any],
        example_kwargs: Optional[Dict[str, Any]] = None,
        ladder: Optional[Sequence[int]] = None,
        max_rows: Optional[int] = None,
        compute: bool = True,
    ) -> None:
        if not example_args:
            raise ValueError("Warmup needs at least one example update argument")
        self.example_args = tuple(example_args)
        self.example_kwargs = dict(example_kwargs or {})
        self.ladder = tuple(ladder) if ladder is not None else None
        self.max_rows = max_rows
        self.compute = bool(compute)

    def _example_rows(self) -> int:
        import numpy as np

        for v in list(self.example_args) + list(self.example_kwargs.values()):
            shape = getattr(v, "shape", None)
            if shape is None:
                shape = np.asarray(v).shape
            if len(shape) >= 1:
                return int(shape[0])
        raise ValueError(
            "Warmup example has no row-aligned (>=1-dim) argument to enumerate "
            "padding tiers from"
        )

    def tiers(self) -> Tuple[int, ...]:
        """The padding tiers this matrix covers, ascending."""
        from metrics_tpu.ops.padding import ladder_tiers

        max_rows = self.max_rows if self.max_rows is not None else self._example_rows()
        return ladder_tiers(max_rows, ladder=self.ladder)

    def tier_avals(self, tier: int, padded: bool = True) -> Tuple[tuple, dict]:
        """``(args_avals, kwargs_avals)`` of one padded-to-``tier`` request,
        as the module runtime's padded update sees it: every row-aligned
        array re-leading-dimmed to ``tier``, plus the ``(tier,)`` bool
        ``valid`` mask ``pad_update_args`` always attaches.

        ``padded=False`` (a ``pad_batches=False`` member: its live calls
        carry the caller's raw shapes and never a pad mask) keeps the
        example's own row count and attaches no pad mask — but a
        caller-supplied ``valid`` example kwarg (the public row-mask
        argument, which such traffic DOES carry) passes through like any
        other kwarg."""
        import numpy as np

        rows = self._example_rows()

        def leaf(v: Any) -> Any:
            shape = getattr(v, "shape", None)
            if shape is None:
                shape = np.asarray(v).shape
            aligned = padded and len(shape) >= 1 and int(shape[0]) == rows
            return _example_aval(v, tier if aligned else None)

        args = tuple(leaf(v) for v in self.example_args)
        # padded: the caller's valid mask is folded into the pad mask at
        # live time (pad_update_args ANDs them), so the example's is
        # replaced by the (tier,) mask; unpadded: it reaches the update
        # verbatim and must stay in the aval signature
        kwargs = {
            k: leaf(v)
            for k, v in self.example_kwargs.items()
            if not (padded and k == "valid")
        }
        if padded:
            import jax

            kwargs["valid"] = jax.ShapeDtypeStruct((tier,), np.dtype(bool))
        return args, kwargs


def _static_key(metric: Any) -> Any:
    """The data-inferred config snapshot of a metric tree — every
    ``_snapshot_attrs`` slot (``None`` included) as ``((path, attr),
    value)`` pairs, via the ONE canonical walk (``serving/loop.py::
    _attr_slots`` — the same enumeration the snapshot/rollback machinery
    uses), so :func:`_static_compatible` can judge slot-by-slot."""
    from metrics_tpu.serving.loop import _attr_slots

    return tuple(_attr_slots(metric))


class WarmupEngine:
    """Precompile one served prototype's warmup matrix on a background
    thread and install shared executable tables on its replicas.

    Lifecycle: construct → :meth:`install` on each live replica (cheap,
    synchronous — dispatchers with still-empty tables) → :meth:`start` →
    the thread compiles entries largest tier first, publishing each
    executable the moment it is ready (serving goes zero-trace
    progressively). ``status`` walks ``pending → running → done|failed``;
    a failure records ``serve_warmup_error`` and leaves serving on the
    normal tracing path — warmup can degrade nothing but cold-start
    latency.
    """

    def __init__(self, prototype: Any, spec: Warmup, name: Optional[str] = None) -> None:
        if not isinstance(spec, Warmup):
            raise TypeError(
                f"warmup= expects a metrics_tpu.serving.Warmup spec, got {type(spec).__name__}"
            )
        self._proto = prototype
        self.spec = spec
        self.name = name or type(prototype).__name__
        self.status = "pending"
        self.error: Optional[str] = None
        self.graphs_compiled = 0
        self.graphs_skipped = 0
        self.wall_s: Optional[float] = None
        self.started_unix: Optional[float] = None
        # member name -> {"update": table, "compute": table}; tables are the
        # dicts the dispatchers hold — publishing an entry is one atomic
        # dict assignment. Each entry carries its own static/attrs snapshot
        # (see _TableEntry), so install() retains NOTHING: a reporter clone
        # installing once per reduce for the life of the loop leaves no
        # trace on the engine.
        self._tables: Dict[str, Dict[str, Dict[Any, _TableEntry]]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        for member_name, _m in self._iter_members(prototype):
            self._tables[member_name] = {"update": {}, "compute": {}}

    @staticmethod
    def _iter_members(obj: Any) -> List[Tuple[str, Any]]:
        from metrics_tpu.serving.loop import _members

        return _members(obj)

    # -- install -----------------------------------------------------------

    def install(self, obj: Any) -> None:
        """Wire ``obj``'s members (a replica or reporter clone of the
        prototype) to the shared executable tables. Synchronous, cheap (no
        jax work) and retention-free — the engine holds no reference to
        ``obj``; call before the object serves its first request. The
        member's data-inferred attrs stay untouched here: a serving HIT
        applies the matched entry's attrs (the dispatcher's job), and
        traffic whose config diverges from the warmup example simply
        misses to the normal tracing path — warmup never forces example
        config onto live metrics."""
        for member_name, m in self._iter_members(obj):
            tables = self._tables.get(member_name)
            if tables is None:
                continue
            m._update_jit = AOTDispatcher(
                m._make_update_jit, tables["update"], owner=m, kind="update"
            )
            m._compute_jit = AOTDispatcher(
                m._make_compute_jit, tables["compute"], owner=m, exact_static=True, kind="compute"
            )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "WarmupEngine":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"serve-warmup-{self.name}"
        )
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        """Ask the compile loop to stop between entries (shutdown path —
        already-published executables stay valid)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        """Block until the warmup thread finishes; True when it did."""
        if self._thread is None:
            return False
        self._thread.join(timeout=timeout_s)
        return not self._thread.is_alive()

    def state(self) -> Dict[str, Any]:
        """Plain-data warmup status for ``health()`` / exporters."""
        out: Dict[str, Any] = {
            "status": self.status,
            "graphs_compiled": self.graphs_compiled,
            "graphs_skipped": self.graphs_skipped,
            "wall_s": self.wall_s,
        }
        if self.error is not None:
            out["error"] = self.error
        return out

    # -- the compile loop --------------------------------------------------

    def _run(self) -> None:
        from metrics_tpu.obs import trace as _obs_trace
        from metrics_tpu.obs.runtime_metrics import registry as _runtime
        from metrics_tpu.resilience.health import record_degradation

        self.status = "running"
        self.started_unix = time.time()
        t0 = time.monotonic()
        try:
            with _obs_trace.span("serve.warmup", metric=self.name):
                configure_compile_cache()
                self._compile_matrix()
            self.wall_s = time.monotonic() - t0
            _runtime.gauge("serve_warmup_seconds").set(self.wall_s)
            _runtime.gauge("serve_warmup_graphs").set(self.graphs_compiled)
            if self._stop.is_set():
                # shutdown interrupted the matrix: not done, not failed —
                # the published prefix of executables stays valid
                self.status = "stopped"
                return
            self.status = "done"
            record_degradation(
                "serve_warmup_done",
                f"AOT warmup for {self.name} compiled {self.graphs_compiled} graphs "
                f"({self.graphs_skipped} skipped) in {self.wall_s:.2f}s",
                metric=self.name,
                graphs=self.graphs_compiled,
                wall_s=round(self.wall_s, 3),
            )
        except BaseException as err:  # noqa: BLE001 - warmup failure must never kill serving
            self.wall_s = time.monotonic() - t0
            self.status = "failed"
            self.error = f"{type(err).__name__}: {err}"
            _runtime.gauge("serve_warmup_seconds").set(self.wall_s)
            _runtime.gauge("serve_warmup_graphs").set(self.graphs_compiled)
            record_degradation(
                "serve_warmup_error",
                f"AOT warmup for {self.name} failed after {self.graphs_compiled} "
                f"graphs: {self.error} — serving continues on the normal tracing path",
                metric=self.name,
            )

    def _compile_matrix(self) -> None:
        from metrics_tpu.obs.runtime_metrics import registry as _runtime
        from metrics_tpu.serving.loop import _clone, _inferred_attrs

        # an ISOLATED template: tracing swaps instance state in and out, and
        # two concurrent traces through one instance would tear — the live
        # replicas must never be the trace vehicle
        template = _clone(self._proto)
        graphs_gauge = _runtime.gauge("serve_warmup_graphs")
        tiers = sorted(self.spec.tiers(), reverse=True)  # largest miss first
        for member_name, m in self._iter_members(template):
            if self._stop.is_set():
                return
            tables = self._tables[member_name]
            # an unpadded member's live calls carry the caller's raw shapes
            # and no `valid` mask — the tier matrix is meaningless for it
            # (and tracing a pad-mask kwarg it never receives would fail the
            # whole warmup every boot); warm its example shape as given
            padded = bool(getattr(m, "pad_batches", False))
            member_tiers = tiers if padded else [self.spec._example_rows()]
            if not m._can_jit_update() or m.compute_on_cpu or m.debug_checks:
                # eager-only / checkify members never take the jit slot at
                # runtime either — nothing to precompile, nothing lost; the
                # skip count is the member's ACTUAL matrix size, so the
                # compiled+skipped accounting reconciles for mixed trees
                self.graphs_skipped += len(member_tiers) + (1 if self.spec.compute else 0)
                continue
            state_avals = _avals_of(dict(m._defaults))
            update_jit = m._make_update_jit()
            for tier in member_tiers:
                if self._stop.is_set():
                    return
                args_avals, kwargs_avals = self.spec.tier_avals(tier, padded=padded)
                # tracing runs the member's own update body on abstract
                # values: data-inferred attrs (input mode & co) resolve here
                # exactly as the first live request AT THESE AVALS would
                # resolve them — the entry carries that snapshot so a
                # serving hit can apply it (the trace it replaces would
                # have), and a diverged live config misses instead
                exe = update_jit.lower(state_avals, args_avals, kwargs_avals).compile()
                key = _aval_key((state_avals, args_avals, kwargs_avals))
                tables["update"][key] = _TableEntry(exe, _static_key(m), _inferred_attrs(m))
                self.graphs_compiled += 1
                graphs_gauge.set(self.graphs_compiled)
            if self.spec.compute and m._can_jit_compute():
                if self._stop.is_set():
                    return
                compute_jit = m._make_compute_jit()
                exe = compute_jit.lower(state_avals).compile()
                key = _aval_key((state_avals,))
                tables["compute"][key] = _TableEntry(exe, _static_key(m), _inferred_attrs(m))
                self.graphs_compiled += 1
                graphs_gauge.set(self.graphs_compiled)
            elif self.spec.compute:
                self.graphs_skipped += 1


def reset_warmup_state() -> None:
    """Test hook (the shared ``reset_*_state`` contract): clear the
    warn-once memory, the memoized env parses, and the applied-cache memo;
    the jax cache-dir config itself is NOT unset (jax treats it as global
    process state — tests that set it point it at a tmpdir)."""
    global _cache_applied
    _warn_once.reset()
    _ENV_WARMUP.reset()
    _ENV_CACHE_DIR.reset()
    _cache_applied = None

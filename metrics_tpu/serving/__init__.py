"""Serving hardening: thread-safe serve loop with overload shedding.

See :mod:`metrics_tpu.serving.loop` for the design (thread-confined replica
accumulation, merged stale-view reads, shed-on-full ingest) and
:mod:`metrics_tpu.ops.padding` for the padding-tier capacity ladder that
keeps ragged request sizes from recompiling the serving graphs.
"""
from metrics_tpu.serving.loop import ServeLoop  # noqa: F401

__all__ = ["ServeLoop"]

"""Serving hardening: thread-safe serve loop with overload shedding.

See :mod:`metrics_tpu.serving.loop` for the design (thread-confined replica
accumulation, merged stale-view reads, shed-on-full ingest),
:mod:`metrics_tpu.ops.padding` for the padding-tier capacity ladder that
keeps ragged request sizes from recompiling the serving graphs, and
:mod:`metrics_tpu.serving.warmup` for the AOT warmup engine + persistent
compile cache that removes the ladder's first-request trace/compile cost
(``ServeLoop(warmup=Warmup(...))``, ``METRICS_TPU_COMPILE_CACHE_DIR``).
"""
from metrics_tpu.serving.loop import ServeLoop  # noqa: F401
from metrics_tpu.serving.warmup import (  # noqa: F401
    AOTDispatcher,
    Warmup,
    WarmupEngine,
    configure_compile_cache,
    warmup_enabled,
)

__all__ = [
    "ServeLoop",
    "Warmup",
    "WarmupEngine",
    "AOTDispatcher",
    "configure_compile_cache",
    "warmup_enabled",
]

"""Thread-safe serving loop: lock-free accumulation, merged reads, shed-on-full.

The module runtime (``metric.py``) is deliberately single-threaded: two
request threads calling ``metric.update`` concurrently race on
``Metric._state`` (the eager path swaps state per-key — a reader can see a
torn update). This module is the serving answer, built from three rules:

1. **Accumulation is thread-confined.** Each worker thread owns a full
   replica (clone) of the served metric and is the only thread that ever
   updates it — no locks on the request path. After every update the worker
   *publishes* an immutable snapshot of its replica's state (jax arrays are
   immutable; publication is one list-slot assignment, atomic under the
   GIL), so readers never observe a half-applied update.
2. **Reads merge, never block ingestion.** The background reducer is an
   :class:`~metrics_tpu.parallel.async_sync.AsyncSyncScheduler` cycle — the
   SAME double-buffered snapshot→reduce→publish mechanism that powers
   ``Metric(sync_mode='overlapped')``, not a second reduction implementation.
   Each cycle folds the published snapshots through the framework's existing
   merge rules — ``Metric._reduce_states`` (weighted by each replica's
   update count for 'mean' states) and the sketches' own ``sketch_merge`` —
   into a fresh reporter clone and computes it. ``report()`` serves the
   scheduler's front view with its ``staleness_s``; ``report(fresh=True,
   deadline_s=...)`` waits (bounded, on the scheduler's coverage watermark)
   for a view covering every publish that existed at call time, falling
   back to the stale view — the serving path never blocks behind a
   merge/collective (the T3 stance: stale-but-already-reduced beats
   fresh-but-blocking).
3. **Overload sheds loudly.** Ingestion is a bounded queue; ``offer`` on a
   full queue drops the request, counts it, and records an
   ``overload_shed`` event in the process-wide :class:`HealthRegistry`, so
   ``accepted + shed == offered`` always reconciles in ``health_report()``
   — graceful degradation under spike load is counted, never silent.

Pair with ``Metric(pad_batches=True)`` (``ops/padding.py``) so ragged
request sizes compile at most ``len(ladder)`` graphs per replica, and with
a :class:`~metrics_tpu.resilience.snapshot.SnapshotManager` for periodic
crash-safe snapshots: each worker replica saves as one rank of a
``world_size=workers`` group, so the standard elastic restore path merges
them back at ANY new worker count (or into a single offline metric).
"""
import copy
import itertools
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from metrics_tpu.analysis.lockwitness import named_lock
from metrics_tpu.obs import trace as _obs_trace
from metrics_tpu.parallel.async_sync import AsyncSyncScheduler
from metrics_tpu.resilience.health import health_report, record_degradation
from metrics_tpu.utilities.exceptions import MetricsTPUUserError

__all__ = ["ServeLoop"]

# snapshot form of one replica: {member_name: (state_dict, update_count, attrs)}
# where attrs maps child-metric paths ("" = the member itself) to the
# data-inferred `_snapshot_attrs` at that path (e.g. an input-mode enum
# resolved at the first update — a wrapper's wrapped child carries its own).
# Without them a fresh reporter clone could merge the state but not
# compute() it.
_Snapshot = Dict[str, Tuple[Dict[str, Any], int, Dict[str, Dict[str, Any]]]]


def _attr_slots(m: Any, prefix: str = "") -> List[Tuple[Tuple[str, str], Any]]:
    """Every ``_snapshot_attrs`` slot of a metric tree as ``((path, attr),
    value)`` pairs, in tree order, INCLUDING still-``None`` slots — the one
    canonical walk behind :func:`_inferred_attrs` and the warmup
    dispatcher's config key (``serving/warmup.py::_static_key``), so the
    snapshot/rollback view and the executable-compatibility view can never
    enumerate the tree differently."""
    out: List[Tuple[Tuple[str, str], Any]] = [
        ((prefix, a), getattr(m, a, None)) for a in m._snapshot_attrs
    ]
    for name, child in m._named_child_metrics():
        out.extend(_attr_slots(child, f"{prefix}.{name}" if prefix else name))
    return out


def _inferred_attrs(m: Any, prefix: str = "") -> Dict[str, Dict[str, Any]]:
    """Data-inferred ``_snapshot_attrs`` of a metric and (recursively) its
    child metrics, keyed by dotted child path (non-``None`` values only)."""
    out: Dict[str, Dict[str, Any]] = {}
    for (path, attr), value in _attr_slots(m, prefix):
        if value is not None:
            out.setdefault(path, {})[attr] = value
    return out


def _apply_inferred_attrs(m: Any, attrs_by_path: Dict[str, Dict[str, Any]]) -> None:
    """First non-None wins, matching the update path's own
    infer-once-then-keep behavior; unknown paths are skipped (a config
    mismatch surfaces through the state merge, not here)."""
    children = None
    for path, attrs in attrs_by_path.items():
        target = m
        if path:
            if children is None:
                children = dict(m._named_child_metrics())
            head = path.split(".", 1)
            if head[0] not in children:
                continue
            _apply_inferred_attrs(children[head[0]], {head[1] if len(head) > 1 else "": attrs})
            continue
        for a, v in attrs.items():
            if getattr(target, a, None) is None:
                setattr(target, a, v)


def _attr_cells(m: Any) -> List[Tuple[Any, str, Any]]:
    """``(owner, attr, value)`` cells for every ``_snapshot_attrs`` slot of a
    metric and (recursively) its child metrics — INCLUDING still-None slots,
    so a rollback can un-set attrs a failed update inferred (e.g. Accuracy's
    ``mode``, or its ``subset_accuracy`` flip) before failing."""
    out: List[Tuple[Any, str, Any]] = [(m, a, getattr(m, a, None)) for a in m._snapshot_attrs]
    for _, child in m._named_child_metrics():
        out.extend(_attr_cells(child))
    return out


def _is_collection(obj: Any) -> bool:
    return hasattr(obj, "_modules") and hasattr(obj, "items")


def _clone(obj: Any) -> Any:
    new = copy.deepcopy(obj)
    new.reset()
    return new


def _members(obj: Any) -> List[Tuple[str, Any]]:
    """(name, Metric) pairs — one ("", obj) pair for a plain metric.
    ``copy_state=False``: read-only sweeps over a (possibly compute-group
    aliased) collection, same stance as ``health_report``."""
    if _is_collection(obj):
        return list(obj.items(keep_base=True, copy_state=False))
    return [("", obj)]


def _snapshot_of(obj: Any) -> _Snapshot:
    """A consistent, immutable state snapshot of one replica. Taken by the
    thread that owns the replica (between updates), so it never tears."""
    return {
        name: (m._copy_state(), m._update_count, _inferred_attrs(m)) for name, m in _members(obj)
    }


def _fold_snapshot(target: Any, snap: _Snapshot) -> None:
    """Merge one published snapshot into ``target`` through the framework's
    merge rules: ``_reduce_states`` with the replica's update count as the
    weight (exact for sum/cat/max/min/FaultCounters; count-weighted for
    'mean' states; sketches union through ``sketch_merge``). Data-inferred
    attrs (``_snapshot_attrs`` — e.g. Accuracy's input ``mode``) carry over
    too: first non-None wins, matching the update path's own
    infer-once-then-keep behavior."""
    for name, m in _members(target):
        state, count, attrs = snap[name]
        if count == 0:
            continue
        _apply_inferred_attrs(m, attrs)
        merged = m._reduce_states(m._copy_state(), state, m._update_count, batch_count=count)
        object.__setattr__(m, "_state", merged)
        m._update_count += count
        m._update_called = True
        m._computed = None


class ServeLoop:
    """Serve a metric (or ``MetricCollection``) under concurrent traffic.

    Example::

        loop = ServeLoop(Accuracy(num_classes=10, on_invalid="drop",
                                  pad_batches=True), workers=4)
        ok = loop.offer(preds, target)        # False = shed (queue full)
        view = loop.report()                   # last reduced value + staleness_s
        view = loop.report(fresh=True, deadline_s=0.2)  # bounded wait
        loop.stop()

    ``metric`` is used as the pristine prototype: every worker gets a fresh
    clone, and reads merge the clones — the caller's instance is never
    touched by the loop's threads.

    ``warmup=`` takes a :class:`~metrics_tpu.serving.Warmup` spec (one
    representative request's shapes/dtypes) and starts the AOT warmup
    engine (``serving/warmup.py``): the padding-ladder x metric-tree
    matrix precompiles on a background thread into shared executable
    tables, so warmed tiers serve their FIRST live request with zero
    traces and zero compiles; progress rides
    ``health()["serving"]["warmup"]``, ``METRICS_TPU_WARMUP=0`` skips it,
    and ``METRICS_TPU_COMPILE_CACHE_DIR`` persists the compiles across
    restarts. A warmup failure is loud (``serve_warmup_error``) but never
    blocks or degrades serving — the untraced path still works.

    ``drift_monitors=`` takes :class:`~metrics_tpu.obs.DriftMonitor`
    instance(s) (one, a list, or a ``{name: monitor}`` dict): each watches
    one value stream of the ACCEPTED traffic (its ``extract`` hook;
    default first positional argument, O(1) on the offer path) and runs
    its host-side check on the reducer cadence — a distribution shift vs
    the blessed reference records a ``drift_detected`` health event and
    crosses the scraped ``metrics_tpu_drift_*`` gauges within one window
    rotation, and per-host scores federate through ``fleet_extra()``
    (``obs/drift.py``).

    **Windowed members.** A served :class:`~metrics_tpu.WindowedMetric`
    keeps its time-bucket ring per replica, and replicas rotate buckets at
    their own head positions — so the merged view is the SUM of per-worker
    trailing windows, covering between ``window`` (all traffic on one
    worker) and ``workers * window`` (even spread) rows of global traffic,
    not a global trailing ``window``. Size ``window`` as a per-worker
    budget (``global_budget / workers``) when a fixed global span matters.
    """

    def __init__(
        self,
        metric: Any,
        workers: int = 2,
        queue_size: int = 256,
        reduce_every_s: float = 0.25,
        snapshot_manager: Optional[Any] = None,
        snapshot_every_s: Optional[float] = None,
        sync_transport: Optional[str] = None,
        warmup: Optional[Any] = None,
        drift_monitors: Optional[Any] = None,
    ) -> None:
        if workers < 1:
            raise ValueError(f"`workers` must be >= 1, got {workers}")
        if queue_size < 1:
            raise ValueError(f"`queue_size` must be >= 1, got {queue_size}")
        if snapshot_every_s is not None and snapshot_manager is None:
            raise ValueError("`snapshot_every_s` needs a `snapshot_manager`")
        # quantized sync transport (ops/quantize.py): the wire codec the
        # BACKGROUND reduce's cross-process gathers ship float state through
        # — the served report is a deliberately-stale view already, so a
        # compressed reduce trades precision nobody reads at full width for
        # DCN bandwidth (multi-host pods only; the in-process fold is
        # byte-free either way). None resolves METRICS_TPU_SYNC_TRANSPORT >
        # 'exact' per reduce; counters / int states always stay bit-exact.
        from metrics_tpu.ops.quantize import validate_transport

        self.sync_transport = validate_transport(sync_transport)
        self.workers = workers
        self.reduce_every_s = float(reduce_every_s)
        self._proto = metric
        self._replicas = [_clone(metric) for _ in range(workers)]
        self._published: List[Optional[_Snapshot]] = [None] * workers
        self._base_snap: Optional[_Snapshot] = None  # restored pre-crash state

        self._queue: "queue.Queue[Tuple[tuple, dict, Any]]" = queue.Queue(maxsize=queue_size)
        self._stats_lock = named_lock("loop._stats_lock", threading.Lock(), hot=True)
        self._offered = 0
        self._accepted = 0
        self._shed = 0
        self._processed = 0
        self._failed = 0
        self._dead_workers = 0

        self._stopping = False  # set under _stats_lock: offer/stop handshake
        self._last_reporter: Optional[Any] = None
        # two-phase shutdown: workers stop (after draining the backlog)
        # BEFORE the scheduler runs its final pass — a "final" reduce racing
        # ahead of workers still mid-backlog would permanently orphan their
        # later publishes from report()
        self._stop_workers = threading.Event()

        self._snapshot_mgr = snapshot_manager
        self._snapshot_every_s = snapshot_every_s
        self._snapshot_step = itertools.count(1)
        self._last_snapshot_unix = time.time()

        # drift monitors (obs/drift.py): each watches one value stream of
        # the offered traffic (its `extract` hook; default first positional
        # arg). Feeding is an O(1) bounded-buffer append on the offer path;
        # checks — the O(sketch) host-side scoring — ride the scheduler's
        # wake cadence below, so a distribution shift pages within one
        # window rotation without any work in a compiled graph.
        self._drift: Dict[str, Any] = {}
        self._drift_error_reported: Dict[str, bool] = {}  # episode-gated
        # set by ANY failing observe/check since the last cadence tick; the
        # tick only re-arms the episode after a fully-clean interval, so a
        # persistently failing extract hook (whose failures live on the
        # offer path, which a successful check says nothing about) still
        # records ONE event per episode, never one per tick
        self._drift_error_recent: Dict[str, bool] = {}
        if drift_monitors is not None:
            if isinstance(drift_monitors, dict):
                # the dict form is labels-as-keys: a key that contradicts
                # the monitor's own name would silently split the surface
                # (events under monitor.name, the caller expecting the key)
                for key, monitor in drift_monitors.items():
                    if key != getattr(monitor, "name", None):
                        raise MetricsTPUUserError(
                            f"drift_monitors key {key!r} != monitor.name "
                            f"{getattr(monitor, 'name', None)!r}; gauges and events are "
                            "labeled by the monitor's own name — use matching keys "
                            "(or pass a list)"
                        )
                monitors = list(drift_monitors.values())
            elif isinstance(drift_monitors, (list, tuple)):
                monitors = list(drift_monitors)
            else:
                monitors = [drift_monitors]
            for monitor in monitors:
                name = getattr(monitor, "name", None)
                if not name or not callable(getattr(monitor, "check", None)):
                    raise MetricsTPUUserError(
                        "`drift_monitors` must be DriftMonitor instances (or a "
                        f"list/dict of them), got {type(monitor).__name__}"
                    )
                if name in self._drift:
                    raise MetricsTPUUserError(
                        f"duplicate drift monitor name {name!r}: each monitor needs a "
                        "distinct name (it labels the exported gauges)"
                    )
                self._drift[name] = monitor
                self._drift_error_reported[name] = False
                self._drift_error_recent[name] = False

        # the background reducer IS an async-sync scheduler cycle: snapshot =
        # sweep the workers' published states (+ any restored base), reduce =
        # clone+fold+compute — the same double-buffer mechanism as
        # Metric(sync_mode='overlapped'), so serving has no private second
        # reduction machinery. Workers notify() per publish; the cadence is
        # time-driven (reduce_every_s), with the snapshot side-cadence riding
        # the scheduler's tick hook.
        self._scheduler = AsyncSyncScheduler(
            snapshot_fn=self._sweep_published,
            reduce_fn=self._reduce_view,
            sync_every_n=None,
            sync_every_s=self.reduce_every_s,
            tick_fn=self._snapshot_tick,
            on_error=self._on_reduce_error,
            name=f"serve-{type(metric).__name__}",
        )

        # AOT warmup (serving/warmup.py): dispatchers with shared executable
        # tables are installed on every replica BEFORE the workers start (so
        # no worker can race the slot), then the engine's background thread
        # fills the tables largest tier first — serving begins immediately
        # and goes zero-trace progressively. METRICS_TPU_WARMUP=0 is the
        # operator escape hatch; a warmup failure records serve_warmup_error
        # and the untraced path keeps serving.
        self._warmup = None
        if warmup is not None:
            from metrics_tpu.serving.warmup import WarmupEngine, warmup_enabled

            if warmup_enabled():
                engine = WarmupEngine(metric, warmup, name=type(metric).__name__)
                for replica in self._replicas:
                    engine.install(replica)
                self._warmup = engine

        # causal tracing (obs/trace.py): the ctx of the newest worker-update
        # span (set at publish — GIL-atomic slot write) and of the reduce
        # that built _last_reporter, so the reduce links back to the traffic
        # it covered and a fleet publish links back to the reduce it ships
        self._publish_ctx = None
        self._last_reporter_ctx = None

        # flight recorder (obs/flightrec.py): this loop's health() —
        # serving/warmup/sync/drift state — rides every black-box dump;
        # detached on stop() so a dump after shutdown reads no dead loop
        from metrics_tpu.obs import flightrec as _flightrec

        self._flightrec_token = _flightrec.attach_source(
            f"serve:{type(metric).__name__}", self.health
        )

        self._threads = [
            threading.Thread(target=self._worker, args=(i,), daemon=True, name=f"serve-worker-{i}")
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()
        if self._warmup is not None:
            self._warmup.start()

    # -- ingestion ------------------------------------------------------

    def offer(self, *args: Any, **kwargs: Any) -> bool:
        """Enqueue one update batch; returns False when the batch was SHED
        (queue full — counted, health-recorded, never silent)."""
        # the count AND the enqueue happen under one lock hold: a request
        # counted accepted is already queued, so stop()'s drain (which reads
        # the same counters before _stop is ever set) can never let a racing
        # offer slip a batch in after the workers have exited — and
        # ``accepted + shed == offered`` holds at every instant. put_nowait
        # never blocks, and nobody nests the queue's lock around
        # ``_stats_lock``, so holding both here cannot deadlock.
        shed = None
        with _obs_trace.span("serve.offer"):
            # the offer span's context rides the queue item: the worker's
            # update span (another thread) becomes its causal child, so a
            # request's chain starts here and survives every hop to the
            # global aggregator's fold (None while tracing is off)
            ctx = _obs_trace.current_context()
            with self._stats_lock:
                if self._stopping:
                    raise MetricsTPUUserError("ServeLoop.offer called after stop()")
                self._offered += 1
                try:
                    self._queue.put_nowait((args, kwargs, ctx))
                    self._accepted += 1
                except queue.Full:
                    self._shed += 1
                    shed = self._shed
        if shed is not None:
            record_degradation(
                "overload_shed",
                f"serve queue full ({self._queue.maxsize}); request shed",
                shed_total=shed,
                metric=type(self._proto).__name__,
            )
            return False
        # drift monitors watch ACCEPTED traffic (the stream the metric will
        # see); observe() is an O(1) bounded append — a monitor failure
        # degrades loudly and never takes the request with it
        for name, monitor in self._drift.items():
            try:
                values = monitor.extract_from(args, kwargs)
                if values is not None:
                    monitor.observe(values)
            except Exception as err:  # noqa: BLE001 — drift degrades, never sheds
                self._record_drift_error(name, err, during="observe")
        return True

    def _worker(self, i: int) -> None:
        # a worker that dies for ANY reason other than the stop handshake —
        # a BaseException escaping the per-request guard (the guard absorbs
        # Exception; SystemExit/KeyboardInterrupt/thread kills pass through)
        # — must be loud: its published snapshots keep serving (reads merge
        # whatever was published), but its share of the backlog silently
        # stops draining, which is exactly the degradation health() exists
        # to surface
        try:
            self._worker_loop(i)
        finally:
            if not self._stop_workers.is_set():
                with self._stats_lock:
                    self._dead_workers += 1
                record_degradation(
                    "serve_worker_died",
                    f"worker {i} exited outside the stop handshake; its queue share "
                    "no longer drains (published state keeps serving)",
                    worker=i,
                    metric=type(self._proto).__name__,
                )

    def _worker_loop(self, i: int) -> None:
        replica = self._replicas[i]
        while True:
            try:
                args, kwargs, offer_ctx = self._queue.get(timeout=0.05)
            except queue.Empty:
                if self._stop_workers.is_set():
                    return
                continue
            # the module runtime increments update counters (and may flip
            # jittable_update off in its TypeError fallback) BEFORE the body
            # can fail, and the eager fallback mutates state per-key — roll
            # all of it back so a poison request leaves the replica exactly
            # as it was: counts weight the 'mean' merge, and a torn state
            # would poison every subsequent reduce. (_copy_state is a
            # shallow copy over immutable jax arrays — cheap.)
            bookkeeping = [
                (m, m._copy_state(), m._update_count, m.jittable_update, _attr_cells(m))
                for _, m in _members(replica)
            ]
            update_ctx = None
            try:
                # the request-latency seam (serve_update_ms): replica update
                # plus the snapshot build — the full per-request cost on the
                # worker (the slot write + notify below are trivial). The
                # offer's context is installed for the span's duration, so
                # this span is the offer span's causal child across threads.
                with _obs_trace.trace_context(offer_ctx):
                    with _obs_trace.span("serve.update", worker=i):
                        update_ctx = _obs_trace.current_context()
                        replica.update(*args, **kwargs)
                        snapshot = _snapshot_of(replica)
            except Exception as err:  # noqa: BLE001 - one bad request must not kill the worker
                for m, state, count, jittable, attr_cells in bookkeeping:
                    object.__setattr__(m, "_state", state)
                    m._update_count = count
                    object.__setattr__(m, "jittable_update", jittable)
                    # data-inferred attrs too: a malformed first batch that
                    # set Accuracy's `mode` before raising would otherwise
                    # poison the replica's mode check for all later traffic
                    for owner, attr, value in attr_cells:
                        setattr(owner, attr, value)
                    # the rollback may have un-set attrs the warmup
                    # dispatchers' verified-config memo assumed stable —
                    # re-arm their full check so the next hit re-syncs
                    for jit_slot in (m.__dict__.get("_update_jit"), m.__dict__.get("_compute_jit")):
                        reset = getattr(jit_slot, "reset_verified", None)
                        if reset is not None:
                            reset()
                with self._stats_lock:
                    self._failed += 1
                record_degradation(
                    "serve_update_error",
                    f"worker {i} update raised {type(err).__name__}: {err}",
                    metric=type(self._proto).__name__,
                )
            else:
                # publish AFTER the update completes: one atomic slot write
                # of an immutable snapshot — readers never see a torn state.
                # The notify lands after the slot write, so the scheduler's
                # coverage watermark is always a sound lower bound.
                self._published[i] = snapshot
                self._publish_ctx = update_ctx  # newest publish's causal ctx
                self._scheduler.notify()
            finally:
                with self._stats_lock:
                    self._processed += 1
                self._queue.task_done()

    # -- reduction / reads ----------------------------------------------

    def _sweep_published(self) -> Tuple[List[_Snapshot], Optional[int]]:
        """Scheduler snapshot hook: one consistent sweep of the restored
        base + every worker's published state (each slot an immutable
        snapshot — the sweep can never tear). Steps is None: the scheduler
        substitutes its notify (publish-sequence) watermark, so
        ``health()["serving"]["sync"]["sync_lag_steps"]`` counts publishes
        behind — a caught-up reducer reads 0, however much traffic flowed."""
        snaps = [s for s in ([self._base_snap] + list(self._published)) if s is not None]
        return snaps, None

    def _reduce_view(self, snaps: List[_Snapshot]) -> Dict[str, Any]:
        """Scheduler reduce hook: one full clone + fold + compute pass over
        the swept snapshots. Raises on failure — the scheduler then keeps
        the previous view (loudly, via :meth:`_on_reduce_error`) and the
        next cadence tick retries. The span links to the NEWEST publish's
        update span (a reduce fans in many publishes; parent_id cannot hold
        N edges, so one representative producer carries the causal chain
        from offer to this fold and onward to any fleet publish)."""
        with _obs_trace.span("serve.reduce", link_to=self._publish_ctx, snapshots=len(snaps)):
            out = self._reduce_view_inner(snaps)
            self._last_reporter_ctx = _obs_trace.current_context()
            return out

    def _reduce_view_inner(self, snaps: List[_Snapshot]) -> Dict[str, Any]:
        reporter = _clone(self._proto)
        if self._warmup is not None:
            # a fresh clone starts with cold jit slots — every reduce used to
            # re-trace compute; the warmed tables make the scheduler's
            # compute graph a ready executable instead
            self._warmup.install(reporter)
        from metrics_tpu.ops.quantize import resolve_codec, wrap_gather_transport

        codec = resolve_codec(self.sync_transport)
        if codec.name != "exact":
            # the reporter's compute() runs the members' cross-process sync;
            # route its gathers through the quantized wire (reporter-local:
            # the prototype and the worker replicas are never touched)
            from metrics_tpu.parallel.sync import gather_all_arrays

            for _name, m in _members(reporter):
                m.dist_sync_fn = wrap_gather_transport(
                    m.dist_sync_fn or gather_all_arrays, codec
                )
        for snap in snaps:
            _fold_snapshot(reporter, snap)
        value = reporter.compute() if snaps else None
        # fault counters of the merged view, per member (None when unguarded);
        # bind the property once — each read is a device-to-host transfer
        faults = {}
        for name, m in _members(reporter):
            fc = getattr(m, "fault_counts", None)
            if fc:
                faults[name or type(m).__name__] = fc
        self._last_reporter = reporter
        return {
            "value": value,
            "computed_unix": time.time(),
            "updates": sum(m._update_count for _, m in _members(reporter)),
            "faults": faults,
        }

    def _on_reduce_error(self, err: BaseException) -> None:
        record_degradation(
            "serve_reduce_error",
            f"reduce/compute raised {type(err).__name__}: {err}",
            metric=type(self._proto).__name__,
        )

    def _record_drift_error(self, name: str, err: BaseException, during: str) -> None:
        """Episode-gated per monitor (the fleet-publisher encode-error
        stance): a persistently failing check on a fast cadence must not
        wheel the bounded event ring; the next successful check re-arms."""
        with self._stats_lock:
            due = not self._drift_error_reported.get(name)
            self._drift_error_reported[name] = True
            self._drift_error_recent[name] = True
        if due:
            record_degradation(
                "drift_check_error",
                f"drift monitor {name!r} {during} raised {type(err).__name__}: {err} "
                "(reported once per episode; the cadence keeps retrying)",
                monitor=name,
            )

    def _drift_tick(self) -> None:
        """Run every monitor's check on the scheduler's wake cadence (the
        reducer cadence): fold pending rows, score vs the reference, fire
        or clear episodes — all host-side, off the request path."""
        for name, monitor in self._drift.items():
            try:
                with _obs_trace.span("serve.drift_check", monitor=name):
                    monitor.check()
                with self._stats_lock:
                    # re-arm the episode only after a FULLY clean interval:
                    # a check succeeding says nothing about extract/observe
                    # failures on the offer path since the last tick
                    if self._drift_error_recent[name]:
                        self._drift_error_recent[name] = False
                    else:
                        self._drift_error_reported[name] = False
            except Exception as err:  # noqa: BLE001 — drift degrades, never kills the reducer
                self._record_drift_error(name, err, during="check")

    def _snapshot_tick(self) -> Optional[float]:
        """Scheduler tick hook: the periodic-snapshot side cadence (plus
        the drift-check cadence — every scheduler wake runs the monitors'
        host-side checks first). Returns seconds until the next snapshot is
        due so the scheduler's wait wakes for whichever of reduce/snapshot
        cadence fires first — a ``snapshot_every_s`` shorter than
        ``reduce_every_s`` is honored even on an idle loop."""
        if self._drift:
            self._drift_tick()
        if self._snapshot_every_s is None:
            return None
        due_in = self._last_snapshot_unix + self._snapshot_every_s - time.time()
        if due_in > 0:
            return due_in
        try:
            self.save_snapshot()
        except Exception as err:  # noqa: BLE001 - snapshots degrade, never kill serving
            # stamp the attempt: a persistently failing writer retries on the
            # cadence instead of busy-spinning a zero wait
            self._last_snapshot_unix = time.time()
            record_degradation(
                "serve_snapshot_error",
                f"periodic snapshot raised {type(err).__name__}: {err}",
            )
        return self._snapshot_every_s

    def report(self, fresh: bool = False, deadline_s: float = 0.5) -> Dict[str, Any]:
        """The merged metric value as last reduced, never blocking ingestion.

        Default: return the latest reduced view immediately with its age
        (``staleness_s``). ``fresh=True``: request an immediate reduce and
        wait for it at most ``deadline_s`` — on timeout the STALE view comes
        back (``fresh`` False in the result), which is the designed
        degradation: a deadline miss costs freshness, not availability.
        """
        got_fresh = False
        if fresh:
            # "fresh" means: a view covering every publish that existed when
            # this call was made — the scheduler's coverage watermark, not
            # "any view swap" (a reduce already in flight when we asked may
            # have swept snapshots predating the latest publishes). Already
            # covered → no forced reduce; scheduler stopped → answer
            # immediately instead of burning the deadline.
            with _obs_trace.span("serve.forced_reduce"):
                got_fresh = self._scheduler.wait_covered(
                    self._scheduler.seq(), deadline_s=max(0.0, deadline_s)
                )
        sync_view = self._scheduler.view()
        view = sync_view.payload if sync_view is not None else None
        # hand out copies of the view's mutable containers: the same view
        # dict serves every reader until the next reduce, so a caller
        # mutating its result must not corrupt other readers
        value = view["value"] if view else None
        if isinstance(value, dict):
            value = dict(value)
        out: Dict[str, Any] = {
            "value": value,
            "updates": view["updates"] if view else 0,
            "faults": {k: dict(v) for k, v in view["faults"].items()} if view else {},
            "staleness_s": (max(0.0, time.time() - view["computed_unix"]) if view else None),
            "fresh": bool(got_fresh),
            "stats": self.stats(),
        }
        return out

    def wait_warmup(self, timeout_s: Optional[float] = None) -> bool:
        """Block until the AOT warmup thread finishes (done, failed, or
        stopped); True when it did within the deadline. False immediately
        when no warmup is configured (no ``warmup=`` spec, or
        ``METRICS_TPU_WARMUP=0``). Serving never requires this — it exists
        for deploy hooks that want "fully warmed" as a readiness signal and
        for tests; check ``health()["serving"]["warmup"]["status"]`` for
        the outcome."""
        if self._warmup is None:
            return False
        return self._warmup.wait(timeout_s=timeout_s)

    def stats(self) -> Dict[str, int]:
        """Request accounting. Invariant: ``accepted + shed == offered``."""
        with self._stats_lock:
            return {
                "offered": self._offered,
                "accepted": self._accepted,
                "shed": self._shed,
                "processed": self._processed,
                "failed": self._failed,
                "dead_workers": self._dead_workers,
                "queue_depth": self._queue.qsize(),
            }

    def health(self) -> Dict[str, Any]:
        """``health_report()`` over the merged view plus serving counters
        (shed events are already first-class registry events, so a shedding
        loop reads ``degraded`` without this extra key)."""
        rep = (
            health_report(self._last_reporter)
            if self._last_reporter is not None
            else health_report()
        )
        sync_view = self._scheduler.view()
        view = sync_view.payload if sync_view is not None else None
        rep["serving"] = {
            **self.stats(),
            "workers": self.workers,
            "queue_capacity": self._queue.maxsize,
            "report_staleness_s": (
                max(0.0, time.time() - view["computed_unix"]) if view else None
            ),
            # the scheduler's own lag view (publishes behind, seconds behind,
            # cycle in flight) — same fields health_report grows per
            # overlapped metric
            "sync": self._scheduler.lag(),
            # AOT warmup status (serving/warmup.py): pending/running/done/
            # failed + graph counts. Informational — a failed warmup records
            # its own serve_warmup_error event; serving itself is unaffected
            "warmup": self._warmup.state() if self._warmup is not None else None,
        }
        if self._drift:
            # the drift surface (obs/drift.py): latest scores, episode
            # flags, window/check accounting per monitor — what the
            # exporters render as metrics_tpu_drift_* gauges
            rep["drift"] = {name: m.status() for name, m in self._drift.items()}
        if self._last_reporter is not None:
            # per-cohort surface (sliced/): each SlicedMetric member's
            # top-N-by-traffic scrape rows (hard label-cardinality cap —
            # see slices_max_labels) + quarantine accounting; rendered as
            # metrics_tpu_slice_* series by the exporters
            from metrics_tpu.sliced import SlicedMetric

            slices = {}
            for name, m in _members(self._last_reporter):
                if isinstance(m, SlicedMetric):
                    try:
                        slices[name or type(m.wrapped).__name__] = m.scrape_slices()
                    except Exception as err:  # noqa: BLE001 — scrape degrades, never sheds
                        slices[name or type(m.wrapped).__name__] = {
                            "error": f"{type(err).__name__}: {err}"
                        }
            if slices:
                rep["slices"] = slices
        return rep

    def fleet_view(self) -> Optional[Dict[str, Any]]:
        """This loop's merged view as a ``snapshot_state`` payload — the
        :class:`~metrics_tpu.fleet.FleetPublisher` source hook (None until
        the first background reduce completes). The reporter behind the
        front view is immutable once published (each reduce builds a fresh
        clone), so snapshotting it here never races the scheduler."""
        reporter = self._last_reporter
        return None if reporter is None else reporter.snapshot_state()

    def fleet_trace_context(self):
        """The trace context of the reduce that built the current
        ``fleet_view()`` reporter — the ``FleetPublisher`` source hook that
        lets a publish span link back to the reduce it ships (and through
        it to the offer that fed the reduce). ``None`` while tracing is
        off or before the first reduce."""
        return self._last_reporter_ctx

    def fleet_extra(self) -> Optional[Dict[str, Any]]:
        """Header extra for this host's fleet publishes (the
        ``FleetPublisher`` source hook, same surface as
        ``Aggregator.fleet_extra``): the per-monitor drift scores +
        episode flags, so the global aggregator's one scrape names WHICH
        host is drifting — a few dozen bytes per host, never sketch
        state."""
        if not self._drift:
            return None
        return {"drift": {name: m.fleet_scores() for name, m in self._drift.items()}}

    def scrape(self, fmt: str = "prometheus") -> str:
        """One exporter scrape over this loop: :meth:`health` (request
        accounting, shed/fault/degradation counters, sync lag) joined with
        the process self-telemetry (``metrics_tpu.obs`` latency histograms
        — populated when ``METRICS_TPU_TRACE`` is on). ``fmt`` is
        ``"prometheus"`` (text exposition format) or ``"json"``; serve it
        over HTTP with :class:`metrics_tpu.obs.TelemetryExporter`
        (``TelemetryExporter(health_fn=loop.health)``)."""
        from metrics_tpu.obs.export import json_text, prometheus_text

        if fmt == "prometheus":
            return prometheus_text(health=self.health())
        if fmt == "json":
            return json_text(health=self.health())
        raise MetricsTPUUserError(f"`fmt` must be 'prometheus' or 'json', got {fmt!r}")

    # -- lifecycle ------------------------------------------------------

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Wait until every ACCEPTED request has been processed (test/
        shutdown helper); False on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._stats_lock:
                done = self._processed >= self._accepted
            if done:
                return True
            time.sleep(0.005)
        return False

    def stop(self, drain: bool = True, timeout_s: float = 10.0) -> None:
        """Stop workers (optionally draining accepted requests first) and
        run a final reduce so ``report()`` covers everything processed.

        Shutdown is two-phase: workers finish the queue backlog and JOIN
        before the scheduler is told to run its final pass — even when
        ``drain=False`` or the drain timed out, every batch a worker
        processed makes it into the final view (a worker outliving its
        join timeout is the one bounded exception; it is a daemon thread
        and its later publishes are lost with the process)."""
        with self._stats_lock:
            self._stopping = True  # offers now raise; accepted set is final
        # a black-box dump after shutdown must not read a dead loop
        from metrics_tpu.obs import flightrec as _flightrec

        _flightrec.detach_source(self._flightrec_token)
        if self._warmup is not None:
            # stop compiling between entries; published executables stay valid
            self._warmup.stop(timeout_s=timeout_s)
        if drain:
            self.drain(timeout_s)
        self._stop_workers.set()
        for t in self._threads:
            t.join(timeout=timeout_s)
        # final scheduler cycle (skipped when the cadence already covered the
        # last publish — a quiet shutdown must not reduce twice back to back)
        self._scheduler.stop(final=True, timeout_s=timeout_s)

    def __enter__(self) -> "ServeLoop":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- snapshots ------------------------------------------------------

    def save_snapshot(self, step: Optional[int] = None) -> int:
        """One crash-safe snapshot group: worker ``i``'s published state is
        rank ``i`` of a ``world_size=workers`` group (the restored base, if
        any, folds into rank 0), all through ``SnapshotManager``'s atomic,
        checksummed writer — so restore works at ANY new worker count via
        the standard elastic merge."""
        if self._snapshot_mgr is None:
            raise MetricsTPUUserError("ServeLoop has no snapshot_manager configured")
        if step is None:
            step = next(self._snapshot_step)
        published = list(self._published)  # one consistent sweep
        for i in range(self.workers):
            scratch = _clone(self._proto)
            if i == 0 and self._base_snap is not None:
                _fold_snapshot(scratch, self._base_snap)
            if published[i] is not None:
                _fold_snapshot(scratch, published[i])
            self._snapshot_mgr.save(scratch, step=step, rank=i, world_size=self.workers)
        self._last_snapshot_unix = time.time()
        return step

    def restore_snapshot(self) -> Dict[str, Any]:
        """Load the newest intact snapshot group (any saved world size) as
        the serve loop's base state: it joins every subsequent reduce and
        the rank-0 slot of every subsequent snapshot.

        Restore must happen BEFORE the loop serves traffic (the crash-
        recovery startup path). On a loop whose workers have already
        published, the restored base would contain the same updates the
        replicas still hold and every later reduce would count them twice —
        so that call refuses instead."""
        if self._snapshot_mgr is None:
            raise MetricsTPUUserError("ServeLoop has no snapshot_manager configured")
        if any(s is not None for s in self._published):
            raise MetricsTPUUserError(
                "ServeLoop.restore_snapshot on a loop that has already served traffic: "
                "the restored base would double-count the replicas' published updates. "
                "Restore into a fresh ServeLoop before offering requests."
            )
        base = _clone(self._proto)
        info = self._snapshot_mgr.restore(base, rank=0, world_size=1)
        self._base_snap = _snapshot_of(base)
        # the base joins the coverage accounting: notify the scheduler so the
        # cadence picks it up and report(fresh=True) waits for a view that
        # provably includes it
        self._scheduler.notify()
        self._scheduler.request()
        return info

"""Padding-tier capacity ladder: ragged batches compile to a FIXED set of graphs.

Production traffic sends batch shapes the compiler has never seen — and
under jit every fresh leading dimension is a fresh trace, a fresh compile,
and (for state-carrying paths) a fresh cache entry in every downstream
consumer. This module bounds that: any incoming batch size pads **up** to
one of a fixed ladder of capacities, so a sweep of arbitrary ragged sizes
compiles at most ``len(ladder)`` graphs (the budget
``analysis/registry.py`` pins via ``audit_recompilation``'s ladder sweep).

Ladder resolution (the established ``METRICS_TPU_*`` env-var contract —
same stance as ``ops/dispatch.py``):

- ``METRICS_TPU_PAD_LADDER`` unset/empty → **pow-2 mode**: tier =
  ``next_pow2(n)``. Unbounded sizes still hit only ``O(log max_n)`` tiers.
- ``METRICS_TPU_PAD_LADDER="64,256,1024"`` → the explicit ascending ladder;
  the smallest tier ``>= n`` wins. A batch larger than every tier warns
  once and falls back to ``next_pow2(n)`` (degrades the graph-count budget,
  never correctness).
- A malformed value (non-integer token, non-positive tier) warns once and
  falls back to pow-2 mode entirely — a bad env var degrades compile
  reuse, never correctness.

The parse is memoized on the raw string and resolution happens at **call
time** (trace time under jit), like every other ``METRICS_TPU_*`` knob:
changing the var does not invalidate already-cached jits.

**Pad-row invisibility.** Padding alone would poison accumulators, so pad
rows ride the framework's existing row-mask machinery: every padded call
carries a ``valid`` mask (real rows True, pad rows False) that the update
consumes — capacity-mode metrics mask the rows out of their ring states,
stat-scores-family metrics (``_valid_mask_always``) zero the rows'
tp/fp/tn/fn contributions before the reduce — and the pad count lands in
the fault channel's ``padded_rows`` class (informational: it never trips
``on_invalid='warn'/'error'`` and never flips ``health_report``'s
``degraded`` flag). Pad VALUES are all-zeros — always clean under the
traced validators (zero probabilities, label 0) — so the guard counts real
faults only, and the ``valid`` mask alone decides visibility.

Module import performs python work only (no jax calls, no device arrays —
the hang-proof bootstrap contract, ``utilities/backend.py``).
"""
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from metrics_tpu.ops._envtools import EnvParse, WarnOnce

__all__ = [
    "pad_ladder",
    "next_pow2",
    "tier_for",
    "ladder_tiers",
    "pad_rows",
    "pad_update_args",
    "supports_row_mask",
    "reset_padding_state",
    "SLICE_STATE_PREFIX",
]

_ENV_VAR = "METRICS_TPU_PAD_LADDER"

# state-name prefix of the sliced subsystem's (K,)-leading ring states
# (``metrics_tpu/sliced``). Defined HERE — the lowest layer that must know
# it — so `leading_rows` can tell a slice axis from a batch tier without
# importing upward.
SLICE_STATE_PREFIX = "sl__"

_warn_once = WarnOnce()


def _parse_ladder(raw: str) -> Optional[Tuple[int, ...]]:
    try:
        tiers = sorted({int(tok.strip()) for tok in raw.split(",") if tok.strip()})
        if not tiers or any(t < 1 for t in tiers):
            raise ValueError("tiers must be positive integers")
        return tuple(tiers)
    except ValueError:
        _warn_once(
            ("env-malformed", raw),
            f"{_ENV_VAR}={raw!r} is malformed (expected comma-separated positive "
            "integers, e.g. '64,256,1024'); falling back to the pow-2 ladder",
        )
        return None


_ladder_env: "EnvParse[Optional[Tuple[int, ...]]]" = EnvParse(_ENV_VAR, _parse_ladder, None)


def pad_ladder() -> Optional[Tuple[int, ...]]:
    """The configured capacity ladder (ascending, deduplicated), or ``None``
    for pow-2 mode. Malformed values warn once and fall back to ``None``."""
    return _ladder_env()


def next_pow2(n: int) -> int:
    """Smallest power of two ``>= n`` (``n >= 1``)."""
    return 1 << max(0, int(n) - 1).bit_length() if n > 1 else 1


def tier_for(n: int, ladder: Optional[Sequence[int]] = None) -> int:
    """The padded capacity for an ``n``-row batch.

    ``ladder=None`` reads :func:`pad_ladder` (the env var); pass an explicit
    sequence to pin the ladder programmatically (tests, the multichip
    dryrun). A batch above the top tier warns once and rounds up to the
    next power of two instead — oversize traffic degrades the graph-count
    budget, never drops data.
    """
    if n < 1:
        raise ValueError(f"batch must have at least one row, got {n}")
    lad = pad_ladder() if ladder is None else tuple(ladder)
    if lad:
        for t in lad:
            if t >= n:
                return t
        _warn_once(
            ("above-ladder", lad[-1]),
            f"batch of {n} rows exceeds the top padding tier {lad[-1]} "
            f"(ladder {lad}); padding to the next power of two instead — "
            "each distinct oversize pow-2 tier compiles one extra graph",
        )
    return next_pow2(n)


def ladder_tiers(max_rows: int, ladder: Optional[Sequence[int]] = None) -> Tuple[int, ...]:
    """Every tier batches of ``1..max_rows`` rows can land on, ascending —
    the warmup-matrix enumeration surface (``serving/warmup.py``): an AOT
    warmup that precompiles one update graph per returned tier covers every
    batch size up to ``max_rows`` with zero first-request traces.

    ``ladder=None`` reads :func:`pad_ladder` (the env var), mirroring
    :func:`tier_for` exactly: explicit-ladder tiers whose predecessor is
    below ``max_rows`` are reachable, and sizes above the top tier spill
    into the pow-2 overflow tiers ``tier_for`` would warn-and-use; pow-2
    mode yields ``1, 2, 4, ..., next_pow2(max_rows)``.
    """
    if max_rows < 1:
        raise ValueError(f"max_rows must be >= 1, got {max_rows}")
    lad = pad_ladder() if ladder is None else tuple(sorted(set(ladder)))
    tiers = []
    if lad:
        prev = 0
        for t in lad:
            if prev < max_rows:
                tiers.append(t)
            prev = t
        start = lad[-1] + 1  # pow-2 overflow spill above the top tier
    else:
        start = 1
    if start <= max_rows:
        t = next_pow2(start)
        while True:
            tiers.append(t)
            if t >= max_rows:
                break
            t = next_pow2(t + 1)
    return tuple(tiers)


def leading_rows(tree: Any) -> Optional[int]:
    """Leading-axis row count of the first >=1-dim array leaf of ``tree``
    (for a padded request: its ladder tier). One implementation shared by
    the AOT warmup matrix (``serving/warmup.py``), the cost profiler
    (``obs/profile.py``), and the per-tier jit-wall tap (``metric.py``).

    Sliced state trees are excluded from the tap: a ``sl__*`` ring leaf
    (``metrics_tpu/sliced``) leads with the ``(K+2,)`` slice axis, not a
    batch tier, and reporting ``K+2`` as the request's row count would
    corrupt the warmup matrix and the per-tier wall buckets. Any leaf
    reached through a mapping key containing :data:`SLICE_STATE_PREFIX`
    is skipped (this also covers composed rings like ``win__sl__*``)."""
    import jax

    leaves_with_path, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in leaves_with_path:
        if any(
            SLICE_STATE_PREFIX in str(getattr(entry, "key", ""))
            for entry in path
        ):
            continue
        shape = getattr(leaf, "shape", None)
        if shape is not None and len(shape) >= 1:
            return int(shape[0])
    return None


def _row_count(value: Any) -> Optional[int]:
    """Concrete leading-axis length of an array-like, else None."""
    shape = getattr(value, "shape", None)
    if shape is None or len(shape) < 1:
        return None
    try:
        return int(shape[0])
    except TypeError:
        return None  # polymorphic/dynamic dim — nothing to pad


def _pad_host(a: Any, n: int, tier: int) -> Any:
    """Zero-pad one array's leading axis to ``tier`` rows ON HOST.

    numpy, deliberately: padding runs outside the jit boundary, and eager
    on-device ops (``jnp.concatenate`` at every distinct ragged shape)
    would compile one tiny XLA program per incoming batch size — the exact
    unbounded-compile failure the ladder exists to prevent, relocated
    instead of removed (measured 100x the whole update's latency under
    mixed ragged traffic). Serving requests are host-born; a device-array
    input pays one host round trip here and skips it thereafter.
    """
    arr = np.asarray(a)
    out = np.zeros((tier,) + arr.shape[1:], arr.dtype)
    out[:n] = arr
    return out


def _canon(v: Any) -> Any:
    """Canonicalize one update argument to a jax array. Every padded call
    must present the SAME argument types to the jit cache: jax keys numpy
    and jax-array arguments differently, so a mix (padded numpy vs
    exact-tier passthrough) would compile each tier twice and silently
    double the ``len(ladder)`` graph budget."""
    import jax.numpy as jnp

    return jnp.asarray(v)


def pad_rows(
    arrays: Sequence[Any],
    valid: Optional[Any] = None,
    ladder: Optional[Sequence[int]] = None,
) -> Tuple[Tuple[Any, ...], Any]:
    """Pad every array's leading axis up to the ladder tier with zero rows.

    Returns ``(padded_arrays, valid_mask)`` where ``valid_mask`` is the
    bool ``(tier,)`` row mask — ``valid`` (or all-True) for the real rows,
    False for the pad rows. All arrays must share the leading length.
    Padding is host-side numpy (see :func:`_pad_host`). The functional
    building block behind :func:`pad_update_args`; use it directly with
    ``functionalize``d metrics::

        (p, t), mask = pad_rows((preds, target))
        state = jitted_update(state, p, t, valid=mask)   # one graph per tier
    """
    ns = {_row_count(a) for a in arrays}
    ns.discard(None)
    if len(ns) != 1:
        raise ValueError(f"pad_rows needs row-aligned arrays, got leading lengths {sorted(ns)}")
    n = ns.pop()
    tier = tier_for(n, ladder)
    mask = np.zeros((tier,), bool)
    mask[:n] = True if valid is None else np.asarray(valid, bool)
    if tier == n:
        return tuple(_canon(a) for a in arrays), _canon(mask)
    return tuple(_canon(_pad_host(a, n, tier)) for a in arrays), _canon(mask)


def supports_row_mask(metric: Any) -> bool:
    """True when ``metric``'s update can provably hide pad rows: it accepts
    a ``valid`` row mask it actually consumes (capacity-mode ring metrics,
    or classes declaring ``_valid_mask_always`` — the stat-scores family),
    or it is a kwargs-forwarding wrapper over such a metric (the streaming
    wrappers). Delegates to the drop guard's capability predicate — one
    definition of "consumes a row mask" for both subsystems."""
    from metrics_tpu.utilities.guard import _consumes_valid_mask

    return _consumes_valid_mask(metric)


def pad_update_args(metric: Any, args: tuple, kwargs: dict) -> Tuple[tuple, dict, int]:
    """Apply the padding ladder to one module-runtime update call.

    Pads every row-aligned array argument up to the tier (host-side — see
    :func:`_pad_host`), folds the pad mask into the ``valid`` kwarg (AND-ed
    with any caller-provided mask), and returns ``(args, kwargs,
    n_padded)``. Raises when the metric cannot consume a row mask — padding
    without provable invisibility would be silent corruption, so an
    unsupported configuration fails loudly at the first update instead.
    """
    from metrics_tpu.utilities.exceptions import MetricsTPUUserError

    n = None
    for v in list(args) + [v for k, v in kwargs.items() if k != "valid"]:
        n = _row_count(v)
        if n is not None:
            break
    if n is None or n < 1:
        return args, kwargs, 0  # scalar/row-less call: nothing to pad
    prior = kwargs.get("valid")
    # NOTE: an exact-tier batch still gets an (all-True) mask — otherwise
    # tier-N traffic would compile a second, maskless variant of the same
    # tier's graph and the "len(ladder) graphs" budget would double
    if not supports_row_mask(metric):
        raise MetricsTPUUserError(
            f"{type(metric).__name__}(pad_batches=True): this metric's update cannot "
            "consume a `valid` row mask, so padded rows could not be provably masked "
            "out of its accumulators. Use a capacity-mode metric, a stat-scores-family "
            "metric, or disable pad_batches."
        )

    # one pad_rows call over the row-aligned subset (scalars and static
    # config pass through untouched) keeps this path and the functional
    # pad_rows path a single implementation
    row_args = [i for i, v in enumerate(args) if _row_count(v) == n]
    row_kwargs = [k for k, v in kwargs.items() if k != "valid" and _row_count(v) == n]
    padded, mask = pad_rows(
        [args[i] for i in row_args] + [kwargs[k] for k in row_kwargs], valid=prior
    )
    new_args = list(args)
    for i, v in zip(row_args, padded):
        new_args[i] = v
    new_kwargs: Dict[str, Any] = dict(kwargs)
    for k, v in zip(row_kwargs, padded[len(row_args):]):
        new_kwargs[k] = v
    new_kwargs["valid"] = mask
    # the pad count comes from the mask pad_rows ACTUALLY built — a separate
    # tier_for(n) here could race a concurrent env-var/reset change in
    # another serve worker and misstate padded_rows vs the applied mask
    return tuple(new_args), new_kwargs, int(mask.shape[0]) - n


def reset_padding_state() -> None:
    """Clear the warn-once memory and the memoized env parse (test
    isolation — same contract as ``dispatch.reset_dispatch_state``)."""
    _warn_once.reset()
    _ladder_env.reset()

"""Shared warn-once + memoized env-parse helpers for the ops-layer knobs.

``ops/dispatch.py`` (``METRICS_TPU_KERNEL_BACKEND``) and ``ops/padding.py``
(``METRICS_TPU_PAD_LADDER``) share one env-var contract: resolution at call
time (trace time under jit), malformed values warn ONCE and fall back —
a bad env var degrades performance or compile reuse, never correctness —
and tests reset the warn-once memory plus the memoized parse between
cases. This module is that contract's single implementation, so a fix to
one knob (e.g. rank-zero gating of the warning) cannot drift from the
other.

Module import performs python work only (no jax calls, no device arrays —
the hang-proof bootstrap contract, ``utilities/backend.py``).
"""
import os
from typing import Any, Callable, Generic, Tuple, TypeVar

from metrics_tpu.utilities.prints import rank_zero_warn

__all__ = ["WarnOnce", "EnvParse", "bool_token"]

T = TypeVar("T")


def bool_token(raw: str) -> "Any":
    """Parse one boolean env token (``1/0/true/false/on/off/yes/no``,
    case-insensitive); ``None`` for anything else — the caller owns its own
    warn-once message and fallback (``METRICS_TPU_TRACE`` defaults off,
    ``METRICS_TPU_WARMUP`` defaults on)."""
    low = raw.lower()
    if low in ("1", "true", "on", "yes"):
        return True
    if low in ("0", "false", "off", "no"):
        return False
    return None


class WarnOnce:
    """Keyed warn-once registry: the first call per key warns, the rest are
    silent until :meth:`reset` (test isolation — the warning must be
    observable per test, not per process)."""

    def __init__(self) -> None:
        self._seen: set = set()

    def __call__(self, key: Tuple[Any, ...], msg: str) -> None:
        if key in self._seen:
            return
        self._seen.add(key)
        rank_zero_warn(msg, UserWarning)

    def reset(self) -> None:
        self._seen.clear()


class EnvParse(Generic[T]):
    """Memoized parse of one env var: ``parse(raw)`` runs only when the raw
    string CHANGES (these knobs sit on eager hot paths — re-tokenizing an
    unchanged var per call buys nothing); unset/empty returns ``empty``
    without parsing. The parse callable owns its own malformed-value
    handling (warn once, return a fallback) — memoization means its
    warning naturally fires once per raw value."""

    def __init__(self, var: str, parse: Callable[[str], T], empty: T) -> None:
        self.var = var
        self._parse = parse
        self._empty = empty
        self._cache: Tuple[str, T] = ("", empty)

    def __call__(self) -> T:
        raw = os.environ.get(self.var, "").strip()
        if not raw:
            return self._empty
        if raw == self._cache[0]:
            return self._cache[1]
        value = self._parse(raw)
        self._cache = (raw, value)
        return value

    def reset(self) -> None:
        self._cache = ("", self._empty)

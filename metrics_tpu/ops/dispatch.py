"""Kernel dispatch: ONE switch between implementations of every hot op.

Prior rounds hardcoded their kernel choices at each call site:
``binned_precision_recall.py`` probed ``jax.default_backend()`` inline to
pick pallas vs XLA (and again to pick interpret mode), every curve /
retrieval path imported the packed-radix order directly, and the quantile
sketch had exactly one precompaction strategy. This registry gives each hot
op a named set of implementations and one resolution rule shared by every
caller::

    choice := programmatic override   (set_kernel_override / kernel_override)
            | per-op env token        (METRICS_TPU_KERNEL_BACKEND="histogram=pallas")
            | global env token        (METRICS_TPU_KERNEL_BACKEND=pallas)
            | "auto"

``auto`` asks the op's chooser (typically: pallas on TPU when the shape is
supported, the XLA path everywhere else; the chooser may inspect the call's
arguments). A forced choice that cannot run — pallas off-TPU without
interpret mode, an unknown implementation name, an impl guard rejecting the
shape — WARNS ONCE per (op, reason) and falls back to the op's default
path: a bad env var degrades performance, never correctness. A *global*
token that simply does not name an implementation of some op (e.g.
``pallas`` applied to an op with no pallas kernel) leaves that op on
``auto`` silently — it is a blanket preference, not a per-op demand.

Resolution happens at call time — under ``jax.jit`` that is trace time, so
the choice is baked into the compiled graph and changing the env var does
NOT invalidate already-cached jits (the same stance as every other
``METRICS_TPU_*`` perf knob; tests and benches build fresh jits per
choice). Module import registers pure python dicts only — no jax calls, no
device arrays (the hang-proof bootstrap contract, ``utilities/backend.py``).

Registered ops (impl modules self-register at import; ``resolve`` lazily
imports them all so partial imports cannot hide an implementation):

==================  ============================  ==========================
op                  implementations               callers through the switch
==================  ============================  ==========================
ascending_order     radix | argsort               AUC reorder, retrieval
                                                  ``_group_layout``, FID
                                                  shuffle, sketch quantile
descending_order    radix | argsort               ``_binary_clf_curve``,
                                                  capacity curve prologue,
                                                  retrieval kernels
partition_order     radix | argsort               ROC/PRC boundary
                                                  compactions
stable_key_order    radix | argsort               retrieval grouping
histogram           xla | pallas |                ``bucket_counts`` (sharded
                    pallas-interpret              ranks pass 1)
compactor_fold      xla | pallas |                sketch level folds
                    pallas-interpret              (``ops/compactor.py``)
sketch_precompact   binned | sort                 ``QuantileSketch.update``
binned_counters     xla | pallas |                binned precision/recall
                    pallas-interpret              metrics
sync_transport      exact | fp16 | int8           ``fused_sync``'s quantized
                                                  wire, overlapped metric
                                                  cycles, ``ServeLoop``
                                                  reduces (own env var:
                                                  ``METRICS_TPU_SYNC_TRANSPORT``)
==================  ============================  ==========================

Ops may carry their OWN env var (``register_op(..., env_var=...)``) —
consulted between the programmatic override and the shared
``METRICS_TPU_KERNEL_BACKEND`` tokens, same warn-once fallback.
"""
import contextlib
import importlib
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

from metrics_tpu.ops._envtools import EnvParse, WarnOnce

__all__ = [
    "KernelOp",
    "register_op",
    "resolve",
    "call",
    "registered_ops",
    "set_kernel_override",
    "clear_kernel_overrides",
    "kernel_override",
    "reset_dispatch_state",
]

_ENV_VAR = "METRICS_TPU_KERNEL_BACKEND"

# modules that self-register implementations at import; ``resolve`` imports
# them lazily so a caller that imported only ``ops.compactor`` still sees
# the pallas kernels when it forces them
_IMPL_MODULES = (
    "metrics_tpu.ops.bucketed_rank",
    "metrics_tpu.ops.compactor",
    "metrics_tpu.ops.binning",
    "metrics_tpu.ops.pallas_kernels",
    "metrics_tpu.ops.binned_counters",
    "metrics_tpu.ops.quantize",
)


class KernelOp:
    """One dispatched op: named impls, optional per-impl guards, an optional
    ``auto`` chooser, and the default (always-runnable) implementation.

    ``env_var`` (optional) gives the op its OWN environment variable —
    consulted after the programmatic override and before the shared
    ``METRICS_TPU_KERNEL_BACKEND`` tokens (the ``sync_transport`` op's
    ``METRICS_TPU_SYNC_TRANSPORT`` is the first user). Values are plain
    impl names; unknown ones warn once and fall back to the default, same
    as any env-forced choice."""

    def __init__(self, name: str, default: str, env_var: Optional[str] = None) -> None:
        self.name = name
        self.default = default
        self.env_var = env_var
        self.impls: Dict[str, Callable] = {}
        self.guards: Dict[str, Callable[..., Optional[str]]] = {}
        self.chooser: Optional[Callable[..., str]] = None

    def impl(self, impl_name: str, guard: Optional[Callable[..., Optional[str]]] = None):
        """Decorator registering an implementation. ``guard(*args, **kw)``
        returns ``None`` when the impl can run, else a human-readable reason
        (triggering the warn-once fallback to the default path)."""

        def deco(fn: Callable) -> Callable:
            self.impls[impl_name] = fn
            if guard is not None:
                self.guards[impl_name] = guard
            return fn

        return deco

    def auto_rule(self, fn: Callable[..., str]) -> Callable[..., str]:
        """Decorator registering the ``auto`` chooser. It must only return
        implementation names that can actually run for the given args (its
        guards are not re-consulted)."""
        self.chooser = fn
        return fn


_OPS: Dict[str, KernelOp] = {}
_OP_ENV: Dict[str, "EnvParse[Optional[str]]"] = {}  # ops with their own env var
_OVERRIDES: Dict[str, str] = {}
_warn_once = WarnOnce()
_IMPLS_ENSURED = False


def register_op(name: str, default: str, env_var: Optional[str] = None) -> KernelOp:
    """Get-or-create an op. The first registration pins the default impl
    name (later calls with a different default are a programming error)."""
    op = _OPS.get(name)
    if op is None:
        op = _OPS[name] = KernelOp(name, default, env_var)
        if env_var is not None:
            # the per-op env var is a single bare impl token (memoized like
            # the shared var; whitespace-trimmed; validation — warn-once +
            # fallback — happens in _resolve_choice like any env choice)
            _OP_ENV[name] = EnvParse(env_var, lambda raw: raw.strip(), None)
    elif op.default != default:
        raise ValueError(
            f"kernel op {name!r} already registered with default {op.default!r}, "
            f"refusing to re-register with default {default!r}"
        )
    return op


def registered_ops() -> Dict[str, KernelOp]:
    _ensure_impls()
    return dict(_OPS)


def _ensure_impls() -> None:
    global _IMPLS_ENSURED
    if _IMPLS_ENSURED:
        return
    _IMPLS_ENSURED = True  # set first: the impl modules themselves resolve
    for mod in _IMPL_MODULES:
        importlib.import_module(mod)


def _parse_env_choices(raw: str) -> Dict[str, str]:
    """Parse ``METRICS_TPU_KERNEL_BACKEND``: comma-separated tokens, bare
    token = global choice (key ``"*"``), ``op=choice`` = per-op. Malformed
    tokens warn once and are ignored (same stance as
    ``METRICS_TPU_EAGER_WARN_ROWS``)."""
    choices: Dict[str, str] = {}
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" in tok:
            op_name, _, val = tok.partition("=")
            op_name, val = op_name.strip(), val.strip()
            if op_name and val:
                # _OPS is fully populated here (resolve() runs
                # _ensure_impls before consulting the env): a typo'd op
                # name would otherwise be stored-but-never-consulted —
                # the same silent-self-comparison trap the programmatic
                # override guards against by raising
                if op_name not in _OPS:
                    _warn_once(
                        ("env-unknown-op", op_name),
                        f"{_ENV_VAR}: {op_name!r} is not a registered kernel "
                        f"op (have {sorted(_OPS)}); token {tok!r} ignored",
                    )
                else:
                    choices[op_name] = val
            else:
                _warn_once(
                    ("env-malformed", tok),
                    f"{_ENV_VAR}: malformed token {tok!r} ignored "
                    "(expected `choice` or `op=choice`)",
                )
        else:
            choices["*"] = tok
    return choices


# memoized on the raw string — dispatch runs on eager hot paths, and
# re-tokenizing an unchanged var per call buys nothing
_env_choices: "EnvParse[Dict[str, str]]" = EnvParse(_ENV_VAR, _parse_env_choices, {})


def _requested(op_name: str) -> Tuple[str, str]:
    """(choice, source) with source in {'override', 'env', 'global-env',
    'auto'} — the source decides how loudly a non-applicable choice fails."""
    if op_name in _OVERRIDES:
        return _OVERRIDES[op_name], "override"
    own = _OP_ENV.get(op_name)
    if own is not None:
        choice = own()
        if choice:
            return choice, "env"
    env = _env_choices()
    if op_name in env:
        return env[op_name], "env"
    if "*" in env:
        return env["*"], "global-env"
    return "auto", "auto"


def resolve(op_name: str, *args: Any, **kwargs: Any) -> Tuple[str, Callable]:
    """Pick the implementation for one call. Returns ``(impl_name, fn)``;
    never raises for a bad *choice* (warn-once + default), only for an
    unknown *op*."""
    op = _get_op(op_name)
    choice, source = _requested(op_name)
    impl_name, fn = _resolve_choice(op, choice, source, args, kwargs)
    # observability seam: which impl each call (= each trace, under jit)
    # baked in — a host-side instant event, never a graph op
    from metrics_tpu.obs import trace as _obs_trace

    _obs_trace.instant("dispatch.resolve", op=op_name, impl=impl_name, source=source)
    return impl_name, fn


def _get_op(op_name: str) -> KernelOp:
    _ensure_impls()
    op = _OPS.get(op_name)
    if op is None:
        raise KeyError(f"unknown kernel op {op_name!r} (have {sorted(_OPS)})")
    return op


def _resolve_choice(
    op: KernelOp, choice: str, source: str, args: Tuple, kwargs: Dict
) -> Tuple[str, Callable]:
    op_name = op.name
    if choice != "auto":
        if choice not in op.impls:
            if source == "global-env":
                choice = "auto"  # blanket preference; this op has no such impl
            else:
                _warn_once(
                    (op_name, choice, "unknown-impl"),
                    f"kernel backend {choice!r} ({source}) is not an implementation "
                    f"of op {op_name!r} (have {sorted(op.impls)}); using the "
                    f"default {op.default!r} path",
                )
                choice = op.default
        if choice != "auto":
            guard = op.guards.get(choice)
            reason = guard(*args, **kwargs) if guard is not None else None
            if reason is not None:
                _warn_once(
                    (op_name, choice, reason),
                    f"kernel backend {choice!r} for op {op_name!r} is unavailable "
                    f"({reason}); falling back to the {op.default!r} path",
                )
                choice = op.default
    if choice == "auto":
        choice = op.chooser(*args, **kwargs) if op.chooser is not None else op.default
    return choice, op.impls[choice]


def call(op_name: str, *args: Any, **kwargs: Any) -> Any:
    """Resolve and run: the one entry point every caller goes through."""
    _, fn = resolve(op_name, *args, **kwargs)
    return fn(*args, **kwargs)


def call_as(op_name: str, choice: str, *args: Any, **kwargs: Any) -> Any:
    """Run a specific implementation for ONE call — same guard / warn-once
    fallback semantics as an env-forced choice, but without touching the
    process-global override table, so per-call forces (e.g. a metric's
    ``use_pallas=`` ctor knob) stay reentrant and thread-safe."""
    name, fn = _resolve_choice(_get_op(op_name), choice, "call", args, kwargs)
    return fn(*args, **kwargs)


def _check_override_op(op_name: str) -> None:
    """Overrides are test/bench hooks: a typo'd OP name would otherwise be
    stored-but-never-consulted, making an A/B silently compare an impl
    against itself — so unknown ops raise here (typo'd IMPL names are the
    env var's territory and warn-once instead)."""
    _ensure_impls()
    if op_name not in _OPS:
        raise KeyError(f"unknown kernel op {op_name!r} (have {sorted(_OPS)})")


def set_kernel_override(op_name: str, choice: str) -> None:
    """Programmatic per-op choice — wins over the env var. Applies to jits
    traced AFTER the call (resolution is trace-time). Raises on unknown op
    names (see ``_check_override_op``)."""
    _check_override_op(op_name)
    _OVERRIDES[op_name] = choice


def clear_kernel_overrides() -> None:
    _OVERRIDES.clear()


@contextlib.contextmanager
def kernel_override(**choices: str) -> Iterator[None]:
    """``with kernel_override(sketch_precompact="sort"): ...`` — scoped
    programmatic choices (the bench A/B and parity-test hook). Raises on
    unknown op names (see ``_check_override_op``)."""
    for op_name in choices:
        _check_override_op(op_name)
    saved = dict(_OVERRIDES)
    _OVERRIDES.update(choices)
    try:
        yield
    finally:
        _OVERRIDES.clear()
        _OVERRIDES.update(saved)


def reset_dispatch_state() -> None:
    """Clear overrides, the warn-once memory, AND the memoized env parse
    (test isolation — the fallback warning must be observable per test,
    not per process, and a cached parse would skip its warn-once)."""
    _OVERRIDES.clear()
    _warn_once.reset()
    _env_choices.reset()
    for env in _OP_ENV.values():
        env.reset()

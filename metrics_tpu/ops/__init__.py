"""Hand-written TPU kernels (pallas) for hot metric ops.

XLA handles most fusion; these kernels cover the few update paths where the
default lowering materializes a large intermediate (see each module's
docstring). Every kernel has an identical-semantics XLA fallback and runs in
pallas interpret mode off-TPU, so parity tests execute everywhere.
"""
from metrics_tpu.ops.binned_counters import binned_counter_update  # noqa: F401
from metrics_tpu.ops.bucketed_rank import (  # noqa: F401
    ascending_order,
    ascending_ranks,
    bucket_counts,
    descending_order,
    inverse_permutation,
    partition_order,
    sharded_descending_ranks,
    stable_key_order,
)

"""Hand-written kernels (pallas + packed-radix XLA) for hot metric ops.

XLA handles most fusion; these kernels cover the few update paths where the
default lowering materializes a large intermediate or serializes (see each
module's docstring). Since ISSUE 6 every kernel choice goes through ONE
dispatch layer (``ops/dispatch.py``): per-op ``xla | pallas | auto``
selection via ``METRICS_TPU_KERNEL_BACKEND`` with a warn-once fallback to
the XLA path when pallas is unavailable or the shape is unsupported — so
callers (`_binary_clf_curve`, capacity-mode compactions, retrieval
``_group_layout``, ``streaming/sketches.py``, the binned PR metrics) import
this surface instead of hardcoding a kernel. Every pallas kernel has an
identical-semantics XLA fallback and runs in pallas interpret mode off-TPU,
so parity tests execute everywhere (``tests/ops/``).
"""
from metrics_tpu.ops import dispatch  # noqa: F401
from metrics_tpu.ops.binned_counters import binned_counter_update  # noqa: F401
from metrics_tpu.ops.bucketed_rank import (  # noqa: F401
    ascending_order,
    ascending_ranks,
    bucket_counts,
    descending_order,
    inverse_permutation,
    partition_order,
    sharded_descending_ranks,
    stable_key_order,
)
from metrics_tpu.ops.binning import halving_level, halving_map, key_to_float32  # noqa: F401
from metrics_tpu.ops.compactor import (  # noqa: F401
    fold_cascade,
    fold_level,
    precompact_batch,
    weighted_cdf,
    weighted_quantiles,
    weighted_rank,
)
from metrics_tpu.ops.dispatch import (  # noqa: F401
    kernel_override,
    registered_ops,
    set_kernel_override,
)
from metrics_tpu.ops.pallas_kernels import (  # noqa: F401
    compactor_fold_pallas,
    histogram_pallas,
)

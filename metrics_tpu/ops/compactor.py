"""Fixed-shape compactor kernels for the streaming quantile sketch.

A KLL/MRL-style compactor keeps ``L`` levels of at most ``k`` sorted items
each; an item at level ``l`` stands for ``2**l`` input rows. Textbook
implementations compact *data-dependently* (only the level that overflows),
which cannot live inside a fixed-shape XLA program. These kernels are the
static-shape reformulation (the same stance as ``CatBuffer`` vs growing
lists, SURVEY.md §7 hard part #1):

- every level buffer is a fixed ``(k,)`` array, ascending-sorted with
  ``+inf`` padding past the valid ``count`` prefix (the invariant every
  kernel below preserves, so a plain value-only ``jnp.sort`` of a
  concatenation re-establishes it for free);
- a level fold is *unconditional* over all ``L`` levels — a level that did
  not overflow passes through bitwise unchanged (sorting a sorted buffer is
  the identity), so the cascade is a static Python loop of ``L`` cheap
  ``(k + M,)`` value-only sorts, never a traced while-loop;
- compaction keeps one element of each adjacent pair of the sorted buffer,
  alternating the kept side per pair index (``2*j + (j & 1)``) — a pure
  function of the sorted data, so merging two sketches is **bitwise
  commutative**, and the alternation cancels the one-sided rank bias a
  fixed offset would accumulate.

Rank-error accounting (the ``eps`` contract of
``metrics_tpu/streaming/sketches.py``): one compaction at level ``l``
perturbs any rank by at most ``2**l``; at most ``~2n / (k * 2**l)``
compactions happen at level ``l`` over ``n`` rows, so the total error is
bounded by ``~2 * L * n / k`` (batch pre-compaction adds one more
``~2n / k`` term). ``QuantileSketchState.create`` sizes ``k`` from the
requested ``eps`` with this bound.

The final quantile query reuses :func:`metrics_tpu.ops.bucketed_rank.
ascending_order` — the one place the sketch needs a *permutation* (to carry
per-item weights through the value sort) rather than sorted values.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.ops.bucketed_rank import ascending_order

Array = jax.Array

# plain python float, NOT a jnp scalar: module import must never create a
# device array (the hang-proof bootstrap contract — utilities/backend.py)
_INF = float("inf")


def _masked_ascending(x: Array, count: Array) -> Array:
    """Re-establish the level invariant: positions ``>= count`` forced to
    ``+inf`` (dropped rows must not linger as maskable-but-present ghosts —
    a later sort would pull them back into the counted prefix)."""
    return jnp.where(jnp.arange(x.shape[0]) < count, x, _INF)


def fold_level(
    items: Array, count: Array, inc: Array, inc_count: Array
) -> Tuple[Array, Array, Array, Array]:
    """Fold ``inc`` (same level weight) into one level buffer.

    ``items`` is ``(k,)`` sorted/+inf-padded with ``count`` valid; ``inc``
    is ``(M,)`` in the same form (any static ``M``). Returns
    ``(new_items (k,), new_count, promoted ((k + M) // 2,),
    promoted_count)`` — when the combined count stays within ``k`` the
    level absorbs everything and ``promoted`` is empty; on overflow the
    whole buffer compacts (pairs of adjacent sorted items collapse to one
    item of doubled weight, alternating kept side per pair) and at most one
    unpaired leftover stays at the level. All shapes static; fully
    jittable.
    """
    k = items.shape[0]
    combined = jnp.sort(jnp.concatenate([items, inc]))  # (k + M,), +inf last
    c = count + inc_count
    overflow = c > k

    # --- no-overflow branch: absorb, nothing promoted ------------------
    keep_items = combined[:k]
    # (invariant holds: exactly c valid reals occupy the prefix)

    # --- overflow branch: compact the whole buffer ---------------------
    pairs = c // 2
    p_len = (k + inc.shape[0]) // 2
    j = jnp.arange(p_len)
    picked = combined[2 * j + (j & 1)]  # one per adjacent pair, alternating
    promoted = jnp.where(j < pairs, picked, _INF)
    leftover_count = c - 2 * pairs  # 0 or 1
    leftover = jnp.where(jnp.arange(k) < leftover_count, combined[2 * pairs], _INF)

    new_items = jnp.where(overflow, leftover, keep_items)
    new_count = jnp.where(overflow, leftover_count, c)
    promoted = jnp.where(overflow, promoted, _INF)
    promoted_count = jnp.where(overflow, pairs, 0)
    return new_items, new_count, promoted, promoted_count


def precompact_batch(x: Array, valid: Array, k: int) -> Tuple[Array, Array, int]:
    """Reduce a batch to at most ``k`` items of weight ``2**level``.

    Sorts the batch once (invalid rows to ``+inf``), then applies static
    halving rounds (the batch-local form of level compaction — same
    alternating pair rule) until it fits a level buffer. Returns
    ``(items (k,), count, level)`` with ``level`` a *static* int (it only
    depends on the static batch size), so the caller's cascade can skip
    the untouched lower levels at trace time. Odd-count rounds drop the
    one unpaired (largest) item — bounded by the documented error term.
    """
    x = jnp.asarray(x, jnp.float32).reshape(-1)
    valid = jnp.broadcast_to(jnp.asarray(valid, bool).reshape(-1), x.shape)
    valid = valid & jnp.isfinite(x)
    cur = jnp.sort(jnp.where(valid, x, _INF))
    m = jnp.sum(valid.astype(jnp.int32))
    level = 0
    while cur.shape[0] > k:
        half = cur.shape[0] // 2
        j = jnp.arange(half)
        cur = cur[2 * j + (j & 1)]
        m = m // 2
        cur = _masked_ascending(cur, m)
        level += 1
    if cur.shape[0] < k:
        cur = jnp.concatenate([cur, jnp.full((k - cur.shape[0],), _INF)])
    return cur, m, level


def fold_cascade(
    items: Array, counts: Array, inc: Array, inc_count: Array, start_level: int
) -> Tuple[Array, Array]:
    """Run ``inc`` (weight ``2**start_level``) up the level cascade.

    ``items``/``counts`` are the full ``(L, k)``/``(L,)`` sketch buffers.
    The loop over levels is static: levels below ``start_level`` are
    untouched, levels above fold unconditionally (a non-overflowing fold
    is the bitwise identity). A promotion that would leave the top level
    is folded back into it — losing half that weight's resolution, which
    ``QuantileSketchState.create`` makes unreachable by sizing ``L`` for
    ``max_items``.
    """
    L, k = items.shape
    rows = []
    cnts = []
    for lvl in range(L):
        if lvl < start_level:
            rows.append(items[lvl])
            cnts.append(counts[lvl])
            continue
        if lvl == L - 1:
            # top level never promotes: absorb (and saturate — see docstring)
            combined = jnp.sort(jnp.concatenate([items[lvl], inc]))
            c = jnp.minimum(counts[lvl] + inc_count, k)
            rows.append(_masked_ascending(combined[:k], c))
            cnts.append(c)
            inc = jnp.full_like(inc, _INF)
            inc_count = jnp.zeros((), jnp.int32)
            continue
        new_items, new_count, inc, inc_count = fold_level(
            items[lvl], counts[lvl], inc, inc_count
        )
        rows.append(new_items)
        cnts.append(new_count)
    return jnp.stack(rows), jnp.stack(cnts).astype(jnp.int32)


def level_weights(items: Array, counts: Array) -> Array:
    """Per-slot row weights ``2**level`` (float32; zero past each level's
    valid prefix)."""
    L, k = items.shape
    slot_valid = jnp.arange(k)[None, :] < counts[:, None]
    w = jnp.exp2(jnp.arange(L, dtype=jnp.float32))[:, None]
    return jnp.where(slot_valid, w, 0.0)


def weighted_quantiles(items: Array, counts: Array, qs: Array) -> Array:
    """Quantile values from the level buffers: one packed-radix value sort
    over all ``L * k`` slots with weights carried through the permutation
    (``ascending_order``), then a cumulative-weight lookup. ``+inf``
    padding sorts last with zero weight, so no compaction is needed."""
    vals = items.ravel()
    w = level_weights(items, counts).ravel()
    order = ascending_order(vals)
    sv = vals[order]
    cw = jnp.cumsum(w[order])
    total = cw[-1]
    targets = jnp.maximum(jnp.asarray(qs, jnp.float32) * total, 1.0)
    idx = jnp.clip(jnp.searchsorted(cw, targets, side="left"), 0, sv.shape[0] - 1)
    return jnp.where(total > 0, sv[idx], jnp.nan)


def weighted_rank(items: Array, counts: Array, v: Array) -> Array:
    """Estimated number of inserted rows ``<= v`` (float32)."""
    w = level_weights(items, counts)
    return jnp.sum(jnp.where(items <= jnp.asarray(v, jnp.float32), w, 0.0))

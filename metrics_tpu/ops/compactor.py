"""Fixed-shape compactor kernels for the streaming quantile sketch.

A KLL/MRL-style compactor keeps ``L`` levels of at most ``k`` sorted items
each; an item at level ``l`` stands for ``2**l`` input rows. Textbook
implementations compact *data-dependently* (only the level that overflows),
which cannot live inside a fixed-shape XLA program. These kernels are the
static-shape reformulation (the same stance as ``CatBuffer`` vs growing
lists, SURVEY.md §7 hard part #1):

- every level buffer is a fixed ``(k,)`` array, ascending-sorted with
  ``+inf`` padding past the valid ``count`` prefix (the invariant every
  kernel below preserves, so a plain value-only ``jnp.sort`` of a
  concatenation re-establishes it for free);
- a level fold is shape-unconditional over all ``L`` levels — a level that
  did not overflow passes through bitwise unchanged — but since ISSUE 6 the
  cascade SHORT-CIRCUITS at runtime: each level's fold sits behind a
  ``lax.cond`` on "anything to fold here?", so levels the promotion never
  reaches cost a scalar compare instead of a ``(k + M,)`` sort. A 512-row
  update dropped from ~39 ms (20 unconditional folds) to the cost of the
  one fold that can actually spill (bench notes in BASELINE.md);
- compaction keeps one element of each adjacent pair of the sorted buffer,
  alternating the kept side per pair index (``2*j + (j & 1)``) — a pure
  function of the sorted data, so merging two sketches is **bitwise
  commutative**, and the alternation cancels the one-sided rank bias a
  fixed offset would accumulate. The post-sort compact/select stage is the
  dispatched ``compactor_fold`` op (``ops/dispatch.py``): the XLA impl
  below everywhere, the fused pallas kernel
  (``ops/pallas_kernels.py``) on TPU / under interpret-mode parity tests.

Rank-error accounting (the ``eps`` contract of
``metrics_tpu/streaming/sketches.py``): one compaction at level ``l``
perturbs any rank by at most ``2**l``; at most ``~2n / (k * 2**l)``
compactions happen at level ``l`` over ``n`` rows, so the total error is
bounded by ``~2 * L * n / k`` (batch pre-compaction adds one more
``~2n / k`` term). ``QuantileSketchState.create`` sizes ``k`` from the
requested ``eps`` with this bound.

Batch pre-compaction is the dispatched ``sketch_precompact`` op: the
default ``binned`` impl (``ops/binning.py``) bins the batch through
``bucketed_rank``'s orderable-key grid — a value-only unsigned sort, ~6x
cheaper than this module's legacy full float sort, which stays registered
as the ``sort`` impl for A/B benching (`bench.py` ``compactor`` phase).

The final quantile query reuses :func:`metrics_tpu.ops.bucketed_rank.
ascending_order` — the one place the sketch needs a *permutation* (to carry
per-item weights through the value sort) rather than sorted values.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.ops import dispatch as _dispatch
from metrics_tpu.ops.bucketed_rank import ascending_order

Array = jax.Array

# plain python float, NOT a jnp scalar: module import must never create a
# device array (the hang-proof bootstrap contract — utilities/backend.py)
_INF = float("inf")


def _masked_ascending(x: Array, count: Array) -> Array:
    """Re-establish the level invariant: positions ``>= count`` forced to
    ``+inf`` (dropped rows must not linger as maskable-but-present ghosts —
    a later sort would pull them back into the counted prefix)."""
    return jnp.where(jnp.arange(x.shape[0]) < count, x, _INF)


_FOLD = _dispatch.register_op("compactor_fold", default="xla")


@_FOLD.impl("xla")
def _compactor_fold_xla(
    combined: Array, c: Array, k: int
) -> Tuple[Array, Array, Array, Array]:
    """Post-sort compact/select stage: ``combined`` is the sorted
    ``(k + M,)`` concatenation with ``c`` valid reals in its prefix."""
    overflow = c > k

    # --- no-overflow branch: absorb, nothing promoted ------------------
    keep_items = combined[:k]
    # (invariant holds: exactly c valid reals occupy the prefix)

    # --- overflow branch: compact the whole buffer ---------------------
    pairs = c // 2
    p_len = combined.shape[0] // 2
    j = jnp.arange(p_len)
    picked = combined[2 * j + (j & 1)]  # one per adjacent pair, alternating
    promoted = jnp.where(j < pairs, picked, _INF)
    leftover_count = c - 2 * pairs  # 0 or 1
    leftover = jnp.where(jnp.arange(k) < leftover_count, combined[2 * pairs], _INF)

    new_items = jnp.where(overflow, leftover, keep_items)
    new_count = jnp.where(overflow, leftover_count, c)
    promoted = jnp.where(overflow, promoted, _INF)
    promoted_count = jnp.where(overflow, pairs, 0)
    return new_items, new_count, promoted, promoted_count


def fold_level(
    items: Array, count: Array, inc: Array, inc_count: Array
) -> Tuple[Array, Array, Array, Array]:
    """Fold ``inc`` (same level weight) into one level buffer.

    ``items`` is ``(k,)`` sorted/+inf-padded with ``count`` valid; ``inc``
    is ``(M,)`` in the same form (any static ``M``). Returns
    ``(new_items (k,), new_count, promoted ((k + M) // 2,),
    promoted_count)`` — when the combined count stays within ``k`` the
    level absorbs everything and ``promoted`` is empty; on overflow the
    whole buffer compacts (pairs of adjacent sorted items collapse to one
    item of doubled weight, alternating kept side per pair) and at most one
    unpaired leftover stays at the level. All shapes static; fully
    jittable. The sort runs here; the compact/select stage dispatches
    (``compactor_fold``: XLA everywhere, the fused pallas kernel on TPU).
    """
    k = items.shape[0]
    combined = jnp.sort(jnp.concatenate([items, inc]))  # (k + M,), +inf last
    return _dispatch.call("compactor_fold", combined, count + inc_count, k)


_PRECOMPACT = _dispatch.register_op("sketch_precompact", default="binned")


@_PRECOMPACT.impl("sort")
def _precompact_sort(x: Array, valid: Array, k: int) -> Tuple[Array, Array, int]:
    """Legacy full-sort pre-compaction (the A/B baseline): one float sort
    of the whole batch, then round-by-round halving gathers."""
    x = jnp.asarray(x, jnp.float32).reshape(-1)
    valid = jnp.broadcast_to(jnp.asarray(valid, bool).reshape(-1), x.shape)
    valid = valid & jnp.isfinite(x)
    cur = jnp.sort(jnp.where(valid, x, _INF))
    m = jnp.sum(valid.astype(jnp.int32))
    level = 0
    while cur.shape[0] > k:
        half = cur.shape[0] // 2
        j = jnp.arange(half)
        cur = cur[2 * j + (j & 1)]
        m = m // 2
        cur = _masked_ascending(cur, m)
        level += 1
    return cur, m, level


def precompact_batch(x: Array, valid: Array, k: int) -> Tuple[Array, Array, int]:
    """Reduce a batch to at most ``k`` items of weight ``2**level``.

    Applies static halving rounds (the batch-local form of level compaction
    — same alternating pair rule) to the value-ordered batch until it fits
    a level buffer. Returns ``(items (min(n', k),), count, level)`` with
    ``level`` a *static* int (it only depends on the static batch size), so
    the caller's cascade can skip the untouched lower levels at trace time.
    Batches smaller than ``k`` come back at their own (static) length — no
    ``+inf`` padding to ``k``, so every downstream fold sorts ``k + n``
    instead of ``2k`` elements (the ISSUE 6 small-batch fix). Odd-count
    rounds drop the one unpaired (largest) item — bounded by the documented
    error term.

    Dispatched (``sketch_precompact``): the default ``binned`` impl bins by
    ``bucketed_rank``'s orderable uint32 key (``ops/binning.py``, ~6x
    cheaper); ``sort`` is the legacy full float sort.
    """
    return _dispatch.call("sketch_precompact", x, valid, k)


def fold_cascade(
    items: Array, counts: Array, inc: Array, inc_count: Array, start_level: int
) -> Tuple[Array, Array]:
    """Run ``inc`` (weight ``2**start_level``) up the level cascade.

    ``items``/``counts`` are the full ``(L, k)``/``(L,)`` sketch buffers.
    The loop over levels is static — levels below ``start_level`` are
    untouched at trace time — and every fold above sits behind a
    ``lax.cond`` on ``inc_count > 0``: a fold whose incoming buffer is
    empty is the bitwise identity, so the cond skips its ``(k + M,)`` sort
    at RUNTIME and only the levels the promotion actually reaches pay
    anything (the ISSUE 6 short-circuit; bitwise-identical outputs either
    way). A promotion that would leave the top level is folded back into
    it — losing half that weight's resolution, which
    ``QuantileSketchState.create`` makes unreachable by sizing ``L`` for
    ``max_items``.
    """
    L, k = items.shape
    rows = []
    cnts = []
    for lvl in range(L):
        if lvl < start_level:
            rows.append(items[lvl])
            cnts.append(counts[lvl])
            continue
        if lvl == L - 1:
            # top level never promotes: absorb (and saturate — see docstring)
            def _absorb(level_items, level_count, inc_, inc_count_):
                combined = jnp.sort(jnp.concatenate([level_items, inc_]))
                c = jnp.minimum(level_count + inc_count_, k)
                return _masked_ascending(combined[:k], c), c

            def _skip_top(level_items, level_count, inc_, inc_count_):
                return level_items, jnp.minimum(level_count, k)

            row, c = jax.lax.cond(
                inc_count > 0, _absorb, _skip_top, items[lvl], counts[lvl], inc, inc_count
            )
            rows.append(row)
            cnts.append(c)
            inc = jnp.full_like(inc, _INF)
            inc_count = jnp.zeros((), jnp.int32)
            continue

        p_len = (k + inc.shape[0]) // 2

        def _fold(level_items, level_count, inc_, inc_count_):
            return fold_level(level_items, level_count, inc_, inc_count_)

        def _skip(level_items, level_count, inc_, inc_count_):
            return (
                level_items,
                level_count,
                jnp.full((p_len,), _INF, jnp.float32),
                jnp.zeros((), jnp.int32),
            )

        new_items, new_count, inc, inc_count = jax.lax.cond(
            inc_count > 0, _fold, _skip, items[lvl], counts[lvl], inc, inc_count
        )
        rows.append(new_items)
        cnts.append(new_count)
    return jnp.stack(rows), jnp.stack(cnts).astype(jnp.int32)


def level_weights(items: Array, counts: Array) -> Array:
    """Per-slot row weights ``2**level`` (float32; zero past each level's
    valid prefix)."""
    L, k = items.shape
    slot_valid = jnp.arange(k)[None, :] < counts[:, None]
    w = jnp.exp2(jnp.arange(L, dtype=jnp.float32))[:, None]
    return jnp.where(slot_valid, w, 0.0)


def weighted_quantiles(items: Array, counts: Array, qs: Array) -> Array:
    """Quantile values from the level buffers: one packed-radix value sort
    over all ``L * k`` slots with weights carried through the permutation
    (``ascending_order``), then a cumulative-weight lookup. ``+inf``
    padding sorts last with zero weight, so no compaction is needed."""
    vals = items.ravel()
    w = level_weights(items, counts).ravel()
    order = ascending_order(vals)
    sv = vals[order]
    cw = jnp.cumsum(w[order])
    total = cw[-1]
    targets = jnp.maximum(jnp.asarray(qs, jnp.float32) * total, 1.0)
    idx = jnp.clip(jnp.searchsorted(cw, targets, side="left"), 0, sv.shape[0] - 1)
    return jnp.where(total > 0, sv[idx], jnp.nan)


def weighted_rank(items: Array, counts: Array, v: Array) -> Array:
    """Estimated number of inserted rows ``<= v`` (float32)."""
    w = level_weights(items, counts)
    return jnp.sum(jnp.where(items <= jnp.asarray(v, jnp.float32), w, 0.0))


def weighted_cdf(items: Array, counts: Array, points: Array) -> Array:
    """Estimated CDF at many probe points in ONE pass: ``(P,)`` fractions of
    inserted rows ``<= points[i]`` (the vectorized form of
    :func:`weighted_rank` — one ``(P, L, k)`` broadcast compare instead of
    ``P`` scans). Each value is off by at most the sketch's rank-error
    fraction ``eps``; an empty sketch answers NaN everywhere."""
    w = level_weights(items, counts)
    pts = jnp.atleast_1d(jnp.asarray(points, jnp.float32))
    ranks = jnp.sum(
        jnp.where(items[None, :, :] <= pts[:, None, None], w[None, :, :], 0.0),
        axis=(1, 2),
    )
    total = jnp.sum(w)
    return jnp.where(total > 0, ranks / jnp.maximum(total, 1.0), jnp.nan)

"""Bucketed-rank kernels: exact sort orders without comparison argsort.

Every exact threshold-curve compute (AUROC, AveragePrecision, ROC,
PrecisionRecallCurve in ``capacity=`` mode) and the retrieval grouping
funnel through a global ``jnp.argsort`` — the measured #1 scaling wall
(264 ms/1M on TPU, BASELINE.md). The expensive part is NOT comparison
sorting per se but XLA's *variadic* sort carrying an index payload through
every comparison: on the CPU backend a value-only ``jnp.sort`` of uint32
keys is ~10x cheaper than ``jnp.argsort`` of the same data, and gathers
are nearly free. These kernels exploit that asymmetry.

Two cooperating forms:

1. **Packed-radix orders** (single program): the sort key is decomposed
   into static bit-slices ("buckets" on a 2^b-point quantization grid of
   the orderable key bits). Each LSD pass packs ``(key_slice << idx_bits)
   | running_rank`` into ONE uint32 word and value-sorts it — cumulative
   bucket offsets and within-bucket positions come out of the same sort,
   so per-element ranks stay exact at full key resolution, with ties
   broken by position exactly like a stable argsort. Permutations are
   **bit-identical** to ``jnp.argsort`` (see comparator notes below).

2. **Histogram ranks** (``shard_map``): pass 1 computes per-bucket counts
   over a static score-quantization grid and reduces them with ONE small
   ``psum``/``all_gather`` of ``(num_buckets + 3,)`` histograms — the fused
   computation-collective pattern — instead of all-gathering the raw
   scores for a replicated sort. Pass 2 converts cumulative bucket
   offsets + within-bucket positions (device-prefix from the gathered
   histograms + a local packed-radix order) into global ranks. Ranks are
   exact whenever no quantization bucket holds two distinct scores from
   different devices (always true for binned/quantized scores); the
   returned ``resolved`` flag reports bucket collisions so callers can
   fall back to the gathered-sort path when bit-exactness matters for
   continuous scores.

Comparator parity: XLA's float sort comparator (measured on the CPU
backend) treats -0.0 == +0.0 and flushes float32 denormals to zero, and
jax sorts NaNs last. The orderable-key construction below reproduces all
three, so ``ascending_order(x) == jnp.argsort(x, stable=True)`` and
``descending_order(x) == jnp.argsort(-x)`` hold bitwise — including
tie-heavy and adversarial inputs (verified in
``tests/ops/test_bucketed_rank.py``).
"""
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.ops import dispatch as _dispatch

Array = jax.Array

_U32_MAX = 0xFFFFFFFF


def _index_bits(n: int) -> int:
    """Bits needed to carry a position in ``[0, n)`` through a packed word."""
    return max(1, (n - 1).bit_length()) if n > 1 else 1


def _float32_ascending_word(s: Array) -> Array:
    """Monotone uint32 key: unsigned ascending order == XLA float32 sort order.

    Standard sign-fold (non-negative floats keep bit order; negative floats
    reverse it), with two comparator-parity fixes measured on the CPU
    backend: denormals (including -0.0/+0.0) collapse to the +0.0 key
    because XLA comparisons flush them to zero, and NaNs of either sign map
    to the maximum key (jax sorts NaNs last).
    """
    i = jax.lax.bitcast_convert_type(s, jnp.int32)
    u = jax.lax.bitcast_convert_type(s, jnp.uint32)
    # exponent == 0 -> zero or denormal -> comparator sees exactly 0.0
    u = jnp.where((u & jnp.uint32(0x7F800000)) == 0, jnp.uint32(0), u)
    i = jnp.where((u & jnp.uint32(0x7F800000)) == 0, jnp.int32(0), i)
    asc = jnp.where(i >= 0, u | jnp.uint32(0x80000000), ~u)
    return jnp.where(jnp.isnan(s), jnp.uint32(_U32_MAX), asc)


def _key_words_ascending(x: Array) -> Tuple[List[Array], int]:
    """Decompose ``x`` into uint32 key words (most-significant first) whose
    lexicographic unsigned ascending order equals ``jnp.argsort(x)`` order.

    Returns ``(words, total_bits)``; ``total_bits`` may be below 32 for
    small integer/bool keys so the radix can skip whole passes.
    """
    dt = x.dtype
    if dt == jnp.bool_:
        return [x.astype(jnp.uint32)], 1
    if jnp.issubdtype(dt, jnp.floating):
        if dt in (jnp.float16, jnp.bfloat16):
            # widening is monotone and preserves ties exactly (distinct
            # halfs stay distinct floats), so order carries over bitwise
            x = x.astype(jnp.float32)
        if x.dtype == jnp.float32:
            return [_float32_ascending_word(x)], 32
        # float64 exists only under x64; uint64 ops are available there
        i = jax.lax.bitcast_convert_type(x, jnp.int64)
        u = jax.lax.bitcast_convert_type(x, jnp.uint64)
        exp_mask = jnp.uint64(0x7FF0000000000000)
        u = jnp.where((u & exp_mask) == 0, jnp.uint64(0), u)
        i = jnp.where((u & exp_mask) == 0, jnp.int64(0), i)
        asc = jnp.where(i >= 0, u | jnp.uint64(1 << 63), ~u)
        asc = jnp.where(jnp.isnan(x), jnp.uint64(0xFFFFFFFFFFFFFFFF), asc)
        return [(asc >> jnp.uint64(32)).astype(jnp.uint32), (asc & jnp.uint64(_U32_MAX)).astype(jnp.uint32)], 64
    if jnp.issubdtype(dt, jnp.signedinteger):
        if jnp.dtype(dt).itemsize <= 4:
            asc = jax.lax.bitcast_convert_type(x.astype(jnp.int32), jnp.uint32) ^ jnp.uint32(0x80000000)
            return [asc], 32
        asc = jax.lax.bitcast_convert_type(x, jnp.uint64) ^ jnp.uint64(1 << 63)
        return [(asc >> jnp.uint64(32)).astype(jnp.uint32), (asc & jnp.uint64(_U32_MAX)).astype(jnp.uint32)], 64
    if jnp.issubdtype(dt, jnp.unsignedinteger):
        if jnp.dtype(dt).itemsize <= 4:
            return [x.astype(jnp.uint32)], 32
        return [(x >> jnp.uint64(32)).astype(jnp.uint32), (x & jnp.uint64(_U32_MAX)).astype(jnp.uint32)], 64
    raise TypeError(f"bucketed_rank has no orderable key for dtype {dt}")


def _radix_order_words(words: List[Array], total_bits: int) -> Array:
    """Stable ascending order of lexicographic uint32 key words via LSD
    packed-radix passes.

    Each pass value-sorts ``(key_slice << idx_bits) | rank`` — the slice is
    the pass's bucket id on a ``2^slice_bits`` grid, the low bits are the
    element's rank after the previous pass, so the single sort realizes
    both the cumulative bucket offsets and the stable within-bucket
    positions of a counting sort. Composing passes LSD-first yields the
    exact full-resolution stable order.
    """
    n = words[0].shape[0]
    if n <= 1:
        return jnp.arange(n, dtype=jnp.int32)
    idx_bits = _index_bits(n)
    slice_bits = 32 - idx_bits
    if slice_bits <= 0:
        raise ValueError(f"packed radix supports up to 2^31 rows, got {n}")
    idx_mask = jnp.uint32((1 << idx_bits) - 1)
    slice_mask = jnp.uint32((1 << slice_bits) - 1) if slice_bits < 32 else jnp.uint32(_U32_MAX)
    ranks = jnp.arange(n, dtype=jnp.uint32)
    perm = ranks
    first = True
    bits_left = total_bits
    for word in reversed(words):  # least-significant word first (LSD)
        word_bits = min(32, bits_left)
        bits_left -= word_bits
        shift = 0
        while shift < word_bits:
            bits = (word >> jnp.uint32(shift)) & slice_mask if shift else word & slice_mask
            # gather the slice into current order (first pass is identity)
            cur = bits if first else bits[perm]
            packed = (cur << jnp.uint32(idx_bits)) | ranks
            pos = (jnp.sort(packed) & idx_mask).astype(jnp.int32)
            perm = pos if first else perm[pos]
            first = False
            shift += slice_bits
    return perm.astype(jnp.int32)


# --------------------------------------------------------------------------
# Dispatched order ops (ops/dispatch.py): the packed-radix kernels are the
# default `radix` impls; the plain `jnp.argsort` forms stay registered as
# the `argsort` escape hatch / A-B reference, so every caller
# (_binary_clf_curve, capacity-mode compactions, retrieval _group_layout,
# the sketch quantile query) selects through ONE switch instead of
# hardcoding a kernel.
# --------------------------------------------------------------------------

_ASC = _dispatch.register_op("ascending_order", default="radix")
_DESC = _dispatch.register_op("descending_order", default="radix")
_PART = _dispatch.register_op("partition_order", default="radix")
_KEYORD = _dispatch.register_op("stable_key_order", default="radix")


@_ASC.impl("radix")
def _ascending_order_radix(x: Array) -> Array:
    words, bits = _key_words_ascending(jnp.asarray(x))
    return _radix_order_words(words, bits)


@_ASC.impl("argsort")
def _ascending_order_argsort(x: Array) -> Array:
    return jnp.argsort(jnp.asarray(x), stable=True).astype(jnp.int32)


def ascending_order(x: Array) -> Array:
    """Exact stable ascending order: bitwise equal to
    ``jnp.argsort(x, stable=True)`` (see comparator notes in the module
    docstring), at a fraction of the variadic-sort cost for large ``n``."""
    return _dispatch.call("ascending_order", x)


@_DESC.impl("radix")
def _descending_order_radix(x: Array) -> Array:
    return _ascending_order_radix(-jnp.asarray(x))


@_DESC.impl("argsort")
def _descending_order_argsort(x: Array) -> Array:
    return jnp.argsort(-jnp.asarray(x)).astype(jnp.int32)


def descending_order(x: Array) -> Array:
    """Exact replacement for ``jnp.argsort(-x)`` — the curve kernels'
    descending-score order.

    Negation happens in the INPUT dtype so every quirk of the argsort path
    is reproduced bitwise: float -0.0/NaN sign flips (collapsed by the key
    map exactly as the comparator collapses them) and integer INT_MIN
    wraparound.
    """
    return _dispatch.call("descending_order", x)


@_KEYORD.impl("argsort")
def _stable_key_order_argsort(keys: Array, num_buckets: int) -> Array:
    return jnp.argsort(jnp.asarray(keys), stable=True).astype(jnp.int32)


@_KEYORD.impl("radix")
def _stable_key_order_radix(keys: Array, num_buckets: int) -> Array:
    """Stable ascending order for integer keys in ``[0, num_buckets)`` —
    the counting-sort form used for retrieval query-id grouping. Equal to
    ``jnp.argsort(keys, stable=True)`` but needs only
    ``ceil(log2(num_buckets) / (32 - ceil(log2(n))))`` value-sort passes
    (one pass for every realistic query-id width).

    PRECONDITION: every key must lie in ``[0, num_buckets)``. The packed
    word keeps only the low ``ceil(log2(num_buckets))`` key bits, so
    out-of-range or negative keys wrap onto valid bucket ids and the result
    is a silently wrong permutation — clamp or mask first (as
    ``retrieval/base.py`` does). Checked eagerly; uncheckable under jit.
    """
    bits = max(1, int(num_buckets - 1).bit_length()) if num_buckets > 1 else 1
    if bits > 32:
        raise ValueError("stable_key_order supports key widths up to 32 bits")
    keys = jnp.asarray(keys)
    if not isinstance(keys, jax.core.Tracer) and keys.size:
        import numpy as np

        # one fetch for both bounds — two int() calls would each block
        kmin, kmax = (int(x) for x in np.asarray(jnp.stack([keys.min(), keys.max()])))
        if kmin < 0 or kmax >= num_buckets:
            raise ValueError(
                f"stable_key_order keys must be in [0, {num_buckets}), got "
                f"[{kmin}, {kmax}] — low-bit packing would wrap them onto "
                "other buckets and silently mis-sort"
            )
    word = (keys & ((1 << bits) - 1)).astype(jnp.uint32) if bits < 32 else keys.astype(jnp.uint32)
    return _radix_order_words([word], bits)


def stable_key_order(keys: Array, num_buckets: int) -> Array:
    """Stable ascending order for integer keys in ``[0, num_buckets)`` —
    the counting-sort form used for retrieval query-id grouping (see the
    ``radix`` impl above for the precondition and cost model)."""
    return _dispatch.call("stable_key_order", keys, num_buckets)


@_PART.impl("radix")
def _partition_order_radix(first: Array) -> Array:
    return _radix_order_words([(~jnp.asarray(first, bool)).astype(jnp.uint32)], 1)


@_PART.impl("argsort")
def _partition_order_argsort(first: Array) -> Array:
    return jnp.argsort(~jnp.asarray(first, bool), stable=True).astype(jnp.int32)


def partition_order(first: Array) -> Array:
    """Stable order with ``first``-flagged rows compacted to the front —
    the single-pass (1-bit bucket) replacement for
    ``jnp.argsort(~first, stable=True)`` boundary compactions."""
    return _dispatch.call("partition_order", first)


def inverse_permutation(perm: Array) -> Array:
    """Invert a permutation without a scatter: the inverse is the stable
    ascending order of the permutation's values (they are distinct), so one
    more packed pass does it. ``inverse_permutation(ascending_order(x))``
    equals ``jnp.argsort(jnp.argsort(x))`` — per-element ranks."""
    perm = jnp.asarray(perm)
    n = perm.shape[0]
    return _radix_order_words([perm.astype(jnp.uint32)], _index_bits(n))


def ascending_ranks(x: Array) -> Array:
    """Per-element stable ascending ranks — bitwise equal to
    ``jnp.argsort(jnp.argsort(x, axis=-1), axis=-1)`` on 1-D input (vmap
    for batches)."""
    return inverse_permutation(ascending_order(x))


# --------------------------------------------------------------------------
# Histogram pass (pass 1) + sharded exact ranks
# --------------------------------------------------------------------------

_HIST = _dispatch.register_op("histogram", default="xla")


@_HIST.impl("xla")
def _histogram_xla(bucket_ids: Array, num_buckets: int) -> Array:
    """Scatter-add histogram — XLA lowers it as a serialized write loop,
    which is still the right default off-TPU for large grids."""
    return jnp.zeros(num_buckets, jnp.int32).at[jnp.asarray(bucket_ids)].add(1)


def bucket_counts(
    scores: Array,
    lo: Array,
    hi: Array,
    num_buckets: int,
    valid: Optional[Array] = None,
) -> Tuple[Array, Array]:
    """Pass 1: per-bucket counts over a static quantization grid.

    Lower bucket ids hold HIGHER scores (descending-rank orientation).
    ``lo``/``hi`` are the FINITE score bounds; the layout appends dedicated
    edge buckets so an infinite outlier cannot poison the grid span for
    every row (the regression that motivated this: one ``+inf`` made
    ``hi - lo`` infinite and every bucket id ``floor(nan)``):

    - bucket ``0``: ``+inf`` scores (rank highest)
    - buckets ``1 .. num_buckets``: the finite grid, full resolution
    - bucket ``num_buckets + 1``: ``-inf`` scores
    - bucket ``num_buckets + 2``: overflow — valid ``nan`` scores together
      with invalid rows, exactly where the local sort's ``nan`` fill ties
      them (jax sorts nans last)

    Returns ``(counts, bucket_ids)`` with ``counts`` of shape
    ``(num_buckets + 3,)``.
    """
    scores = jnp.asarray(scores, jnp.float32)
    finite = jnp.isfinite(scores)
    # no-finite-scores edge: lo/hi come in as +inf/-inf; every row is
    # where-routed to an edge/overflow bucket, but the grid arithmetic must
    # still be finite (floor(inf/nan) -> int32 is XLA-UB even on dead lanes)
    lo = jnp.where(jnp.isfinite(lo), lo, jnp.float32(0))
    hi = jnp.where(jnp.isfinite(hi), hi, jnp.float32(0))
    span = jnp.maximum(hi - lo, jnp.float32(1e-30))
    # clamp into the grid: semantics-preserving (out-of-range values hit the
    # same edge buckets the id-clip below would give them) and it keeps
    # (hi - s) / span * num_buckets finite for huge invalid-but-finite
    # scores that would otherwise overflow float32 before the int32 cast
    s = jnp.clip(jnp.where(finite, scores, jnp.float32(0)), lo, hi)
    b = 1 + jnp.clip(
        jnp.floor((hi - s) / span * num_buckets).astype(jnp.int32), 0, num_buckets - 1
    )
    b = jnp.where(scores == jnp.inf, 0, b)
    b = jnp.where(scores == -jnp.inf, num_buckets + 1, b)
    b = jnp.where(jnp.isnan(scores), num_buckets + 2, b)
    if valid is not None:
        b = jnp.where(jnp.asarray(valid, bool), b, num_buckets + 2)
    # the dispatched histogram op: XLA scatter-add here, the pallas one-hot
    # accumulator (ops/pallas_kernels.py) on TPU / under interpret parity
    counts = _dispatch.call("histogram", b, num_buckets + 3)
    return counts, b


def sharded_descending_ranks(
    scores: Array,
    axis_name: str,
    num_buckets: int = 2048,
    valid: Optional[Array] = None,
) -> Tuple[Array, Array]:
    """Exact global descending ranks of per-device score shards under
    ``shard_map`` — one histogram collective instead of a gathered sort.

    Two small collectives total (vs gathering the raw scores): a 2-scalar
    ``pmax`` agreeing the quantization grid, then ONE ``all_gather`` of a
    fused per-device payload — the ``(num_buckets + 3,)`` histogram (finite
    grid plus the ``+inf``/``-inf``/overflow edge buckets) concatenated
    with the per-bucket min/max orderable keys that feed the ``resolved``
    collision check. Pass 2 assembles each local element's global rank as::

        global_bucket_offset[b]            # exclusive cumsum over buckets
        + device_prefix[b]                 # same-bucket counts, lower ranks
        + local_within_bucket_position     # local packed-radix order

    Global order is (score desc, device, local position): bit-identical to
    a stable ``argsort(-concat(shards))`` whenever every bucket holds at
    most one distinct score globally. The returned ``resolved`` bool says
    exactly that (via per-bucket pmin/pmax of the orderable key); with
    continuous scores in colliding buckets, ranks are still a valid
    permutation but only bucket-granular, and callers that need bit-exact
    ranks should fall back to the gathered path when ``~resolved``.
    Invalid rows rank after all valid rows.
    """
    scores = jnp.asarray(scores, jnp.float32)
    v = jnp.ones(scores.shape, bool) if valid is None else jnp.asarray(valid, bool)
    # grid bounds over FINITE valid scores only — an inf outlier must not
    # stretch the span to infinity (it gets a dedicated edge bucket instead)
    vf = v & jnp.isfinite(scores)
    local_lo = jnp.min(jnp.where(vf, scores, jnp.inf))
    local_hi = jnp.max(jnp.where(vf, scores, -jnp.inf))
    # one fused grid-agreement collective: pmax of (-lo, hi) == (-pmin(lo), pmax(hi))
    neg_lo, hi = jax.lax.pmax(jnp.stack([-local_lo, local_hi]), axis_name)
    lo = -neg_lo

    counts, b = bucket_counts(scores, lo, hi, num_buckets, valid=v)

    # per-bucket min/max orderable keys for the resolved collision check,
    # with the same nan fill as the local sort, so valid-nan rows and
    # invalid rows share one key (they genuinely tie, broken by position)
    # and the overflow bucket does not spuriously report a collision
    nb = num_buckets + 3
    key = _float32_ascending_word(jnp.where(v, -scores, jnp.nan))
    kmin = jnp.full(nb, jnp.uint32(_U32_MAX)).at[b].min(key)
    kmax = jnp.zeros(nb, jnp.uint32).at[b].max(key)

    # ONE fused histogram collective: counts + kmin + kmax ride a single
    # all_gather payload instead of three bucket-axis collectives
    payload = jnp.concatenate([counts.astype(jnp.uint32), kmin, kmax])
    gathered = jax.lax.all_gather(payload, axis_name)  # (D, 3 * (num_buckets + 3))
    counts_g = gathered[:, :nb].astype(counts.dtype)
    gmin = gathered[:, nb : 2 * nb].min(axis=0)
    gmax = gathered[:, 2 * nb :].max(axis=0)

    totals = counts_g.sum(axis=0)
    offsets = jnp.concatenate([jnp.zeros(1, totals.dtype), jnp.cumsum(totals)[:-1]])
    d = jax.lax.axis_index(axis_name)
    ndev = counts_g.shape[0]
    below = jnp.where(jnp.arange(ndev)[:, None] < d, counts_g, 0).sum(axis=0)

    # local within-bucket positions from the local full-resolution order:
    # rank among local same-bucket rows = local desc rank - bucket offset.
    # Invalid rows are NaN-filled so they sort strictly after every valid
    # score (even valid -inf), matching their overflow-bucket routing.
    order = descending_order(jnp.where(v, scores, jnp.nan))
    local_rank = inverse_permutation(order)
    local_offsets = jnp.concatenate(
        [jnp.zeros(1, counts.dtype), jnp.cumsum(counts)[:-1]]
    )
    within = local_rank - local_offsets[b]

    granks = (offsets[b] + below[b] + within).astype(jnp.int32)

    resolved = jnp.all((gmin == gmax) | (totals <= 1))
    return granks, resolved

"""Binned-key precompaction: the O(n)-bandwidth sketch batch pass.

``QuantileSketch.update`` must reduce an arbitrary batch to at most ``k``
items of weight ``2**level`` before folding it into the level cascade. The
reference formulation (``ops/compactor.py::precompact_batch``'s ``sort``
path) runs a full ``jnp.sort`` of the batch under XLA's *float* comparator
— measured 530 ms for 1M float32 rows on the CPU backend, ~90% of the
entire ``qsketch_update_ms`` wall (BASELINE.md).

This pass re-uses ``bucketed_rank``'s orderable-key grid instead: every
float32 maps through ``_float32_ascending_word`` onto a monotone uint32
"bucket id" at full 32-bit resolution — the same grid construction the
histogram-rank kernel bins with, including its edge handling (non-finite
and invalid rows route to the TOP key, exactly where the sort path's
``+inf`` fill ties them; ``-0.0``/denormals collapse onto ``+0.0``'s
bucket just as the XLA comparator collapses them when ordering). Binning
the batch by key is then a *value-only unsigned* sort — which XLA lowers
~6.4x cheaper than the NaN-aware float comparator (83 ms vs 530 ms at 1M
on this CPU; on ints the lowering is a branch-free radix-style loop, so
the pass is bandwidth-bound) — and the level-buffer-sized run the
compaction keeps costs ONE static gather: the alternating-pair halving
cascade is a pure index map, so all ``level`` rounds compose at trace time
into a single ``(<=k,)`` gather of the binned keys (`_halving_map`),
replacing the ~n gathered elements of the round-by-round chain.

Output contract: **bit-identical to the sort path** — same kept values at
the same slots, same count, same static level — except that ``-0.0`` and
float32 denormals canonicalize to ``+0.0`` (the key map is the XLA
comparator's own equivalence, so rank semantics are untouched; pinned in
``tests/ops/test_binning.py`` across adversarial distributions). The
bit-parity argument: element ``j`` of the compacted run is the sorted
batch at static position ``P(j)`` (the composed halving map), and
``j < m >> level`` implies every intermediate halving index stayed inside
its round's valid prefix, so the one-shot gather sees exactly the value
the round-by-round chain saw.
"""
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.ops import dispatch as _dispatch
from metrics_tpu.ops.bucketed_rank import _float32_ascending_word

Array = jax.Array

_INF = float("inf")
# past every finite key AND the +inf key (0xFF800000); equals the NaN key,
# where the sort path's invalid fill also lands (jax sorts NaNs last)
_INVALID_KEY = 0xFFFFFFFF


def key_to_float32(key: Array) -> Array:
    """Invert ``_float32_ascending_word``: monotone uint32 key -> float32.

    Only keys in the forward map's image appear here; the collapsed
    ``-0.0``/denormal keys invert to ``+0.0`` (canonicalization, see module
    docstring) and the ``0xFFFFFFFF`` invalid key inverts to a NaN."""
    key = jnp.asarray(key, jnp.uint32)
    neg = key < jnp.uint32(0x80000000)  # negative floats were stored as ~u
    u = jnp.where(neg, ~key, key & jnp.uint32(0x7FFFFFFF))
    return jax.lax.bitcast_convert_type(u, jnp.float32)


def halving_level(n: int, k: int) -> int:
    """Number of alternating-pair halving rounds pre-compaction applies to
    an ``n``-row batch (its output items' level / weight exponent) —
    :func:`halving_map`'s round count without materializing the O(n) index
    map (each round keeps ``count // 2`` items, so the count-only
    recurrence is exact). The ONE source of the level rule: callers that
    must predict the level (``QuantileSketchState.insert``'s oversized-
    batch split) share it with the map itself, so they can never
    diverge."""
    level = 0
    while n > k:
        n //= 2
        level += 1
    return level


def halving_map(n: int, k: int) -> Tuple[np.ndarray, int]:
    """Compose the alternating-pair halving rounds into one static index
    map: ``idx[j]`` is the sorted-batch position the ``j``-th kept item of
    ``precompact`` comes from, ``level`` the number of rounds (item weight
    ``2**level``, == ``halving_level(n, k)``). Pure numpy at trace time —
    the map depends only on the static batch size."""
    idx = np.arange(n, dtype=np.int64)
    level = halving_level(n, k)
    for _ in range(level):
        j = np.arange(idx.shape[0] // 2)
        idx = idx[2 * j + (j & 1)]
    return idx.astype(np.int32), level


_PRECOMPACT = _dispatch.register_op("sketch_precompact", default="binned")


@_PRECOMPACT.impl("binned")
def _precompact_binned(x: Array, valid: Array, k: int) -> Tuple[Array, Array, int]:
    """The binned-key pass (see module docstring). Same contract as the
    ``sort`` impl in ``ops/compactor.py``."""
    x = jnp.asarray(x, jnp.float32).reshape(-1)
    valid = jnp.broadcast_to(jnp.asarray(valid, bool).reshape(-1), x.shape)
    valid = valid & jnp.isfinite(x)
    keys = jnp.where(valid, _float32_ascending_word(x), jnp.uint32(_INVALID_KEY))
    m = jnp.sum(valid.astype(jnp.int32))
    binned = jnp.sort(keys)  # value-only unsigned binning pass
    idx, level = halving_map(x.shape[0], k)
    kept = key_to_float32(binned[jnp.asarray(idx)]) if idx.size else jnp.zeros((0,), jnp.float32)
    count = m >> level
    cur = jnp.where(jnp.arange(idx.shape[0]) < count, kept, _INF)
    return cur, count.astype(jnp.int32), level

"""Blockwise quantized sync transport: error-bounded low-bit wire codecs.

At multihost/DCN scale the sync wall is payload *bytes*: fused_sync already
packs a guarded collection into ≤2 all-reduces (``parallel/sync.py``), every
``AsyncSyncScheduler`` cycle re-ships the full state, and every fleet view
blob pickles raw fp32 — so the remaining lever is the width of each lane on
the wire. Per EQuARX (quantized all-reduce inside XLA) and DynamiQ
(compressed multi-hop all-reduce, PAPERS.md), this module provides opt-in
blockwise low-bit transport with *stated* worst-case error, registered as a
dispatched op so one resolution rule covers every customer::

    choice := programmatic argument   (fused_sync(transport=...),
                                       Metric(sync_transport=...),
                                       kernel_override(sync_transport=...))
            | METRICS_TPU_SYNC_TRANSPORT   ("exact" | "fp16" | "int8")
            | "exact"                       (the default)

An unknown choice warns ONCE and falls back to ``exact`` — a bad env var
degrades bytes, never correctness (the ``ops/dispatch.py`` contract).

**Block scheme.** A flat f32 vector is split into blocks of
``DEFAULT_BLOCK`` lanes; each block carries one f32 scale =
``max(|finite x|)`` over the block (floored at the smallest normal f32).

- ``int8``: finite lanes encode as ``round(x / scale * 126)`` clipped to
  ``[-126, 126]``; the three spare codes are NaN/±inf passthrough lanes
  (``-128`` → NaN, ``127`` → +inf, ``-127`` → −inf), reconstructed exactly.
  Worst-case absolute error per lane is ``scale / 252`` — i.e. relative to
  the block's absmax, at most ``1/252 ≈ 0.40%``. DENORMAL COLLAPSE: lanes
  below the smallest normal f32 (``2**-126``) may decode to exactly zero —
  XLA's flush-to-zero semantics can zero them before the scale is even
  computed — so the envelope for denormal lanes is "absolute error below
  ``2**-126``", far beneath any metric's meaningful precision (this is
  also the one regime where the jax and numpy twins may differ: both stay
  inside the envelope, numpy without FTZ quantizing, jax flushing).
  Single-lane blocks (scalar sum states) decode to within 2 ulp of their
  input — the lane is its own block absmax, so only the two f32 scale
  roundings remain. Wire cost: 1 byte/lane + 4 bytes/block scale (1.125
  B/lane at block 32, ~3.6× fewer bytes than f32).
- ``fp16``: lanes are normalized by the block scale and stored as float16
  (NaN/±inf are natively representable — ``x/scale`` of a non-finite lane
  stays non-finite). Per-lane relative error ≤ ``2**-10`` for lanes at
  least ``2**-14`` of the block absmax; smaller lanes have absolute error
  ≤ ``absmax * 2**-24`` (fp16 subnormal granularity). The WIRE dtype is
  int16: scale/tail lanes are bit patterns, and a float psum would quiet
  any lane that happens to form a signaling-NaN pattern — integer adds
  preserve every lane exactly. Wire cost: 2 bytes/lane + 4 bytes/block
  (~2× fewer bytes than f32).
- ``exact``: the identity codec — f32 in, f32 out, bit-identical. Every
  customer's behavior with transport ``exact`` is *the same code path* as
  before this layer existed (pinned in tests and the
  ``quantized_fused_step`` registry entry).

**Exact tails.** Lossless lanes (sketch level ``counts``/``n_seen``, any
counter riding a packed payload) NEVER quantize: ``encode(x, exact_tail=t)``
ships the last ``t`` lanes bit-exact (f32 bit patterns carried in wire-dtype
lanes), so transport quantization can only ever touch value lanes whose
error budget is already stated.

**Error composition.** A sketch's documented rank-error eps extends under
quantized transport to ``eps_total = eps_sketch + eps_transport``, where
``eps_transport`` is the rank mass a per-lane value perturbation of
``absmax/252`` (int8) / ``2**-10`` relative (fp16) can move — bounded by
the CDF's local density and pinned empirically by the property suite in
``tests/ops/test_quantize.py`` across adversarial distributions.

Both jax (in-graph, trace-safe, static shapes) and numpy (host-side: the
overlapped gather path and the fleet wire) implementations are provided and
kept bit-identical — the property suite asserts encode parity lane by lane.

Module import performs python work only beyond importing jax/numpy — no
jax calls, no device arrays (the hang-proof bootstrap contract,
``utilities/backend.py``).
"""
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.ops import dispatch

__all__ = [
    "DEFAULT_BLOCK",
    "MAX_CODE",
    "CODE_NAN",
    "CODE_POS_INF",
    "CODE_NEG_INF",
    "TINY_NORMAL",
    "INT8_REL_ERROR_BOUND",
    "FP16_REL_ERROR_BOUND",
    "MIN_HOST_QUANTIZE_SIZE",
    "TRANSPORTS",
    "WireCodec",
    "validate_transport",
    "resolve_codec",
    "blockwise_int8_encode_np",
    "blockwise_int8_decode_np",
    "host_encode",
    "host_decode",
    "wrap_gather_transport",
]

# 32-lane blocks: small enough that a block of a SORTED payload (each
# quantile-sketch level is a sorted run — the dominant quantized bytes)
# spans a narrow value range, so the absmax-relative error stays small
# relative to every lane in the block even on 50-decade-skewed streams;
# scale overhead is 4/32 = 12.5% (int8 ships 1.125 B/lane vs f32's 4)
DEFAULT_BLOCK = 32
MAX_CODE = 126  # finite int8 codes live in [-126, 126]
CODE_NAN = -128  # the three spare codes are the NaN/±inf passthrough lanes
CODE_POS_INF = 127
CODE_NEG_INF = -127
TINY_NORMAL = float(np.float32(2.0 ** -126))  # scale floor (denormal collapse)
# worst-case per-lane error bounds (module docstring derivations)
INT8_REL_ERROR_BOUND = 1.0 / (2 * MAX_CODE)  # |err| <= absmax_block / 252
FP16_REL_ERROR_BOUND = 2.0 ** -10  # |err| <= max(|x| * 2**-10, absmax * 2**-24)
# host-side gather leaves smaller than this ship exact: there is no byte win
# on tiny leaves and scalar aggregates (a MeanMetric value) keep full width
MIN_HOST_QUANTIZE_SIZE = 64

TRANSPORTS = ("exact", "fp16", "int8")


def _num_blocks(n: int, block: int) -> int:
    return -(-int(n) // int(block)) if n > 0 else 0


# --------------------------------------------------------------------------
# jax core (in-graph, static shapes — safe under jit / shard_map)
# --------------------------------------------------------------------------


def _split(x: Any, exact_tail: int):
    x = jnp.asarray(x, jnp.float32).reshape(-1)
    t = int(exact_tail)
    if not 0 <= t <= x.shape[0]:
        raise ValueError(f"exact_tail={t} out of range for a {x.shape[0]}-lane payload")
    return x[: x.shape[0] - t], x[x.shape[0] - t :]


def _block_scales(x2: Any):
    """Per-block f32 scale: max finite magnitude, floored at the smallest
    normal f32 (all-zero / all-special / denormal blocks get the floor)."""
    finite = jnp.isfinite(x2)
    absmax = jnp.max(jnp.where(finite, jnp.abs(x2), jnp.float32(0)), axis=1)
    return jnp.maximum(absmax, jnp.float32(TINY_NORMAL)), finite


def _blocked(head: Any, block: int):
    nb = _num_blocks(head.shape[0], block)
    return jnp.pad(head, (0, nb * block - head.shape[0])).reshape(nb, block), nb


def _int8_encode(x: Any, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> Any:
    head, tail = _split(x, exact_tail)
    x2, _nb = _blocked(head, block)
    scales, finite = _block_scales(x2)
    # specials are zeroed BEFORE the cast (int8-of-NaN is undefined), then
    # overwritten with their passthrough codes
    q = jnp.clip(
        jnp.round(jnp.where(finite, x2, jnp.float32(0)) / scales[:, None] * jnp.float32(MAX_CODE)),
        -MAX_CODE,
        MAX_CODE,
    ).astype(jnp.int8)
    q = jnp.where(jnp.isnan(x2), jnp.int8(CODE_NAN), q)
    q = jnp.where(x2 == jnp.inf, jnp.int8(CODE_POS_INF), q)
    q = jnp.where(x2 == -jnp.inf, jnp.int8(CODE_NEG_INF), q)
    return jnp.concatenate(
        [
            q.reshape(-1),
            jax.lax.bitcast_convert_type(scales, jnp.int8).reshape(-1),
            jax.lax.bitcast_convert_type(tail, jnp.int8).reshape(-1),
        ]
    )


def _int8_decode(wire: Any, n: int, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> Any:
    wire = jnp.asarray(wire, jnp.int8).reshape(-1)
    t = int(exact_tail)
    h = int(n) - t
    nb = _num_blocks(h, block)
    q = wire[: nb * block].reshape(nb, block)
    scales = jax.lax.bitcast_convert_type(
        wire[nb * block : nb * block + 4 * nb].reshape(nb, 4), jnp.float32
    )
    tail = jax.lax.bitcast_convert_type(
        wire[nb * block + 4 * nb : nb * block + 4 * nb + 4 * t].reshape(t, 4), jnp.float32
    )
    vals = q.astype(jnp.float32) * (scales[:, None] / jnp.float32(MAX_CODE))
    vals = jnp.where(q == CODE_NAN, jnp.float32(jnp.nan), vals)
    vals = jnp.where(q == CODE_POS_INF, jnp.float32(jnp.inf), vals)
    vals = jnp.where(q == CODE_NEG_INF, jnp.float32(-jnp.inf), vals)
    return jnp.concatenate([vals.reshape(-1)[:h], tail])


def _fp16_encode(x: Any, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> Any:
    # the WIRE dtype is int16, not float16: wire lanes carry f32 scale and
    # exact-tail BIT PATTERNS, and an fp16 psum would quiet any lane whose
    # half happens to be a signaling-NaN pattern (IEEE x+0.0 flips the
    # quiet bit), silently corrupting "bit-exact" scales/counters. Integer
    # adds are exact, so bitcasting the whole wire to s16 preserves every
    # lane through the scatter-psum (the int8 wire is integer already).
    head, tail = _split(x, exact_tail)
    x2, _nb = _blocked(head, block)
    scales, _finite = _block_scales(x2)
    h16 = (x2 / scales[:, None]).astype(jnp.float16)  # NaN/±inf pass natively
    return jax.lax.bitcast_convert_type(
        jnp.concatenate(
            [
                h16.reshape(-1),
                jax.lax.bitcast_convert_type(scales, jnp.float16).reshape(-1),
                jax.lax.bitcast_convert_type(tail, jnp.float16).reshape(-1),
            ]
        ),
        jnp.int16,
    )


def _fp16_decode(wire: Any, n: int, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> Any:
    wire = jax.lax.bitcast_convert_type(jnp.asarray(wire, jnp.int16).reshape(-1), jnp.float16)
    t = int(exact_tail)
    h = int(n) - t
    nb = _num_blocks(h, block)
    h16 = wire[: nb * block].reshape(nb, block)
    scales = jax.lax.bitcast_convert_type(
        wire[nb * block : nb * block + 2 * nb].reshape(nb, 2), jnp.float32
    )
    tail = jax.lax.bitcast_convert_type(
        wire[nb * block + 2 * nb : nb * block + 2 * nb + 2 * t].reshape(t, 2), jnp.float32
    )
    vals = h16.astype(jnp.float32) * scales[:, None]
    return jnp.concatenate([vals.reshape(-1)[:h], tail])


def _exact_encode(x: Any, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> Any:
    return jnp.asarray(x, jnp.float32).reshape(-1)


def _exact_decode(wire: Any, n: int, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> Any:
    return jnp.asarray(wire, jnp.float32).reshape(-1)


# --------------------------------------------------------------------------
# numpy twins (host side: overlapped gathers, fleet wire) — bit-identical
# to the jax core (pinned lane-by-lane in tests/ops/test_quantize.py)
# --------------------------------------------------------------------------


def _blocked_np(head: np.ndarray, block: int):
    """numpy twin of :func:`_blocked` (zero-pad to a block multiple)."""
    nb = _num_blocks(head.shape[0], block)
    x2 = np.zeros((nb, block), np.float32)
    x2.reshape(-1)[: head.shape[0]] = head
    return x2, nb


def _block_scales_np(x2: np.ndarray, nb: int):
    """numpy twin of :func:`_block_scales` — ONE definition of the scale
    rule per implementation, because the lane-by-lane jax/numpy parity pin
    would silently break if a floor or padding tweak missed a copy."""
    finite = np.isfinite(x2)
    absmax = (
        np.max(np.where(finite, np.abs(x2), np.float32(0)), axis=1)
        if nb
        else np.zeros((0,), np.float32)
    )
    return np.maximum(absmax, np.float32(TINY_NORMAL)).astype(np.float32), finite


def blockwise_int8_encode_np(x: Any, block: int = DEFAULT_BLOCK):
    """``(codes int8 (nb*block,), scales f32 (nb,))`` for a flat f32 vector
    — the piece the fleet wire stores per leaf (scales in the leaf header)."""
    x = np.asarray(x, np.float32).reshape(-1)
    x2, nb = _blocked_np(x, block)
    scales, finite = _block_scales_np(x2, nb)
    q = np.clip(
        np.round(np.where(finite, x2, np.float32(0)) / scales[:, None] * np.float32(MAX_CODE)),
        -MAX_CODE,
        MAX_CODE,
    ).astype(np.int8)
    q = np.where(np.isnan(x2), np.int8(CODE_NAN), q)
    q = np.where(x2 == np.inf, np.int8(CODE_POS_INF), q)
    q = np.where(x2 == -np.inf, np.int8(CODE_NEG_INF), q)
    return q.reshape(-1), scales


def blockwise_int8_decode_np(codes: Any, scales: Any, n: int, block: int = DEFAULT_BLOCK):
    codes = np.asarray(codes, np.int8).reshape(-1)
    scales = np.asarray(scales, np.float32).reshape(-1)
    nb = _num_blocks(n, block)
    q = codes[: nb * block].reshape(nb, block)
    vals = q.astype(np.float32) * (scales[:, None] / np.float32(MAX_CODE))
    vals = np.where(q == CODE_NAN, np.float32(np.nan), vals)
    vals = np.where(q == CODE_POS_INF, np.float32(np.inf), vals)
    vals = np.where(q == CODE_NEG_INF, np.float32(-np.inf), vals)
    return vals.reshape(-1)[: int(n)]


def _int8_encode_np(x: Any, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> np.ndarray:
    x = np.asarray(x, np.float32).reshape(-1)
    t = int(exact_tail)
    head, tail = x[: x.shape[0] - t], x[x.shape[0] - t :]
    codes, scales = blockwise_int8_encode_np(head, block)
    return np.concatenate([codes, scales.view(np.int8), tail.view(np.int8)])


def _int8_decode_np(wire: Any, n: int, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> np.ndarray:
    wire = np.asarray(wire, np.int8).reshape(-1)
    t = int(exact_tail)
    h = int(n) - t
    nb = _num_blocks(h, block)
    scales = wire[nb * block : nb * block + 4 * nb].view(np.float32)
    tail = wire[nb * block + 4 * nb : nb * block + 4 * nb + 4 * t].view(np.float32)
    head = blockwise_int8_decode_np(wire[: nb * block], scales, h, block)
    return np.concatenate([head, tail])


def _fp16_encode_np(x: Any, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> np.ndarray:
    x = np.asarray(x, np.float32).reshape(-1)
    t = int(exact_tail)
    head, tail = x[: x.shape[0] - t], x[x.shape[0] - t :]
    x2, nb = _blocked_np(head, block)
    scales, _finite = _block_scales_np(x2, nb)
    h16 = (x2 / scales[:, None]).astype(np.float16)
    # int16 wire: bit patterns, not fp16 arithmetic lanes (see _fp16_encode)
    return np.concatenate(
        [h16.reshape(-1), scales.view(np.float16), tail.view(np.float16)]
    ).view(np.int16)


def _fp16_decode_np(wire: Any, n: int, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> np.ndarray:
    wire = np.asarray(wire, np.int16).reshape(-1).view(np.float16)
    t = int(exact_tail)
    h = int(n) - t
    nb = _num_blocks(h, block)
    h16 = wire[: nb * block].reshape(nb, block)
    scales = wire[nb * block : nb * block + 2 * nb].view(np.float32)
    tail = wire[nb * block + 2 * nb : nb * block + 2 * nb + 2 * t].view(np.float32)
    vals = h16.astype(np.float32) * scales.reshape(-1, 1)
    return np.concatenate([vals.reshape(-1)[:h], tail])


def _exact_encode_np(x: Any, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> np.ndarray:
    return np.asarray(x, np.float32).reshape(-1)


def _exact_decode_np(wire: Any, n: int, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> np.ndarray:
    return np.asarray(wire, np.float32).reshape(-1)


# --------------------------------------------------------------------------
# the codec objects + dispatch registration
# --------------------------------------------------------------------------


class WireCodec(NamedTuple):
    """One named wire transport: paired jax / numpy encode+decode over a
    flat f32 payload with an optional bit-exact tail. Shapes are static
    functions of ``(n, exact_tail, block)`` so the jax pair is safe inside
    jit / shard_map."""

    name: str
    wire_dtype: Any  # jnp dtype of the in-graph wire (psum operand dtype)
    np_wire_dtype: Any
    lanes_per_scale: int  # wire lanes carrying one f32 block scale
    lanes_per_exact: int  # wire lanes carrying one bit-exact f32 tail lane
    encode: Callable  # (x, exact_tail=0, block=...) -> wire   (jax)
    decode: Callable  # (wire, n, exact_tail=0, block=...) -> f32 (jax)
    encode_np: Callable
    decode_np: Callable

    def wire_size(self, n: int, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> int:
        if self.name == "exact":
            return int(n)
        nb = _num_blocks(int(n) - int(exact_tail), block)
        return nb * block + self.lanes_per_scale * nb + self.lanes_per_exact * int(exact_tail)

    def wire_bytes(self, n: int, exact_tail: int = 0, block: int = DEFAULT_BLOCK) -> int:
        return self.wire_size(n, exact_tail, block) * np.dtype(self.np_wire_dtype).itemsize


EXACT_CODEC = WireCodec(
    name="exact",
    wire_dtype=jnp.float32,
    np_wire_dtype=np.float32,
    lanes_per_scale=0,
    lanes_per_exact=1,
    encode=_exact_encode,
    decode=_exact_decode,
    encode_np=_exact_encode_np,
    decode_np=_exact_decode_np,
)

FP16_CODEC = WireCodec(
    name="fp16",
    # int16, not float16: the wire carries bit patterns (half payload lanes
    # + bitcast f32 scales/tails), and only integer psum lanes are immune
    # to IEEE NaN-quieting — see _fp16_encode
    wire_dtype=jnp.int16,
    np_wire_dtype=np.int16,
    lanes_per_scale=2,
    lanes_per_exact=2,
    encode=_fp16_encode,
    decode=_fp16_decode,
    encode_np=_fp16_encode_np,
    decode_np=_fp16_decode_np,
)

INT8_CODEC = WireCodec(
    name="int8",
    wire_dtype=jnp.int8,
    np_wire_dtype=np.int8,
    lanes_per_scale=4,
    lanes_per_exact=4,
    encode=_int8_encode,
    decode=_int8_decode,
    encode_np=_int8_encode_np,
    decode_np=_int8_decode_np,
)

# the dispatched op: one resolution rule (programmatic > METRICS_TPU_SYNC_
# TRANSPORT > exact) shared by fused_sync, the overlapped metric cycle,
# ServeLoop's background reduce, and anything else that moves state bytes
_OP = dispatch.register_op("sync_transport", default="exact", env_var="METRICS_TPU_SYNC_TRANSPORT")
_OP.impl("exact")(lambda: EXACT_CODEC)
_OP.impl("fp16")(lambda: FP16_CODEC)
_OP.impl("int8")(lambda: INT8_CODEC)


def validate_transport(name: Optional[str]) -> Optional[str]:
    """Raise on unknown PROGRAMMATIC transport names (``None`` passes —
    it means "resolve the env-backed default"). Ctor typos are code bugs
    and raise eagerly; env-var typos get the warn-once fallback instead.
    The one definition every `sync_transport=` constructor shares."""
    if name is not None and name not in TRANSPORTS:
        raise ValueError(f"`sync_transport` must be one of {TRANSPORTS}, got {name!r}")
    return name


def resolve_codec(choice: Optional[str] = None) -> WireCodec:
    """The one entry point customers resolve their transport through.

    ``choice=None`` follows the dispatch rule (override > env > ``exact``);
    a concrete name forces that codec for this call with the env-forced
    stance (unknown names warn once and fall back to ``exact``). Resolution
    happens at call time — trace time under jit, so the choice is baked
    into the compiled graph like every other ``METRICS_TPU_*`` perf knob.
    """
    if choice is None:
        return dispatch.call("sync_transport")
    return dispatch.call_as("sync_transport", str(choice))


# --------------------------------------------------------------------------
# host wire (self-describing: one int32 length header bit-carried in wire
# lanes, so ragged per-rank rows — e.g. pre-concat 'cat' states — decode
# without out-of-band shape)
# --------------------------------------------------------------------------


def host_encode(arr: Any, codec: WireCodec, block: int = DEFAULT_BLOCK) -> np.ndarray:
    """One host array -> a self-describing 1-D wire (numpy)."""
    flat = np.asarray(arr, np.float32).reshape(-1)
    header = np.asarray([flat.shape[0]], np.int32).view(codec.np_wire_dtype)
    return np.concatenate([header, codec.encode_np(flat, 0, block)])


def host_decode(wire: Any, codec: WireCodec, block: int = DEFAULT_BLOCK) -> np.ndarray:
    """Inverse of :func:`host_encode` -> flat f32 values."""
    wire = np.asarray(wire, codec.np_wire_dtype).reshape(-1)
    lanes = np.dtype(np.int32).itemsize // np.dtype(codec.np_wire_dtype).itemsize
    n = int(wire[:lanes].view(np.int32)[0])
    return codec.decode_np(wire[lanes:], n, 0, block)


def wrap_gather_transport(gather: Callable, codec: WireCodec) -> Callable:
    """Wrap a process-level gather (``dist_sync_fn`` signature:
    ``(array, group=None) -> [per-rank arrays]``) so floating leaves ship
    as the codec's wire and decode per rank.

    Integer / bool leaves (counters, CountMin counts, HLL registers,
    CatBuffer masks) ALWAYS bypass — lossless paths stay lossless — as do
    floating leaves smaller than :data:`MIN_HOST_QUANTIZE_SIZE` (scalar
    aggregates keep full width; there is no byte win on tiny leaves). The
    wire is self-describing (:func:`host_encode`), so ragged per-rank rows
    — different 'cat' lengths per rank — decode correctly.
    """
    if codec.name == "exact":
        return gather

    def quantized_gather(x: Any, group: Any = None) -> Any:
        arr = np.asarray(x)
        # f64 leaves bypass too: the wire is f32-based, so squeezing a
        # float64 accumulator through it would silently destroy values
        # beyond f32 range/precision — outside the documented envelope
        if (
            arr.dtype not in (np.float32, np.float16)
            or arr.size < MIN_HOST_QUANTIZE_SIZE
        ):
            return gather(x, group)
        # rows may be RAGGED in the leading axis (pre-concat 'cat' states);
        # trailing dims are config-fixed, so each row reshapes to (-1, *rest)
        trailing = arr.shape[1:]
        rows = gather(host_encode(arr, codec), group)
        return [
            jnp.asarray(
                host_decode(np.asarray(row), codec).astype(arr.dtype).reshape((-1,) + trailing)
            )
            for row in rows
        ]

    return quantized_gather

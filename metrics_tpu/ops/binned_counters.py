"""Pallas kernel for binned precision/recall counter updates.

The binned curve metrics (``classification/binned_precision_recall.py``)
accumulate TP/FP/FN per (class, threshold). The straightforward XLA update
builds the full ``(N, C, T)`` comparison tensor in HBM — at the default
T=100 thresholds that is ~100x the input size of pure memory traffic. This
kernel tiles the batch: each grid step compares one ``(TILE_N, C)`` block
against all thresholds inside VMEM and accumulates straight into the
``(C, T)`` counters, so HBM sees only the inputs once and the counters once.

Off-TPU the same kernel runs in pallas interpret mode (slow, correct), which
is how the CPU test suite checks parity against the XLA path. Impl
selection goes through the dispatched ``binned_counters`` op
(``ops/dispatch.py``): ``auto`` picks the kernel on TPU and the
straightforward XLA reduction elsewhere; ``METRICS_TPU_KERNEL_BACKEND``
overrides per-op.
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from metrics_tpu.ops import dispatch as _dispatch

Array = jax.Array

_TILE_N = 256


def _counter_kernel(preds_ref, tgt_ref, thr_ref, tps_ref, fps_ref, fns_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        tps_ref[:] = jnp.zeros_like(tps_ref)
        fps_ref[:] = jnp.zeros_like(fps_ref)
        fns_ref[:] = jnp.zeros_like(fns_ref)

    p = preds_ref[:]  # (TILE_N, C)
    t = tgt_ref[:]  # (TILE_N, C) in {0, 1}
    thr = thr_ref[:]  # (1, T)
    ge = (p[:, :, None] >= thr[0][None, None, :]).astype(jnp.float32)  # (TILE_N, C, T)
    t3 = t[:, :, None]
    tps_ref[:] += jnp.sum(t3 * ge, axis=0)
    fps_ref[:] += jnp.sum((1.0 - t3) * ge, axis=0)
    fns_ref[:] += jnp.sum(t3 * (1.0 - ge), axis=0)


_BINNED = _dispatch.register_op("binned_counters", default="xla")


@_BINNED.impl("xla")
def _binned_counter_xla(preds: Array, target_onehot: Array, thresholds: Array):
    """The straightforward XLA form: materializes the ``(N, C, T)``
    comparison tensor (what the pallas kernel exists to avoid)."""
    tgt = (target_onehot == 1)[..., None]  # (N, C, 1)
    pred = preds[..., None] >= thresholds  # (N, C, T)
    tps = jnp.sum(tgt & pred, axis=0).astype(jnp.float32)
    fps = jnp.sum((~tgt) & pred, axis=0).astype(jnp.float32)
    fns = jnp.sum(tgt & (~pred), axis=0).astype(jnp.float32)
    return tps, fps, fns


def _binned_pallas_guard(*args, **kwargs):
    from metrics_tpu.ops.pallas_kernels import _pallas_guard

    return _pallas_guard()


@_BINNED.impl("pallas", guard=_binned_pallas_guard)
def _binned_counter_pallas(preds: Array, target_onehot: Array, thresholds: Array):
    return _binned_counter_kernel_call(preds, target_onehot, thresholds, interpret=False)


@_BINNED.impl("pallas-interpret")
def _binned_counter_pallas_interpret(preds: Array, target_onehot: Array, thresholds: Array):
    return _binned_counter_kernel_call(preds, target_onehot, thresholds, interpret=True)


@_BINNED.auto_rule
def _binned_auto(*args, **kwargs) -> str:
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def binned_counter_update(
    preds: Array,
    target_onehot: Array,
    thresholds: Array,
    interpret: Optional[bool] = None,
    backend: Optional[str] = None,
):
    """TP/FP/FN counts per (class, threshold) for one batch — dispatched
    (op ``binned_counters``: pallas on TPU, XLA elsewhere, overridable via
    ``METRICS_TPU_KERNEL_BACKEND``).

    Args:
        preds: ``(N, C)`` scores.
        target_onehot: ``(N, C)`` 0/1 ground truth.
        thresholds: ``(T,)`` decision thresholds.
        interpret: legacy knob — ``True`` forces the pallas interpreter,
            ``False`` the compiled pallas kernel; ``None`` defers to the
            dispatch layer.
        backend: explicit impl name (``xla | pallas | pallas-interpret``);
            wins over ``interpret``.

    Returns:
        ``(tps, fps, fns)`` — each ``(C, T)`` float32.
    """
    if backend is None and interpret is not None:
        backend = "pallas-interpret" if interpret else "pallas"
    if backend is not None:
        # per-call force: call_as, NOT the shared override table — this is
        # a library hot path and must stay reentrant/thread-safe
        return _dispatch.call_as("binned_counters", backend, preds, target_onehot, thresholds)
    return _dispatch.call("binned_counters", preds, target_onehot, thresholds)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _binned_counter_kernel_call(
    preds: Array, target_onehot: Array, thresholds: Array, interpret: bool = False
):
    n, num_classes = preds.shape
    num_thr = thresholds.shape[0]
    if n == 0:
        # an empty grid never runs the kernel body, leaving pallas output
        # buffers undefined — the correct result is simply all-zero counters
        zero = jnp.zeros((num_classes, num_thr), jnp.float32)
        return zero, zero, zero
    pad = (-n) % _TILE_N
    if pad:
        # -inf scores never clear any threshold and a zero target adds
        # nothing to TP/FN: padded rows are exact no-ops
        preds = jnp.concatenate([preds, jnp.full((pad, num_classes), -jnp.inf, preds.dtype)])
        target_onehot = jnp.concatenate([target_onehot, jnp.zeros((pad, num_classes), target_onehot.dtype)])
    grid = preds.shape[0] // _TILE_N

    out_shape = jax.ShapeDtypeStruct((num_classes, num_thr), jnp.float32)
    tps, fps, fns = pl.pallas_call(
        _counter_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_TILE_N, num_classes), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_N, num_classes), lambda i: (i, 0)),
            pl.BlockSpec((1, num_thr), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((num_classes, num_thr), lambda i: (0, 0)),
            pl.BlockSpec((num_classes, num_thr), lambda i: (0, 0)),
            pl.BlockSpec((num_classes, num_thr), lambda i: (0, 0)),
        ],
        out_shape=[out_shape, out_shape, out_shape],
        interpret=interpret,
    )(
        preds.astype(jnp.float32),
        target_onehot.astype(jnp.float32),
        thresholds.astype(jnp.float32).reshape(1, -1),
    )
    return tps, fps, fns

"""Pallas kernel for binned precision/recall counter updates.

The binned curve metrics (``classification/binned_precision_recall.py``)
accumulate TP/FP/FN per (class, threshold). The straightforward XLA update
builds the full ``(N, C, T)`` comparison tensor in HBM — at the default
T=100 thresholds that is ~100x the input size of pure memory traffic. This
kernel tiles the batch: each grid step compares one ``(TILE_N, C)`` block
against all thresholds inside VMEM and accumulates straight into the
``(C, T)`` counters, so HBM sees only the inputs once and the counters once.

Off-TPU the same kernel runs in pallas interpret mode (slow, correct), which
is how the CPU test suite checks parity against the XLA path.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array

_TILE_N = 256


def _counter_kernel(preds_ref, tgt_ref, thr_ref, tps_ref, fps_ref, fns_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        tps_ref[:] = jnp.zeros_like(tps_ref)
        fps_ref[:] = jnp.zeros_like(fps_ref)
        fns_ref[:] = jnp.zeros_like(fns_ref)

    p = preds_ref[:]  # (TILE_N, C)
    t = tgt_ref[:]  # (TILE_N, C) in {0, 1}
    thr = thr_ref[:]  # (1, T)
    ge = (p[:, :, None] >= thr[0][None, None, :]).astype(jnp.float32)  # (TILE_N, C, T)
    t3 = t[:, :, None]
    tps_ref[:] += jnp.sum(t3 * ge, axis=0)
    fps_ref[:] += jnp.sum((1.0 - t3) * ge, axis=0)
    fns_ref[:] += jnp.sum(t3 * (1.0 - ge), axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def binned_counter_update(preds: Array, target_onehot: Array, thresholds: Array, interpret: bool = False):
    """TP/FP/FN counts per (class, threshold) for one batch.

    Args:
        preds: ``(N, C)`` scores.
        target_onehot: ``(N, C)`` 0/1 ground truth.
        thresholds: ``(T,)`` decision thresholds.
        interpret: run the pallas interpreter (required off-TPU).

    Returns:
        ``(tps, fps, fns)`` — each ``(C, T)`` float32.
    """
    n, num_classes = preds.shape
    num_thr = thresholds.shape[0]
    if n == 0:
        # an empty grid never runs the kernel body, leaving pallas output
        # buffers undefined — the correct result is simply all-zero counters
        zero = jnp.zeros((num_classes, num_thr), jnp.float32)
        return zero, zero, zero
    pad = (-n) % _TILE_N
    if pad:
        # -inf scores never clear any threshold and a zero target adds
        # nothing to TP/FN: padded rows are exact no-ops
        preds = jnp.concatenate([preds, jnp.full((pad, num_classes), -jnp.inf, preds.dtype)])
        target_onehot = jnp.concatenate([target_onehot, jnp.zeros((pad, num_classes), target_onehot.dtype)])
    grid = preds.shape[0] // _TILE_N

    out_shape = jax.ShapeDtypeStruct((num_classes, num_thr), jnp.float32)
    tps, fps, fns = pl.pallas_call(
        _counter_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_TILE_N, num_classes), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_N, num_classes), lambda i: (i, 0)),
            pl.BlockSpec((1, num_thr), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((num_classes, num_thr), lambda i: (0, 0)),
            pl.BlockSpec((num_classes, num_thr), lambda i: (0, 0)),
            pl.BlockSpec((num_classes, num_thr), lambda i: (0, 0)),
        ],
        out_shape=[out_shape, out_shape, out_shape],
        interpret=interpret,
    )(
        preds.astype(jnp.float32),
        target_onehot.astype(jnp.float32),
        thresholds.astype(jnp.float32).reshape(1, -1),
    )
    return tps, fps, fns

"""Pallas TPU kernels for the radix-histogram and compactor-fold inner loops.

Two hot inner loops get hand kernels here, both behind the ``ops/dispatch``
switch (``auto`` selects them on TPU, the XLA paths everywhere else; the
``pallas-interpret`` impls run the SAME kernel bodies through the pallas
interpreter, which is how the CPU test suite pins bit parity —
``tests/ops/test_pallas_kernels.py``):

- **histogram** — per-bucket counts of integer bucket ids (pass 1 of
  ``bucketed_rank.sharded_descending_ranks`` and any grid binning). XLA
  lowers the ``.at[b].add(1)`` scatter as a serialized loop of random
  writes (measured ~119 ms for 1M rows on CPU — slower than sorting the
  ids); on TPU the scatter lowering is similarly serial. The kernel
  instead streams row tiles through VMEM and accumulates a one-hot
  compare against the bucket lanes with the VPU — ``num_buckets`` extra
  compares per element, traded for zero serialized writes, which is the
  right trade for the modest grids the rank kernels use (the ``auto``
  rule caps it at ``num_buckets <= 8192``).

- **compactor_fold** — the post-sort compact/select stage of a sketch
  level fold (``ops/compactor.py::fold_level``): alternating-pair picks,
  odd-leftover extraction, overflow select. Pure bandwidth; XLA
  materializes each ``where``/gather as its own HBM pass, the kernel
  fuses them into one VMEM-resident block.

Both kernels follow the in-repo pallas idiom (``ops/binned_counters.py``):
grid accumulation via an output block revisited per step, ``pl.when`` for
first-step init. Native-TPU numbers are pending the next TPU window
(TPU_STATUS.md); everything here is exercised in interpret mode on CPU.
"""
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from metrics_tpu.ops import dispatch as _dispatch

Array = jax.Array

_INF = float("inf")
_HIST_TILE_ROWS = 4  # 4 x 128 ids per grid step (keeps the one-hot in VMEM)
_PALLAS_MAX_BUCKETS = 8192


def _pallas_guard(*args, **kwargs):
    """Shared impl guard: the compiled kernels need a real TPU."""
    if jax.default_backend() != "tpu":
        return (
            "pallas kernels compile only on the TPU backend; use "
            "'pallas-interpret' for the (slow) interpreter"
        )
    return None


# --------------------------------------------------------------------------
# histogram
# --------------------------------------------------------------------------


def _histogram_kernel(ids_ref, out_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    ids = ids_ref[:].reshape(-1, 1)  # (TILE_ROWS * 128, 1)
    buckets = jax.lax.broadcasted_iota(jnp.int32, (1, out_ref.shape[1]), 1)
    out_ref[:] += jnp.sum((ids == buckets).astype(jnp.int32), axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("num_buckets", "interpret"))
def histogram_pallas(bucket_ids: Array, num_buckets: int, interpret: bool = False) -> Array:
    """Per-bucket counts of ``bucket_ids`` over ``[0, num_buckets)``.

    PRECONDITION (same stance as ``stable_key_order``): ids outside
    ``[0, num_buckets)`` are silently not counted — callers produce
    clipped/edge-routed ids (``bucket_counts`` does).
    """
    ids = jnp.asarray(bucket_ids, jnp.int32).reshape(-1)
    n = ids.shape[0]
    if n == 0:
        # an empty grid never runs the kernel body (binned_counters.py)
        return jnp.zeros((num_buckets,), jnp.int32)
    tile = _HIST_TILE_ROWS * 128
    pad = (-n) % tile
    if pad:
        # the dump lane: one past the last real bucket, sliced off below
        ids = jnp.concatenate([ids, jnp.full((pad,), num_buckets, jnp.int32)])
    nb_pad = -(-(num_buckets + 1) // 128) * 128
    ids2 = ids.reshape(-1, 128)
    out = pl.pallas_call(
        _histogram_kernel,
        grid=(ids2.shape[0] // _HIST_TILE_ROWS,),
        in_specs=[pl.BlockSpec((_HIST_TILE_ROWS, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, nb_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, nb_pad), jnp.int32),
        interpret=interpret,
    )(ids2)
    return out[0, :num_buckets]


def _hist_shape_guard(bucket_ids, num_buckets, **kwargs):
    if num_buckets > _PALLAS_MAX_BUCKETS:
        return (
            f"histogram kernel supports up to {_PALLAS_MAX_BUCKETS} buckets "
            f"(one-hot lanes must fit VMEM), got {num_buckets}"
        )
    return None


def _hist_pallas_guard(bucket_ids, num_buckets, **kwargs):
    return _pallas_guard() or _hist_shape_guard(bucket_ids, num_buckets)


_HIST = _dispatch.register_op("histogram", default="xla")


@_HIST.impl("pallas", guard=_hist_pallas_guard)
def _histogram_pallas_native(bucket_ids: Array, num_buckets: int) -> Array:
    return histogram_pallas(bucket_ids, num_buckets, interpret=False)


@_HIST.impl("pallas-interpret", guard=_hist_shape_guard)
def _histogram_pallas_interpret(bucket_ids: Array, num_buckets: int) -> Array:
    return histogram_pallas(bucket_ids, num_buckets, interpret=True)


@_HIST.auto_rule
def _histogram_auto(bucket_ids, num_buckets, **kwargs) -> str:
    if jax.default_backend() == "tpu" and num_buckets <= _PALLAS_MAX_BUCKETS:
        return "pallas"
    return "xla"


# --------------------------------------------------------------------------
# compactor fold
# --------------------------------------------------------------------------


def _make_fold_kernel(k: int, total: int, p_pad: int, k_pad: int):
    def _fold_kernel(comb_ref, cnt_ref, items_ref, count_ref, prom_ref, pcount_ref):
        comb = comb_ref[:]  # (1, P) sorted, +inf beyond the real total
        c = cnt_ref[0, 0]
        overflow = c > k
        pairs = c // 2
        # alternating-pair pick: one survivor per adjacent sorted pair
        two = comb.reshape(-1, 2)  # (P // 2, 2)
        j = jax.lax.broadcasted_iota(jnp.int32, (1, two.shape[0]), 1)
        picked = jnp.where(
            (j & 1) == 1, two[:, 1].reshape(1, -1), two[:, 0].reshape(1, -1)
        )
        prom = jnp.where((j < pairs) & overflow, picked, _INF)
        # odd leftover: the single element at position 2 * pairs (one-hot
        # select — buffers hold finite values or +inf padding only)
        pos = jax.lax.broadcasted_iota(jnp.int32, (1, total), 1)
        leftover_count = c - 2 * pairs
        leftover_val = jnp.sum(jnp.where(pos == 2 * pairs, comb[:, :total], 0.0))
        kidx = jax.lax.broadcasted_iota(jnp.int32, (1, k_pad), 1)
        leftover_row = jnp.where(kidx < leftover_count, leftover_val, _INF)
        keep_row = jnp.where(kidx < k, comb[:, :k_pad], _INF)
        items_ref[:] = jnp.where(overflow, leftover_row, keep_row)
        count_ref[0, 0] = jnp.where(overflow, leftover_count, c)
        prom_ref[:] = prom[:, :p_pad]
        pcount_ref[0, 0] = jnp.where(overflow, pairs, 0)

    return _fold_kernel


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def compactor_fold_pallas(
    combined: Array, c: Array, k: int, interpret: bool = False
) -> Tuple[Array, Array, Array, Array]:
    """Pallas form of the fold's post-sort stage — same contract as the
    ``xla`` impl in ``ops/compactor.py`` (``combined`` is the sorted
    ``(k + M,)`` concatenation, ``c`` the combined valid count)."""
    total = combined.shape[0]
    p_len = total // 2
    pad128 = lambda v: max(128, -(-v // 128) * 128)  # noqa: E731
    k_pad, p_pad = pad128(k), pad128(p_len)
    # the kernel reshapes (1, P) -> (P//2, 2) and writes (1, p_pad)/(1, k_pad)
    # slices of it, so P must cover both
    P = max(pad128(total + (total % 2)), 2 * p_pad, k_pad)
    comb = jnp.full((1, P), _INF, jnp.float32).at[0, :total].set(
        jnp.asarray(combined, jnp.float32)
    )
    cnt = jnp.asarray(c, jnp.int32).reshape(1, 1)
    items, count, prom, pcount = pl.pallas_call(
        _make_fold_kernel(k, total, p_pad, k_pad),
        grid=(1,),
        in_specs=[
            pl.BlockSpec((1, P), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, k_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, p_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, p_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        interpret=interpret,
    )(comb, cnt)
    return (
        items[0, :k],
        count[0, 0],
        prom[0, :p_len],
        pcount[0, 0],
    )


_FOLD = _dispatch.register_op("compactor_fold", default="xla")


@_FOLD.impl("pallas", guard=lambda *a, **k: _pallas_guard())
def _compactor_fold_pallas_native(combined, c, k):
    return compactor_fold_pallas(combined, c, k, interpret=False)


@_FOLD.impl("pallas-interpret")
def _compactor_fold_pallas_interpret(combined, c, k):
    return compactor_fold_pallas(combined, c, k, interpret=True)


@_FOLD.auto_rule
def _compactor_fold_auto(combined, c, k, **kwargs) -> str:
    return "pallas" if jax.default_backend() == "tpu" else "xla"

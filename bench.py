"""Benchmark: fused MetricCollection step (update + compute) on one chip.

Headline number tracked against the BASELINE.md north star: the reference's
target is a ``MetricCollection([Accuracy, F1, ...]).compute()`` under 2 ms
(BASELINE.json; the reference itself publishes no absolute numbers — see
BASELINE.md). ``vs_baseline`` is the speedup vs that 2 ms budget (>1 = faster
than target).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Robustness: round 1 emitted no number because the environment-pinned ``axon``
TPU backend died during init; a later run showed init can also *hang*
indefinitely. So the backend is probed in a subprocess with a hard timeout
(a hang can't be cancelled once it's in-process), retried, and on failure the
bench falls back to CPU — a number always lands, and the JSON unit string
records which platform produced it.
"""
import json
import os
import subprocess
import sys
import time

_PROBE_SRC = "import jax; print(jax.devices()[0].platform)"


def _probe_default_backend(timeout_s: float = 150.0, attempts: int = 2):
    """Check, in a throwaway subprocess, that the default backend comes up.

    A *hang* (timeout) forces the CPU fallback immediately: a backend that
    hung once can hang again in-process, where nothing can cancel it and no
    JSON line would ever be emitted. Only clean-but-failed probes are retried.
    """
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
        except subprocess.TimeoutExpired:
            print(f"bench: backend probe hung >{timeout_s}s; not retrying", file=sys.stderr)
            return None
        if proc.returncode == 0 and proc.stdout.strip():
            return proc.stdout.strip().splitlines()[-1]  # plugin chatter may precede it
        print(
            f"bench: backend probe attempt {attempt + 1} failed rc={proc.returncode}: "
            f"{proc.stderr.strip()[-500:]}",
            file=sys.stderr,
        )
    return None


def _init_backend():
    platform = _probe_default_backend()
    if platform is None:
        print("bench: default backend unusable; falling back to CPU", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if platform is None:
        from metrics_tpu.utilities.backend import force_cpu_backend

        force_cpu_backend()
        platform = jax.devices()[0].platform
    return jax, platform


_SYNC_BENCH_SRC = """
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_num_cpu_devices', 8)
import time, numpy as np, jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from metrics_tpu.parallel.sync import fused_sync
mesh = Mesh(np.array(jax.devices()), ('data',))
state = {k: jnp.ones((16,), jnp.int32) for k in ('tp', 'fp', 'tn', 'fn')}
def sync_only(s):
    return fused_sync([s], [{k: 'sum' for k in s}], 'data')[0]
fn = jax.jit(jax.shard_map(sync_only, mesh=mesh, in_specs=(P(),), out_specs=P()))
out = fn(state); jax.block_until_ready(out)
iters = 200
t0 = time.perf_counter()
for _ in range(iters):
    out = fn(state)
jax.block_until_ready(out)
print((time.perf_counter() - t0) / iters * 1e6)
"""


def _emit(metric: str, value: float, unit: str, vs_baseline=None) -> None:
    print(json.dumps({"metric": metric, "value": value, "unit": unit, "vs_baseline": vs_baseline}))


def _bench_extras(jax, platform) -> None:
    """Secondary numbers (each its own JSON line; the headline stays last).

    Every block is independent and failure-isolated: a broken path loses one
    line, never the whole bench.
    """
    import numpy as np
    import jax.numpy as jnp

    # --- AUROC at 1M accumulated samples (CatBuffer capacity mode) -------
    try:
        from metrics_tpu import functionalize, AUROC

        n = 1_000_000
        mdef = functionalize(AUROC(capacity=n))
        rng = np.random.default_rng(0)
        batch_p = jnp.asarray(rng.random(n), jnp.float32)
        batch_t = jnp.asarray(rng.integers(0, 2, n), jnp.int32)
        state = jax.jit(mdef.update)(mdef.init(), batch_p, batch_t)
        compute = jax.jit(mdef.compute)
        jax.block_until_ready(compute(state))  # compile
        iters = 10
        t0 = time.perf_counter()
        for _ in range(iters):
            out = compute(state)
        jax.block_until_ready(out)
        _emit(
            "auroc_1m_compute_ms",
            round((time.perf_counter() - t0) / iters * 1e3, 4),
            f"ms/compute (exact rank-based AUROC, 1M samples, {platform})",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: auroc_1m failed: {err}", file=sys.stderr)

    # --- SSIM on 2x3x512x512 ---------------------------------------------
    try:
        from metrics_tpu.functional import structural_similarity_index_measure

        rng = np.random.default_rng(1)
        a = jnp.asarray(rng.random((2, 3, 512, 512)), jnp.float32)
        b = jnp.asarray(rng.random((2, 3, 512, 512)), jnp.float32)
        fn = jax.jit(lambda x, y: structural_similarity_index_measure(x, y, data_range=1.0))
        jax.block_until_ready(fn(a, b))
        iters = 20
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(a, b)
        jax.block_until_ready(out)
        _emit(
            "ssim_512_ms",
            round((time.perf_counter() - t0) / iters * 1e3, 4),
            f"ms (SSIM 2x3x512x512, {platform})",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: ssim_512 failed: {err}", file=sys.stderr)

    # --- retrieval: 100k ragged queries, bucketed vectorized compute -----
    try:
        from metrics_tpu import RetrievalMAP

        rng = np.random.default_rng(2)
        nq = 100_000
        sizes = rng.integers(5, 50, nq)
        idx = np.repeat(np.arange(nq), sizes)
        preds = rng.random(idx.size).astype(np.float32)
        target = (rng.random(idx.size) < 0.2).astype(np.int64)
        m = RetrievalMAP()
        m.update(preds, target, indexes=idx)
        t0 = time.perf_counter()
        m.compute()
        _emit(
            "retrieval_map_100k_s",
            round(time.perf_counter() - t0, 3),
            f"s/compute (100k ragged queries, {idx.size} docs, {platform})",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: retrieval_100k failed: {err}", file=sys.stderr)

    # --- fused-collection sync µs on a virtual 8-device mesh -------------
    # (BASELINE.md's tracked sync metric; real multi-chip is unavailable, so
    # this runs in a CPU-mesh subprocess — an upper bound on collective count,
    # not ICI latency)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SYNC_BENCH_SRC],
            timeout=300,
            capture_output=True,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        if proc.returncode == 0 and proc.stdout.strip():
            _emit(
                "fused_sync_us",
                round(float(proc.stdout.strip().splitlines()[-1]), 2),
                "us/sync (4-state fused psum, 8-device cpu mesh)",
            )
        else:
            print(f"bench: sync bench rc={proc.returncode}: {proc.stderr[-300:]}", file=sys.stderr)
    except Exception as err:  # pragma: no cover
        print(f"bench: sync bench failed: {err}", file=sys.stderr)


def main() -> None:
    jax, platform = _init_backend()
    import jax.numpy as jnp
    import numpy as np

    from __graft_entry__ import entry

    _bench_extras(jax, platform)

    step, (state, _, _) = entry()

    B, C = 8192, 16
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.random((B, C)), jnp.float32)
    target = jnp.asarray(rng.integers(0, C, B), jnp.int32)

    jit_step = jax.jit(step, donate_argnums=0)

    # warmup / compile
    state_w, metrics = jit_step(dict(state), preds, target)
    jax.block_until_ready(metrics)

    iters = 50
    st = state_w  # warmup donated `state`'s buffers; continue from its output
    start = time.perf_counter()
    for _ in range(iters):
        st, metrics = jit_step(st, preds, target)
    jax.block_until_ready(metrics)
    elapsed_ms = (time.perf_counter() - start) / iters * 1e3

    target_ms = 2.0  # BASELINE.md north-star budget for a fused collection step
    print(
        json.dumps(
            {
                "metric": "fused_collection_step_ms",
                "value": round(elapsed_ms, 4),
                "unit": f"ms/step (update+4-metric compute, B=8192, C=16, {platform})",
                "vs_baseline": round(target_ms / elapsed_ms, 2),
            }
        )
    )


if __name__ == "__main__":
    main()

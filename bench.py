"""Benchmark: fused MetricCollection step (update + compute) on one chip.

Headline number tracked against the BASELINE.md north star: the reference's
target is a ``MetricCollection([Accuracy, F1, ...]).compute()`` under 2 ms
(BASELINE.json; the reference itself publishes no absolute numbers — see
BASELINE.md). ``vs_baseline`` is the speedup vs that 2 ms budget (>1 = faster
than target).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    from __graft_entry__ import entry

    step, (state, _, _) = entry()

    B, C = 8192, 16
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.random((B, C)), jnp.float32)
    target = jnp.asarray(rng.integers(0, C, B), jnp.int32)

    jit_step = jax.jit(step, donate_argnums=0)

    # warmup / compile
    state_w, metrics = jit_step(dict(state), preds, target)
    jax.block_until_ready(metrics)

    iters = 50
    st = state_w  # warmup donated `state`'s buffers; continue from its output
    start = time.perf_counter()
    for _ in range(iters):
        st, metrics = jit_step(st, preds, target)
    jax.block_until_ready(metrics)
    elapsed_ms = (time.perf_counter() - start) / iters * 1e3

    target_ms = 2.0  # BASELINE.md north-star budget for a fused collection step
    print(
        json.dumps(
            {
                "metric": "fused_collection_step_ms",
                "value": round(elapsed_ms, 4),
                "unit": "ms/step (update+4-metric compute, B=8192, C=16)",
                "vs_baseline": round(target_ms / elapsed_ms, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
